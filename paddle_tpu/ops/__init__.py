"""paddle_tpu.ops — the functional op surface (the `_C_ops` analog).

Reference: ``python/paddle/_C_ops.py`` re-exporting generated per-op C
functions (``eager_op_function.cc``).  Here the ops are jax-backed OpDefs
(see registry.py); this module assembles the per-category modules and
installs the Tensor operator/method surface exactly like the reference's
monkey-patch layer (``python/paddle/base/dygraph/tensor_patch_methods.py``).
"""
from __future__ import annotations

from . import registry
from .registry import apply, get_op, register_op, all_ops  # noqa: F401

from . import math as math_ops  # noqa: E402
from . import reduction  # noqa: E402
from . import manipulation  # noqa: E402
from . import linalg  # noqa: E402
from . import creation  # noqa: E402
from . import random  # noqa: E402
from . import activation as activation_ops  # noqa: E402
from . import nn_ops  # noqa: E402
from . import nn_ops_nd  # noqa: E402

# --- re-export the flat functional namespace ------------------------------
from .math import (  # noqa: F401
    add, subtract, multiply, divide, pow, maximum, minimum, remainder, mod,
    floor_divide, floor_mod, fmax, fmin, logaddexp, atan2, gcd, lcm,
    bitwise_and, bitwise_or, bitwise_xor, left_shift, right_shift,
    exp, expm1, log, log2, log10, log1p, sqrt, rsqrt, square, abs, neg,
    negative, sign, floor, ceil, round_, trunc, frac, reciprocal, sin, cos,
    tan, asin, acos, atan, sinh, cosh, asinh, acosh, atanh, erf, erfinv,
    lgamma, digamma, bitwise_not, isnan_, isinf_, isfinite_, logical_not,
    logical_and, logical_or, logical_xor, equal, not_equal, greater_than,
    greater_equal, less_than, less_equal, clip, scale, lerp, stanh,
    nan_to_num, i0, rint,
)
from .reduction import (  # noqa: F401
    sum, mean, max, min, amax, amin, prod, any, all, logsumexp, argmax,
    argmin, cumsum, cumprod, cummax, cummin, var, std, numel, count_nonzero,
    nanmean, nansum, median, quantile,
)
from .manipulation import (  # noqa: F401
    cast, reshape, transpose, t, squeeze, unsqueeze, flatten, expand,
    broadcast_to, expand_as, broadcast_shape, tile, concat, stack, split,
    chunk, unstack, unbind, flip, roll, pad, gather, index_select,
    take_along_axis, put_along_axis, scatter, scatter_nd_add, gather_nd,
    where, nonzero, masked_select, masked_fill, topk, sort, argsort, unique,
    unique_consecutive, assign, tril, triu, diag, diagonal,
    repeat_interleave, one_hot, meshgrid, moveaxis, view, slice, getitem,
    setitem,
)
from .linalg import (  # noqa: F401
    matmul, mm, bmm, inner, dot, outer, addmm, einsum, norm, dist,
    triangular_solve, cholesky, inverse, det, slogdet, solve, svd, qr, eigh,
    matrix_power, pinv, matrix_rank, cross, histogram, bincount,
    lu, lu_unpack, cholesky_solve, eig, eigvals, eigvalsh, svdvals, cond,
    corrcoef, cov, lstsq, matrix_exp, multi_dot,
)
from .creation import (  # noqa: F401
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, diag_embed, clone, to_tensor, complex,
    as_complex, as_real,
)
from .extra import (  # noqa: F401
    kron, trace, heaviside, copysign, ldexp, hypot, deg2rad, rad2deg,
    positive, diff, trapezoid, vander, logcumsumexp, renorm, cdist,
    tensordot, bucketize, searchsorted, nanmedian, mode, kthvalue, rot90,
    take, index_add, index_fill, unfold, as_strided, select_scatter,
    slice_scatter, atleast_1d, atleast_2d, atleast_3d, column_stack,
    row_stack, dstack, tensor_split, hsplit, vsplit, dsplit, diagflat,
    index_put, index_put_,
)
from .random import (  # noqa: F401
    seed, get_rng_state, set_rng_state, randn, standard_normal, normal,
    gaussian, rand, uniform, randint, randint_like, randperm, bernoulli,
    poisson, multinomial, normal_, uniform_, exponential_, Generator,
    default_generator, bernoulli_, cauchy_, geometric_, log_normal_,
    log_normal, standard_gamma, binomial,
)
from .tail import (  # noqa: F401
    real, imag, conj, angle, isreal, isneginf, isposinf, signbit, sinc,
    nextafter, polar, sgn, logit, round, gammaln, gammainc, gammaincc,
    multigammaln, i0e, i1, i1e, polygamma, hstack, vstack, block_diag,
    add_n, cartesian_prod, combinations, reverse, crop, unflatten,
    view_as, strided_slice, scatter_nd, diagonal_scatter,
    masked_scatter, index_sample, multiplex, shard_index, reduce_as,
    isin, tril_indices, triu_indices, shape, is_empty, is_integer,
    is_complex, is_floating_point, nanquantile, pdist, histogramdd,
    cumulative_trapezoid, mv, vecdot, householder_product, geqrf,
    ormqr, cholesky_inverse, frexp, bitwise_left_shift,
    bitwise_right_shift,
)
from .lowrank import (  # noqa: F401
    create_tensor, fp8_fp8_half_gemm_fused, histogram_bin_edges,
    matrix_norm, pca_lowrank, svd_lowrank, top_p_sampling, vector_norm,
)

import builtins as _bi  # noqa: E402

from ..core.tensor import Tensor  # noqa: E402


# --- activations (functional) ---------------------------------------------

def relu(x, name=None):
    return apply(activation_ops.relu_op, x)


def relu6(x, name=None):
    return apply(activation_ops.relu6_op, x)


def sigmoid(x, name=None):
    return apply(activation_ops.sigmoid_op, x)


def tanh(x, name=None):
    return apply(activation_ops.tanh_op, x)


def silu(x, name=None):
    return apply(activation_ops.silu_op, x)


def gelu(x, approximate=False, name=None):
    return apply(activation_ops.gelu_op, x, approximate=bool(approximate))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(activation_ops.leaky_relu_op, x,
                 negative_slope=float(negative_slope))


def elu(x, alpha=1.0, name=None):
    return apply(activation_ops.elu_op, x, alpha=float(alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(activation_ops.selu_op, x, scale=float(scale),
                 alpha=float(alpha))


def celu(x, alpha=1.0, name=None):
    return apply(activation_ops.celu_op, x, alpha=float(alpha))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(activation_ops.softplus_op, x, beta=float(beta),
                 threshold=float(threshold))


def softsign(x, name=None):
    return apply(activation_ops.softsign_op, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(activation_ops.hardtanh_op, x, min=float(min),
                 max=float(max))


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return apply(activation_ops.hardsigmoid_op, x, slope=float(slope),
                 offset=float(offset))


def hardswish(x, name=None):
    return apply(activation_ops.hardswish_op, x)


def swish(x, name=None):
    return apply(activation_ops.swish_op, x)


def mish(x, name=None):
    return apply(activation_ops.mish_op, x)


def tanhshrink(x, name=None):
    return apply(activation_ops.tanhshrink_op, x)


def softshrink(x, threshold=0.5, name=None):
    return apply(activation_ops.softshrink_op, x, threshold=float(threshold))


def hardshrink(x, threshold=0.5, name=None):
    return apply(activation_ops.hardshrink_op, x, threshold=float(threshold))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(activation_ops.thresholded_relu_op, x,
                 threshold=float(threshold), value=float(value))


def log_sigmoid(x, name=None):
    return apply(activation_ops.log_sigmoid_op, x)


def prelu(x, weight, data_format="NCHW", name=None):
    return apply(activation_ops.prelu_op, x, weight, data_format=data_format)


def glu(x, axis=-1, name=None):
    return apply(activation_ops.glu_op, x, axis=int(axis))


def swiglu(x, y=None, name=None):
    if y is None:
        return apply(activation_ops.swiglu_op, x)
    return apply(activation_ops.swiglu_op, x, y)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = cast(x, dtype)
    return apply(nn_ops.softmax_op, x, axis=int(axis))


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = cast(x, dtype)
    return apply(nn_ops.log_softmax_op, x, axis=int(axis))


def isnan(x, name=None):
    return isnan_(x)


def isinf(x, name=None):
    return isinf_(x)


def isfinite(x, name=None):
    return isfinite_(x)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    import numpy as np

    from . import infermeta

    infermeta.validate("allclose",
                       (x._data if isinstance(x, Tensor) else x,
                        y._data if isinstance(y, Tensor) else y),
                       {"rtol": rtol, "atol": atol})
    return Tensor(np.allclose(x.numpy(), y.numpy(), rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    import jax.numpy as jnp

    from . import infermeta

    xd = x._data if isinstance(x, Tensor) else x
    yd = y._data if isinstance(y, Tensor) else y
    infermeta.validate("isclose", (xd, yd), {"rtol": rtol, "atol": atol})
    return Tensor(jnp.isclose(xd, yd, rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def equal_all(x, y, name=None):
    import numpy as np

    return Tensor(_bi.bool(np.array_equal(x.numpy(), y.numpy())))


def increment(x, value=1.0, name=None):
    out = add(x, to_tensor(value, dtype=str(x.dtype)))
    x.set_value(out)
    return x


# --------------------------------------------------------------------------
# Tensor method installation (tensor_patch_methods analog)
# --------------------------------------------------------------------------

def _swap(fn):
    def rev(self, other):
        return fn(other if isinstance(other, Tensor) else to_tensor(
            other, dtype=str(self.dtype)), self)

    return rev


def _install_tensor_methods():
    import numpy as np

    T = Tensor

    def _coerce(self, other):
        if isinstance(other, Tensor):
            return other
        return other  # raw scalars handled by jnp broadcasting

    T.__add__ = lambda s, o: add(s, _coerce(s, o))
    T.__radd__ = lambda s, o: add(s, _coerce(s, o))
    T.__sub__ = lambda s, o: subtract(s, _coerce(s, o))
    T.__rsub__ = _swap(subtract)
    T.__mul__ = lambda s, o: multiply(s, _coerce(s, o))
    T.__rmul__ = lambda s, o: multiply(s, _coerce(s, o))
    T.__truediv__ = lambda s, o: divide(s, _coerce(s, o))
    T.__rtruediv__ = _swap(divide)
    T.__floordiv__ = lambda s, o: floor_divide(s, _coerce(s, o))
    T.__mod__ = lambda s, o: remainder(s, _coerce(s, o))
    T.__pow__ = lambda s, o: pow(s, _coerce(s, o))
    T.__rpow__ = _swap(pow)
    T.__matmul__ = lambda s, o: matmul(s, o)
    T.__rmatmul__ = _swap(matmul)
    T.__neg__ = lambda s: neg(s)
    T.__abs__ = lambda s: abs(s)
    T.__invert__ = lambda s: logical_not(s) if s.dtype == np.bool_ \
        else bitwise_not(s)
    T.__eq__ = lambda s, o: equal(s, _coerce(s, o))
    T.__ne__ = lambda s, o: not_equal(s, _coerce(s, o))
    T.__lt__ = lambda s, o: less_than(s, _coerce(s, o))
    T.__le__ = lambda s, o: less_equal(s, _coerce(s, o))
    T.__gt__ = lambda s, o: greater_than(s, _coerce(s, o))
    T.__ge__ = lambda s, o: greater_equal(s, _coerce(s, o))
    T.__and__ = lambda s, o: logical_and(s, o) if s.dtype == np.bool_ \
        else bitwise_and(s, o)
    T.__or__ = lambda s, o: logical_or(s, o) if s.dtype == np.bool_ \
        else bitwise_or(s, o)
    T.__xor__ = lambda s, o: logical_xor(s, o) if s.dtype == np.bool_ \
        else bitwise_xor(s, o)
    T.__hash__ = object.__hash__
    T.__getitem__ = getitem
    T.__setitem__ = setitem

    # Named methods.
    methods = dict(
        add=add, subtract=subtract, multiply=multiply, divide=divide,
        pow=pow, matmul=matmul, mm=mm, bmm=bmm, dot=dot, maximum=maximum,
        minimum=minimum, remainder=remainder, mod=mod,
        floor_divide=floor_divide,
        exp=exp, log=log, log2=log2, log10=log10, log1p=log1p, sqrt=sqrt,
        rsqrt=rsqrt, square=square, abs=abs, sign=sign, floor=floor,
        ceil=ceil, round=round, trunc=trunc, reciprocal=reciprocal,
        sin=sin, cos=cos, tan=tan, asin=asin, acos=acos, atan=atan,
        sinh=sinh, cosh=cosh, tanh=tanh, erf=erf, lgamma=lgamma,
        digamma=digamma, neg=neg, clip=clip, scale=scale, lerp=lerp,
        isnan=isnan_, isinf=isinf_, isfinite=isfinite_,
        logical_and=logical_and, logical_or=logical_or,
        logical_not=logical_not, logical_xor=logical_xor,
        equal=equal, not_equal=not_equal, greater_than=greater_than,
        greater_equal=greater_equal, less_than=less_than,
        less_equal=less_equal, equal_all=equal_all, allclose=allclose,
        isclose=isclose,
        sum=sum, mean=mean, max=max, min=min, amax=amax, amin=amin,
        prod=prod, any=any, all=all, logsumexp=logsumexp, argmax=argmax,
        argmin=argmin, cumsum=cumsum, cumprod=cumprod, var=var, std=std,
        numel=numel, count_nonzero=count_nonzero, median=median,
        cast=cast, astype=cast, reshape=reshape, reshape_=reshape,
        transpose=transpose, t=t, squeeze=squeeze, squeeze_=squeeze,
        unsqueeze=unsqueeze, unsqueeze_=unsqueeze, flatten=flatten,
        expand=expand, expand_as=expand_as, broadcast_to=broadcast_to,
        tile=tile, concat=concat, split=split, chunk=chunk, unbind=unbind,
        flip=flip, roll=roll, gather=gather, index_select=index_select,
        take_along_axis=take_along_axis, put_along_axis=put_along_axis,
        scatter=scatter, scatter_nd_add=scatter_nd_add, gather_nd=gather_nd,
        where=where, nonzero=nonzero, masked_select=masked_select,
        masked_fill=masked_fill, topk=topk, sort=sort, argsort=argsort,
        unique=unique, tril=tril, triu=triu, diag=diag, diagonal=diagonal,
        repeat_interleave=repeat_interleave, moveaxis=moveaxis,
        norm=norm, dist=dist, inverse=inverse, cholesky=cholesky,
        multinomial=multinomial, normal_=normal_, uniform_=uniform_,
        exponential_=exponential_, fill_=None, zero_=None,
        softmax=softmax, sigmoid=sigmoid, relu=relu, gelu=gelu,
        one_hot=one_hot, bincount=bincount, histogram=histogram,
        nan_to_num=nan_to_num,
        # long-tail (ops/extra.py + linalg tail), round 3
        kron=kron, trace=trace, heaviside=heaviside, copysign=copysign,
        hypot=hypot, deg2rad=deg2rad, rad2deg=rad2deg, diff=diff,
        trapezoid=trapezoid, vander=vander, logcumsumexp=logcumsumexp,
        renorm=renorm, cdist=cdist, tensordot=tensordot,
        bucketize=bucketize, nanmedian=nanmedian, mode=mode,
        kthvalue=kthvalue, rot90=rot90, take=take, index_add=index_add,
        index_fill=index_fill, index_put=index_put,
        index_put_=index_put_, unfold=unfold, as_strided=as_strided,
        select_scatter=select_scatter, slice_scatter=slice_scatter,
        diagflat=diagflat, atleast_1d=atleast_1d, atleast_2d=atleast_2d,
        atleast_3d=atleast_3d, tensor_split=tensor_split,
        hsplit=hsplit, vsplit=vsplit, dsplit=dsplit, lu=lu,
        eig=eig, eigvals=eigvals, eigvalsh=eigvalsh, svdvals=svdvals,
        cond=cond, corrcoef=corrcoef, cov=cov, lstsq=lstsq,
        matrix_exp=matrix_exp, cholesky_solve=cholesky_solve,
        # long-tail (ops/tail.py), round 4
        real=real, imag=imag, conj=conj, angle=angle, isreal=isreal,
        isneginf=isneginf, isposinf=isposinf, signbit=signbit,
        sinc=sinc, nextafter=nextafter, polar=None, sgn=sgn,
        logit=logit, gammaln=gammaln, gammainc=gammainc,
        gammaincc=gammaincc, multigammaln=multigammaln, i0e=i0e, i1=i1,
        i1e=i1e, polygamma=polygamma, unflatten=unflatten,
        view_as=view_as, strided_slice=strided_slice,
        diagonal_scatter=diagonal_scatter, masked_scatter=masked_scatter,
        index_sample=index_sample, reduce_as=reduce_as, isin=isin,
        is_empty=is_empty, nanquantile=nanquantile, pdist=None,
        cumulative_trapezoid=cumulative_trapezoid, mv=mv, vecdot=vecdot,
        householder_product=householder_product,
        cholesky_inverse=cholesky_inverse, crop=crop,
    )
    for name, fn in methods.items():
        if fn is None:
            continue
        setattr(T, name, fn)

    def fill_(self, value):
        import jax.numpy as jnp

        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        import jax.numpy as jnp

        self._data = jnp.zeros_like(self._data)
        return self

    T.fill_ = fill_
    T.zero_ = zero_

    def _inplace_apply(self, fn, *args, **kw):
        # Route through an autograd proxy so the new node's input edge
        # keeps pointing at the OLD producer (no self-loop after rebind).
        from .manipulation import _autograd_proxy

        out = fn(_autograd_proxy(self), *args, **kw)
        self._data = out._data
        self._grad_node = out._grad_node
        self._out_slot = out._out_slot
        self.stop_gradient = out.stop_gradient and self.stop_gradient
        return self

    def add_(self, y):
        return _inplace_apply(self, add, y)

    def scale_(self, scale_v=1.0, bias=0.0, bias_after_scale=True):
        return _inplace_apply(self, scale, scale_v, bias, bias_after_scale)

    def subtract_(self, y):
        return _inplace_apply(self, subtract, y)

    def multiply_(self, y):
        return _inplace_apply(self, multiply, y)

    def clip_(self, min=None, max=None):
        return _inplace_apply(self, clip, min, max)

    T.add_ = add_
    T.subtract_ = subtract_
    T.multiply_ = multiply_
    T.scale_ = scale_
    T.clip_ = clip_


_install_tensor_methods()


# --- generated in-place variants (ops/inplace.py), round 4 -----------------
def _install_inplace_variants():
    import sys

    from . import inplace as _inplace_mod

    mod = sys.modules[__name__]
    created = _inplace_mod.install(mod)
    # math.py's round_ is the decimal-less FUNCTIONAL round kept for
    # internal use; the public paddle.round_ must be the in-place
    # variant, so it is explicitly overridden below.
    force = {"round_"}
    for name, fn in created.items():
        # don't clobber hand-written variants (add_/clip_/... above or
        # the random in-place fills like normal_)
        if name in force or not hasattr(mod, name):
            setattr(mod, name, fn)
        if name in force or not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    return sorted(created)


_INPLACE_VARIANTS = _install_inplace_variants()
