"""Low-rank factorizations + sampling/creation tail.

Reference points: ``python/paddle/sparse/unary.py:1066`` (pca_lowrank)
/ ``:1186`` (svd_lowrank), ``python/paddle/tensor/search.py:1360``
(top_p_sampling), ``python/paddle/tensor/creation.py:263`` (create_tensor),
``python/paddle/tensor/linalg.py:2461`` (histogram_bin_edges), ``:327``
(fp8_fp8_half_gemm_fused) and the linalg norms
(``vector_norm``/``matrix_norm``).

TPU-native: the low-rank path is the randomized range-finder (Halko et al.)
— q tall-skinny matmuls + one small exact SVD, all MXU work with static
shapes; top-p rides sort/cumsum + Gumbel-categorical so it stays jittable
inside a decode loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# -- randomized low-rank -----------------------------------------------------

def _on_factorization_device(fn, *args):
    """Run a small QR/SVD.  Eagerly on a TPU backend the tiny [.., q]
    factorizations go through the CPU backend — they're microseconds of
    work, and the remote TPU compiler is a known crash on degenerate
    small-transpose HLO; under tracing (jit) the op stays in-graph."""
    if any(isinstance(a, jax.core.Tracer) for a in args):
        return fn(*args)
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        return fn(*args)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        out = fn(*[jax.device_put(a, cpu) for a in args])
    return jax.tree_util.tree_map(lambda t: jax.device_put(t, dev), out)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized truncated SVD (sparse/unary.py:1186): subspace iteration
    on a Gaussian sketch, exact SVD of the small projected matrix."""
    from .random import default_generator

    a = _raw(x)
    if M is not None:
        a = a - _raw(M)
    m, n = a.shape[-2], a.shape[-1]
    q = int(min(q, m, n))
    key = default_generator.next_key()
    omega = jax.random.normal(key, a.shape[:-2] + (n, q), a.dtype)
    y = a @ omega                                   # [.., m, q] range sketch
    # Subspace (power) iteration sharpens the spectrum; QR re-orthogonalizes
    # to keep the basis numerically independent.  The sketch matmuls stay on
    # the accelerator (MXU work); only the tiny QR/SVD factorizations are
    # routed via _on_factorization_device.
    _qr = lambda t: jnp.linalg.qr(t)  # noqa: E731
    qb, _ = _on_factorization_device(_qr, y)
    for _ in range(int(niter)):
        z = jnp.swapaxes(a, -2, -1) @ qb
        qz, _ = _on_factorization_device(_qr, z)
        y = a @ qz
        qb, _ = _on_factorization_device(_qr, y)
    b = jnp.swapaxes(qb, -2, -1) @ a                # [.., q, n] small
    u_b, s, vt = _on_factorization_device(
        lambda t: jnp.linalg.svd(t, full_matrices=False), b)
    u = qb @ u_b
    v = jnp.swapaxes(vt, -2, -1)
    return Tensor(u), Tensor(s), Tensor(v)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """sparse/unary.py:1066 — PCA via the randomized SVD above."""
    a = _raw(x)
    m, n = a.shape[-2], a.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    return svd_lowrank(Tensor(a), q=q, niter=niter)


# -- top-p sampling ----------------------------------------------------------

def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """tensor/search.py:1360 — nucleus sampling.  x [B, V] probabilities,
    ps [B] per-row top-p.  Keeps the minimal prefix of the descending
    distribution with mass >= p, renormalizes, samples one id per row.
    Fully jittable (sort + cumsum + categorical)."""
    from .random import default_generator

    probs = _raw(x).astype(jnp.float32)
    p = _raw(ps).astype(jnp.float32).reshape(-1, 1)
    order = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, order, axis=-1)
    csum = jnp.cumsum(sorted_p, axis=-1)
    # Keep every token whose *preceding* mass is < p (so the boundary token
    # that crosses p stays in the nucleus).
    keep = (csum - sorted_p) < p
    if mode == "truncated":
        kept = jnp.where(keep, sorted_p, 0.0)
    else:
        kept = sorted_p
    if threshold is not None:
        kept = jnp.where(sorted_p >= _raw(threshold).reshape(-1, 1),
                         kept, 0.0)
    # Guard: never zero out an entire row.
    kept = jnp.where(keep.any(-1, keepdims=True), kept, sorted_p)
    logits = jnp.log(jnp.maximum(kept, 1e-30))
    if topp_seed is not None:
        # per-row seeds (the reference's per-query determinism knob)
        seeds = _raw(topp_seed).astype(jnp.uint32).reshape(-1)
        keys = jax.vmap(jax.random.PRNGKey)(seeds)
        pick = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg))(keys, logits)
    else:
        if seed != -1:
            key = jax.random.PRNGKey(seed)
        else:
            key = default_generator.next_key()
        pick = jax.random.categorical(key, logits, axis=-1)  # [B]
    ids = jnp.take_along_axis(order, pick[:, None], axis=-1)
    scores = jnp.take_along_axis(probs, ids, axis=-1).astype(_raw(x).dtype)
    out = (Tensor(scores), Tensor(ids.astype(jnp.int64)))
    if return_top and k > 0:
        topv, topi = jax.lax.top_k(probs, k)
        return out + (Tensor(topv.astype(_raw(x).dtype)),
                      Tensor(topi.astype(jnp.int64)))
    return out


# -- creation / histogram tail ----------------------------------------------

def create_tensor(dtype, name=None, persistable=False):
    """tensor/creation.py:263 — an empty typed holder variable."""
    from ..core.dtype import convert_dtype

    t = Tensor(jnp.zeros((0,), convert_dtype(dtype)))
    t.persistable = persistable
    if name:
        t.name = name
    return t


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    """tensor/linalg.py:2461 — the bin edges ``histogram`` would use."""
    import numpy as np

    arr = np.asarray(_raw(input))
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    return Tensor(jnp.asarray(np.histogram_bin_edges(
        arr, bins=bins, range=(float(lo), float(hi))), jnp.float32))


# -- linalg norms ------------------------------------------------------------

def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """linalg.vector_norm (tensor/linalg.py) — p-norm treating the selected
    axes as one flattened vector."""
    a = _raw(x)
    if axis is None:
        a = a.reshape(-1)
        axis = 0
    p = float(p)
    if p == float("inf"):
        return Tensor(jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim))
    if p == float("-inf"):
        return Tensor(jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim))
    if p == 0:
        return Tensor(jnp.sum((a != 0).astype(a.dtype), axis=axis,
                              keepdims=keepdim))
    return Tensor(jnp.sum(jnp.abs(a) ** p, axis=axis,
                          keepdims=keepdim) ** (1.0 / p))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """linalg.matrix_norm — Frobenius/nuclear/operator norms over the two
    trailing (or given) axes."""
    a = _raw(x)
    axis = tuple(axis)
    if p in ("fro", "f"):
        return Tensor(jnp.sqrt(jnp.sum(
            jnp.abs(a) ** 2, axis=axis, keepdims=keepdim)))
    if p == "nuc" or p in (2, -2, 2.0, -2.0):
        a2 = jnp.moveaxis(a, axis, (-2, -1))
        s = jnp.linalg.svd(a2, compute_uv=False)
        if p == "nuc":
            out = jnp.sum(s, axis=-1)
        elif float(p) > 0:
            out = jnp.max(s, axis=-1)
        else:
            out = jnp.min(s, axis=-1)
        if keepdim:
            out = jnp.expand_dims(out, axis)
        return Tensor(out)
    row_axis, col_axis = axis
    if p in (1, -1, 1.0, -1.0):
        sums = jnp.sum(jnp.abs(a), axis=row_axis, keepdims=True)
        red = jnp.max if float(p) > 0 else jnp.min
        out = red(sums, axis=col_axis, keepdims=True)
    elif p in (float("inf"), float("-inf")):
        sums = jnp.sum(jnp.abs(a), axis=col_axis, keepdims=True)
        red = jnp.max if p > 0 else jnp.min
        out = red(sums, axis=row_axis, keepdims=True)
    else:
        raise ValueError(f"unsupported matrix norm order {p!r}")
    if not keepdim:
        out = jnp.squeeze(out, axis)
    return Tensor(out)


# -- fp8 gemm ----------------------------------------------------------------

def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="float16",
                            act="identity", name=None):
    """tensor/linalg.py:327 — fp8 x fp8 -> half gemm.  TPU-native: XLA
    lowers float8_e4m3fn dot_general onto the MXU directly; scale/bias/act
    fuse into the epilogue."""
    a, b = _raw(x), _raw(y)
    if a.dtype not in (jnp.float8_e4m3fn, jnp.float8_e5m2):
        a = a.astype(jnp.float8_e4m3fn)
    if b.dtype not in (jnp.float8_e4m3fn, jnp.float8_e5m2):
        b = b.astype(jnp.float8_e4m3fn)
    if transpose_x:
        a = jnp.swapaxes(a, -2, -1)
    if transpose_y:
        b = jnp.swapaxes(b, -2, -1)
    out = jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (b.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = out * scale
    if bias is not None:
        out = out + _raw(bias).astype(out.dtype)
    if act in ("relu",):
        out = jnp.maximum(out, 0)
    elif act in ("gelu",):
        out = jax.nn.gelu(out)
    elif act != "identity":
        raise ValueError(f"unsupported act {act!r}")
    dt = jnp.bfloat16 if "bfloat16" in str(output_dtype) else jnp.float16
    return Tensor(out.astype(dt))
