"""Linear algebra ops.

Reference: ``python/paddle/tensor/linalg.py`` (``matmul`` at :189 →
``_C_ops.matmul``) with kernel pairing ``matmul``/``matmul_grad`` in
ops.yaml; the matmul grad math mirrors ``phi/kernels/impl/
matmul_grad_kernel_impl.h``.  matmul is THE MXU op — it stays a single
``jnp.matmul`` so XLA tiles it onto the systolic array; transposes fold into
``dot_general`` dimension numbers rather than materializing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import apply, register_op
from .math import unbroadcast


def _mm(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def _mm_fwd(x, y, transpose_x=False, transpose_y=False):
    return _mm(x, y, transpose_x, transpose_y), (x, y)


def _mm_bwd(saved, g, transpose_x=False, transpose_y=False):
    x, y = saved
    xshape, yshape = jnp.shape(x), jnp.shape(y)
    # 1-D operand cases reduce to vector products.
    if x.ndim == 1 and y.ndim == 1:
        return (g * y).astype(x.dtype), (g * x).astype(y.dtype)
    if x.ndim == 1:
        # out = x @ Y (or Y^T): g shape [..., n]
        yy = jnp.swapaxes(y, -1, -2) if transpose_y else y
        gx = jnp.matmul(g[..., None, :],
                        jnp.swapaxes(yy, -1, -2))[..., 0, :]
        gy = jnp.matmul(x[:, None], g[..., None, :]) if not transpose_y \
            else jnp.matmul(g[..., :, None], x[None, :])
        return (unbroadcast(gx, xshape).astype(x.dtype),
                unbroadcast(gy, yshape).astype(y.dtype))
    if y.ndim == 1:
        xx = jnp.swapaxes(x, -1, -2) if transpose_x else x
        gx = jnp.matmul(g[..., :, None], y[None, :])
        if transpose_x:
            gx = jnp.swapaxes(gx, -1, -2)
        gy = jnp.einsum("...mk,...m->k", xx, g)
        return (unbroadcast(gx, xshape).astype(x.dtype),
                unbroadcast(gy, yshape).astype(y.dtype))

    if not transpose_x and not transpose_y:
        gx = jnp.matmul(g, jnp.swapaxes(y, -1, -2))
        gy = jnp.matmul(jnp.swapaxes(x, -1, -2), g)
    elif transpose_x and not transpose_y:
        gx = jnp.matmul(y, jnp.swapaxes(g, -1, -2))
        gy = jnp.matmul(x, g)
    elif not transpose_x and transpose_y:
        gx = jnp.matmul(g, y)
        gy = jnp.matmul(jnp.swapaxes(g, -1, -2), x)
    else:
        gx = jnp.matmul(jnp.swapaxes(y, -1, -2), jnp.swapaxes(g, -1, -2))
        gy = jnp.matmul(jnp.swapaxes(g, -1, -2), jnp.swapaxes(x, -1, -2))
    return (unbroadcast(gx, xshape).astype(x.dtype),
            unbroadcast(gy, yshape).astype(y.dtype))


matmul_op = register_op("matmul", _mm, fwd=_mm_fwd, bwd=_mm_bwd,
                        static_argnames=("transpose_x", "transpose_y"))


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return apply(matmul_op, x, y, transpose_x=bool(transpose_x),
                 transpose_y=bool(transpose_y))


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def inner(x, y, name=None):
    return apply(_inner_op, x, y)


_inner_op = register_op("inner", jnp.inner)

dot_op = register_op(
    "dot", lambda x, y: jnp.sum(x * y, axis=-1),
    fwd=lambda x, y: (jnp.sum(x * y, axis=-1), (x, y)),
    bwd=lambda saved, g: (g[..., None] * saved[1], g[..., None] * saved[0]))


def dot(x, y, name=None):
    return apply(dot_op, x, y)


def outer(x, y, name=None):
    return apply(_outer_op, x, y)


_outer_op = register_op("outer", jnp.outer)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(_addmm_op, input, x, y, beta=float(beta), alpha=float(alpha))


_addmm_op = register_op(
    "addmm",
    lambda inp, x, y, beta=1.0, alpha=1.0: beta * inp + alpha * jnp.matmul(x, y),
    static_argnames=("beta", "alpha"))


# -- einsum -----------------------------------------------------------------

def einsum(equation, *operands):
    from ..core.tensor import Tensor
    from ..autograd import engine as _engine

    datas = [o._data if isinstance(o, Tensor) else jnp.asarray(o)
             for o in operands]
    need_grad = _engine.is_grad_enabled() and any(
        isinstance(o, Tensor) and not o.stop_gradient for o in operands)
    if not need_grad:
        return Tensor(jnp.einsum(equation, *datas))
    out_data, vjp_fn = jax.vjp(lambda *ds: jnp.einsum(equation, *ds), *datas)
    node = _engine.GradNode(_einsum_fakeop, vjp_fn, list(operands), {},
                            vjp_fallback=True,
                            diff_idx=list(range(len(operands))))
    out = Tensor(out_data, stop_gradient=False)
    node.bind_outputs([out])
    return out


class _EinsumOp:
    name = "einsum"
    jit_bwd = None


_einsum_fakeop = _EinsumOp()


# -- norms / decompositions -------------------------------------------------

def norm(x, p=None, axis=None, keepdim=False, name=None):
    from . import reduction, math as m

    if p is None or p == "fro" or p == 2:
        sq = m.multiply(x, x)
        s = reduction.sum(sq, axis=axis, keepdim=keepdim)
        return m.sqrt(s)
    if p == 1:
        return reduction.sum(m.abs(x), axis=axis, keepdim=keepdim)
    if p == float("inf"):
        return reduction.max(m.abs(x), axis=axis, keepdim=keepdim)
    if p == float("-inf"):
        return reduction.min(m.abs(x), axis=axis, keepdim=keepdim)
    ax = m.abs(x)
    powed = m.pow(ax, p)
    s = reduction.sum(powed, axis=axis, keepdim=keepdim)
    return m.pow(s, 1.0 / p)


def dist(x, y, p=2, name=None):
    from . import infermeta
    from . import math as m

    infermeta.validate("dist", (getattr(x, "_data", x),
                                getattr(y, "_data", y)), {"p": p})
    return norm(m.subtract(x, y), p=p)


_tri_solve_op = register_op(
    "triangular_solve",
    lambda x, y, upper=True, transpose=False, unitriangular=False:
    jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular),
    static_argnames=("upper", "transpose", "unitriangular"))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply(_tri_solve_op, x, y, upper=bool(upper),
                 transpose=bool(transpose), unitriangular=bool(unitriangular))


_cholesky_op = register_op(
    "cholesky",
    lambda x, upper=False: (jnp.linalg.cholesky(x) if not upper
                            else jnp.swapaxes(jnp.linalg.cholesky(x), -1, -2)),
    static_argnames=("upper",))


def cholesky(x, upper=False, name=None):
    return apply(_cholesky_op, x, upper=bool(upper))


_inv_op = register_op("inverse", jnp.linalg.inv)


def inverse(x, name=None):
    return apply(_inv_op, x)


_det_op = register_op("det", jnp.linalg.det)


def det(x, name=None):
    return apply(_det_op, x)


_slogdet_op = register_op(
    "slogdet", lambda x: tuple(jnp.linalg.slogdet(x)), n_outputs=2)


def slogdet(x, name=None):
    return apply(_slogdet_op, x)


_solve_op = register_op("solve", jnp.linalg.solve)


def solve(x, y, name=None):
    return apply(_solve_op, x, y)


def svd(x, full_matrices=False, name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = x._data if isinstance(x, Tensor) else x
    infermeta.validate("svd", (xd,),
                       {"full_matrices": bool(full_matrices)})
    u, s, vh = jnp.linalg.svd(xd, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2))


def qr(x, mode="reduced", name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = x._data if isinstance(x, Tensor) else x
    infermeta.validate("qr", (xd,), {"mode": mode})
    if mode == "r":
        return Tensor(jnp.linalg.qr(xd, mode="r"))
    q, r = jnp.linalg.qr(xd, mode=mode)
    return Tensor(q), Tensor(r)


def eigh(x, UPLO="L", name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = x._data if isinstance(x, Tensor) else x
    infermeta.validate("eigh", (xd,), {"UPLO": UPLO})
    w, v = jnp.linalg.eigh(xd)
    return Tensor(w), Tensor(v)


def matrix_power(x, n, name=None):
    return apply(_matrix_power_op, x, n=int(n))


_matrix_power_op = register_op(
    "matrix_power", lambda x, n: jnp.linalg.matrix_power(x, n),
    static_argnames=("n",))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = x._data if isinstance(x, Tensor) else x
    infermeta.validate("pinv", (xd,), {"hermitian": bool(hermitian)})
    return Tensor(jnp.linalg.pinv(xd, rtol=rcond, hermitian=hermitian))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = x._data if isinstance(x, Tensor) else x
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("matrix_rank", (xd,), {"hermitian": bool(hermitian)})
    return Tensor(jnp.linalg.matrix_rank(xd))


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None
    from ..core.tensor import Tensor

    xd = x._data if isinstance(x, Tensor) else x
    yd = y._data if isinstance(y, Tensor) else y
    if ax is None:
        for i, s in enumerate(xd.shape):
            if s == 3:
                ax = i
                break
    return apply(_cross_op, x, y, axis=int(ax))


_cross_op = register_op(
    "cross", lambda x, y, axis: jnp.cross(x, y, axis=axis),
    static_argnames=("axis",))


def histogram(input, bins=100, min=0, max=0, name=None):
    from ..core.tensor import Tensor
    from . import infermeta
    import numpy as np

    arr = np.asarray(input._data if isinstance(input, Tensor) else input)
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("histogram", (arr,),
                       {"bins": int(bins), "min": min, "max": max})
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(hist, dtype=jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    from ..core.tensor import Tensor
    from . import infermeta
    import numpy as np

    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    w = np.asarray(weights._data) if isinstance(weights, Tensor) else weights
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("bincount", (arr, w), {"minlength": minlength})
    return Tensor(jnp.asarray(np.bincount(arr, w, minlength)))


# -- linalg tail (reference python/paddle/tensor/linalg.py) -----------------

def _raw(x):
    from ..core.tensor import Tensor

    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def lu(x, pivot=True, get_infos=False, name=None):
    """Packed LU + 1-based pivots (reference linalg.lu)."""
    from . import infermeta
    from ..core.tensor import Tensor

    import jax

    xd = _raw(x)
    infermeta.validate("lu", (xd,), {"pivot": bool(pivot)})
    res = jax.lax.linalg.lu(xd)
    packed, piv = res[0], res[1]
    out = (Tensor(packed), Tensor(piv.astype(jnp.int64) + 1))
    if get_infos:
        info = jnp.zeros(packed.shape[:-2], jnp.int64)
        return out + (Tensor(info),)
    return out


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """(P, L, U) from packed LU (reference linalg.lu_unpack)."""
    from . import infermeta
    from ..core.tensor import Tensor

    import jax

    a = _raw(lu_data)
    infermeta.validate("lu_unpack", (a, _raw(lu_pivots)), {})
    piv = _raw(lu_pivots).astype(jnp.int32) - 1  # back to 0-based
    m, n = a.shape[-2], a.shape[-1]
    k = min(m, n)
    if unpack_ludata:
        L = jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[..., :k, :])
        L, U = Tensor(L), Tensor(U)
    else:  # reference: disabled outputs are None, their work skipped
        L = U = None
    if not unpack_pivots:
        return None, L, U
    # pivots -> permutation: apply row swaps to identity (batched)
    batch = piv.shape[:-1]
    n_piv = piv.shape[-1]

    def apply_swaps(piv_row):
        def body(i, pr):
            j = piv_row[i]
            pi, pj = pr[i], pr[j]
            return pr.at[i].set(pj).at[j].set(pi)

        return jax.lax.fori_loop(0, n_piv, body, jnp.arange(m))

    if batch:
        perm = jax.vmap(apply_swaps)(piv.reshape(-1, n_piv))
        perm = perm.reshape(batch + (m,))
    else:
        perm = apply_swaps(piv)
    P = jnp.swapaxes(jnp.eye(m, dtype=a.dtype)[perm], -1, -2)
    return Tensor(P), L, U


def cholesky_solve(x, y, upper=False, name=None):
    """Solve A X = B given the Cholesky factor (reference
    linalg.cholesky_solve)."""
    from . import infermeta
    from ..core.tensor import Tensor

    import jax.scipy.linalg as jsl

    xd, yd = _raw(x), _raw(y)
    infermeta.validate("cholesky_solve", (xd, yd), {"upper": bool(upper)})
    return Tensor(jsl.cho_solve((yd, not upper), xd))


def eig(x, name=None):
    """General (non-symmetric) eigendecomposition.  XLA has no TPU
    kernel for general eig (CPU only in the reference's GPU build too —
    phi eig kernel is CPU); computed host-side via LAPACK."""
    from . import infermeta
    from ..core.tensor import Tensor

    import numpy as _np

    xd = _raw(x)
    infermeta.validate("eig", (xd,), {})
    w, v = _np.linalg.eig(_np.asarray(xd))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    from ..core.tensor import Tensor

    import numpy as _np

    return Tensor(jnp.asarray(_np.linalg.eigvals(_np.asarray(_raw(x)))))


def eigvalsh(x, UPLO="L", name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = _raw(x)
    infermeta.validate("eigvalsh", (xd,), {"UPLO": UPLO})
    return Tensor(jnp.linalg.eigvalsh(xd, UPLO=UPLO))


def svdvals(x, name=None):
    from ..core.tensor import Tensor

    return Tensor(jnp.linalg.svd(_raw(x), compute_uv=False))


def cond(x, p=None, name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = _raw(x)
    infermeta.validate("cond", (xd,), {"p": p})
    return Tensor(jnp.asarray(jnp.linalg.cond(xd, p=p)))


def corrcoef(x, rowvar=True, name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = _raw(x)
    infermeta.validate("corrcoef", (xd,), {"rowvar": rowvar})
    return Tensor(jnp.corrcoef(xd, rowvar=rowvar))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None,
        name=None):
    from . import infermeta
    from ..core.tensor import Tensor

    xd = _raw(x)
    fw = None if fweights is None else _raw(fweights)
    aw = None if aweights is None else _raw(aweights)
    infermeta.validate("cov", (xd,), {"rowvar": rowvar, "ddof": ddof,
                                      "fweights": fw, "aweights": aw})
    return Tensor(jnp.cov(xd, rowvar=rowvar,
                          ddof=1 if ddof else 0, fweights=fw,
                          aweights=aw))


def lstsq(x, y, rcond=None, driver=None, name=None):
    """Least squares (reference linalg.lstsq): returns (solution,
    residuals, rank, singular_values)."""
    from . import infermeta
    from ..core.tensor import Tensor

    infermeta.validate("lstsq", (_raw(x), _raw(y)), {"driver": driver})
    sol, res, rank, sv = jnp.linalg.lstsq(_raw(x), _raw(y), rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(jnp.asarray(rank)),
            Tensor(sv))


def matrix_exp(x, name=None):
    from ..core.tensor import Tensor

    import jax.scipy.linalg as jsl

    return Tensor(jsl.expm(_raw(x)))


def multi_dot(tensors, name=None):
    """Chain matmul with optimal-order association (jnp's dynamic
    program picks the association)."""
    from . import infermeta
    from ..core.tensor import Tensor

    datas = [_raw(t) for t in tensors]
    infermeta.validate("multi_dot", tuple(datas), {})
    return Tensor(jnp.linalg.multi_dot(datas))


# Declared-``__all__`` tail (reference python/paddle/linalg.py): re-exports
# of ops that live in the shared tail modules plus the lowrank family.
from .lowrank import (  # noqa: F401,E402
    fp8_fp8_half_gemm_fused, matrix_norm, pca_lowrank, svd_lowrank,
    vector_norm,
)
from .tail import (  # noqa: F401,E402
    cholesky_inverse, householder_product, ormqr,
)


def inv(x, name=None):
    """reference linalg.inv — alias of paddle.inverse."""
    return inverse(x, name=name)
