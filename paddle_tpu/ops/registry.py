"""Op registry + eager dispatch.

TPU-native re-design of the reference's kernel registry/dispatch stack:
``phi::KernelFactory`` (``paddle/phi/core/kernel_factory.h:316``), the
generated C++ op API (``paddle/phi/api/generator/api_gen.py:456``) and the
generated dygraph ad_funcs (``paddle/fluid/eager/auto_code_generator/
generator/eager_gen.py:321``).

Design (SURVEY.md §7.2): on TPU, XLA *is* the kernel library.  An ``OpDef``
binds a name to three jax-level callables:

  * ``fn(*arrays, **attrs) -> array(s)``       plain forward
  * ``fwd(*arrays, **attrs) -> (out, saved)``  forward returning residuals
  * ``bwd(saved, grad_out, **attrs) -> grads`` VJP over the recorded inputs

``fwd``/``bwd`` are hand-written for hot ops (mirroring the reference's
ops.yaml/backward.yaml kernel pairing); ops registered with only ``fn`` get
an automatic ``jax.vjp`` fallback.  Each callable is wrapped in ``jax.jit``
once at registration, so the eager hot loop is an XLA executable-cache hit —
the "dispatch" the reference does per-op in C++ becomes a jitted call here.

The ``apply`` function is the analog of a generated ad_func: it decides
whether gradients are required, runs the (jitted) forward, and hangs a
``GradNode`` off the outputs for the tape-free backward engine
(``paddle/fluid/eager/backward.cc:439`` analog in autograd/engine.py).
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax

from ..core import flags
from . import infermeta as _infermeta

_OPS: dict[str, "OpDef"] = {}

# Hooks set by paddle_tpu.amp.debugging (kept here to avoid import cycles):
# _OP_STATS: {(op_name, dtype): count} when operator-stats collection is on.
# _CHECKER_CFG: TensorCheckerConfig scoping the NaN/Inf check per op.
_OP_STATS = None
_CHECKER_CFG = None


class OpDef:
    __slots__ = (
        "name", "fn", "fwd", "bwd", "n_outputs", "jit_fn", "jit_fwd",
        "jit_bwd", "static_argnames", "nondiff_argnums", "_grad_ops",
    )

    def __init__(self, name, fn, fwd=None, bwd=None, n_outputs=1,
                 static_argnames=(), nondiff_argnums=()):
        self.name = name
        self.fn = fn
        self.fwd = fwd
        self.bwd = bwd
        self.n_outputs = n_outputs
        self.static_argnames = tuple(static_argnames)
        self.nondiff_argnums = frozenset(nondiff_argnums)
        if flags.flag("FLAGS_eager_jit_ops"):
            self.jit_fn = jax.jit(fn, static_argnames=self.static_argnames)
            self.jit_fwd = (
                jax.jit(fwd, static_argnames=self.static_argnames)
                if fwd is not None else None)
            self.jit_bwd = (
                jax.jit(bwd, static_argnames=self.static_argnames)
                if bwd is not None else None)
        else:  # pragma: no cover - debug escape hatch
            self.jit_fn, self.jit_fwd, self.jit_bwd = fn, fwd, bwd

    def __repr__(self):
        return f"OpDef({self.name})"


def register_op(name, fn=None, *, fwd=None, bwd=None, n_outputs=1,
                static_argnames=(), nondiff_argnums=()):
    """Register an op. Usable as a decorator over the plain forward."""

    def _register(f):
        op = OpDef(name, f, fwd=fwd, bwd=bwd, n_outputs=n_outputs,
                   static_argnames=static_argnames,
                   nondiff_argnums=nondiff_argnums)
        _OPS[name] = op
        return op

    if fn is not None:
        return _register(fn)
    return _register


def get_op(name: str) -> OpDef:
    return _OPS[name]


_DYN_OPS: dict = {}


def cached_apply(name, fn, *args, n_outputs=1, **attrs):
    """Dispatch ``fn`` through a cached ad-hoc OpDef (full dispatch
    semantics: jit cache, NaN checks, eager tape) without entering the
    global registry sweep.  The OpDef is rebuilt whenever the attr-key
    set (or output arity) changes so ``static_argnames`` never goes
    stale.  Shared by the domain namespaces (sparse/audio/geometric/
    rnn/...)."""
    # Key on the code object too: per-call closures share one compiled
    # OpDef, but two modules reusing an op name with different bodies
    # get distinct entries instead of silently running the first fn.
    key = (name, getattr(fn, "__code__", fn))
    op = _DYN_OPS.get(key)
    if op is None or set(op.static_argnames) != set(attrs.keys()) \
            or op.n_outputs != n_outputs:
        op = OpDef(name, fn, n_outputs=n_outputs,
                   static_argnames=tuple(attrs.keys()))
        _DYN_OPS[key] = op
    return apply(op, *args, **attrs)


def grad_op(op: OpDef, attrs: dict, n_outs: int, diff_idx: tuple,
            n_inputs: int) -> OpDef:
    """OpDef computing d(inputs[diff_idx]) from (cotangents, *inputs) —
    used by create_graph=True backward: the VJP is recomputed from the
    op's forward fn and dispatched through apply(), so the backward
    itself lands on the tape (second-order edges recorded).  Reference
    analog: the eager engine's double-grad support
    (paddle/fluid/eager/general_grad.h + backward.yaml double_grad).

    Signature of the returned op's fn:
        fn(*cotangents[n_outs], *forward_inputs[n_inputs]) ->
            grads for the diff_idx positions (bare array when one).
    The cache lives ON the OpDef instance — dynamically-created OpDefs
    can share a name with different closures (MoE per-layer ops), so a
    name-keyed global cache would hand back the wrong forward."""
    cache = getattr(op, "_grad_ops", None)
    if cache is None:
        cache = {}
        try:
            op._grad_ops = cache
        except AttributeError:  # non-OpDef custom op objects
            pass
    key = (tuple(sorted(attrs.items())), n_outs, diff_idx, n_inputs)
    cached = cache.get(key)
    if cached is not None:
        return cached
    fwd_fn = op.fn

    def bwd_plain(*args):
        cots = args[:n_outs]
        ins = list(args[n_outs:])

        def f(*dins):
            full = list(ins)
            for j, d in zip(diff_idx, dins):
                full[j] = d
            return fwd_fn(*full, **attrs)

        _, vjp_fn = jax.vjp(f, *[ins[j] for j in diff_idx])
        cot = cots[0] if n_outs == 1 else tuple(cots)
        gs = vjp_fn(cot)
        # Single diff input -> bare array: apply()'s n_outputs=1 contract
        # (and the cotangent structure of THIS op's own vjp) expects it.
        return gs[0] if len(diff_idx) == 1 else tuple(gs)

    gop = OpDef(
        f"grad[{op.name}]", bwd_plain, n_outputs=max(1, len(diff_idx)),
        nondiff_argnums=tuple(n_outs + i for i in range(n_inputs)
                              if i not in diff_idx))
    cache[key] = gop
    return gop


def all_ops() -> dict:
    return dict(_OPS)


# ---------------------------------------------------------------------------
# Eager dispatch (the ad_func analog).
# ---------------------------------------------------------------------------

def apply(op: OpDef, *tensor_args, attrs=None, **kw_attrs):
    """Run ``op`` on Tensor arguments; returns Tensor(s).

    Mirrors the generated ad_func control flow (eager_gen.py:321): collect
    autograd metadata -> decide require_any_grad -> forward -> node creation
    -> set edges/history.  AMP auto-cast hooks in via ops.amp_transform.
    """
    from ..core.tensor import Tensor
    from ..autograd import engine as _engine
    from ..amp import state as _amp_state

    attrs = dict(attrs or {})
    attrs.update(kw_attrs)

    # Derived grad ops ("grad[<name>]", create_graph backward) skip AMP:
    # the normal backward (jit_bwd) is never amp-cast either, and their
    # names are in no AMP list — casting here would make create_graph
    # grads numerically diverge from plain ones under auto_cast.
    if _amp_state.amp_enabled() and not op.name.startswith("grad["):
        tensor_args = _amp_state.amp_transform(op.name, tensor_args)

    datas = []
    need_grad = False
    grad_on = _engine.is_grad_enabled()
    for t in tensor_args:
        if isinstance(t, Tensor):
            datas.append(t._data)
            if grad_on and not t.stop_gradient:
                need_grad = True
        else:
            datas.append(t)

    # InferMeta-style eager validation (ops/infermeta.py): metadata-only
    # checks with reference-style InvalidArgument messages.  Traced
    # values go through unchanged — XLA's shape system owns that path.
    if op.name in _infermeta._VALIDATORS and not any(
            isinstance(d, jax.core.Tracer) for d in datas):
        _infermeta.validate(op.name, datas, attrs)

    if need_grad and op.jit_fwd is not None:
        out_data, saved = op.jit_fwd(*datas, **attrs)
        node = _engine.GradNode(op, saved, tensor_args, attrs)
    elif need_grad:
        # jax.vjp fallback for ops without a hand-written backward pairing.
        fun = functools.partial(op.fn, **attrs) if attrs else op.fn
        diff_idx = [i for i in range(len(datas))
                    if i not in op.nondiff_argnums]
        closed = _close_over(fun, datas, diff_idx)
        out_data, vjp_fn = jax.vjp(closed, *[datas[i] for i in diff_idx])
        node = _engine.GradNode(op, vjp_fn, tensor_args, attrs,
                                vjp_fallback=True, diff_idx=diff_idx)
    else:
        out_data = op.jit_fn(*datas, **attrs)
        node = None

    if flags.flag("FLAGS_check_nan_inf") and (
            _CHECKER_CFG is None or _CHECKER_CFG._applies_to(op.name)):
        _check_nan_inf(op.name, out_data)
    if _OP_STATS is not None:
        outs = out_data if isinstance(out_data, (tuple, list)) \
            else [out_data]
        for o in outs:
            if o is not None:
                k = (op.name, str(o.dtype))
                _OP_STATS[k] = _OP_STATS.get(k, 0) + 1

    # Ops whose outputs are all non-differentiable dtypes (bool/int —
    # comparisons, argmax...) never get a grad node, matching the
    # reference's IsDifferentiable check in ad_funcs.
    if need_grad:
        import jax.numpy as jnp

        outs_flat = out_data if isinstance(out_data, (tuple, list)) \
            else [out_data]
        if not any(o is not None and jnp.issubdtype(o.dtype, jnp.inexact)
                   for o in outs_flat):
            need_grad = False
            node = None

    if op.n_outputs == 1 and not isinstance(out_data, (tuple, list)):
        out = Tensor(out_data, stop_gradient=not need_grad)
        if node is not None:
            node.bind_outputs([out])
        return out
    outs = [Tensor(o, stop_gradient=not need_grad) if o is not None else None
            for o in out_data]
    if node is not None:
        node.bind_outputs(outs)
    return tuple(outs)


def _close_over(fun, datas, diff_idx):
    if len(diff_idx) == len(datas):
        return fun

    def closed(*diff_args):
        full = list(datas)
        for i, a in zip(diff_idx, diff_args):
            full[i] = a
        return fun(*full)

    return closed


def _check_nan_inf(name, out):
    """Reference: fluid/eager/nan_inf_utils.h:38 CheckTensorHasNanOrInf."""
    import jax.numpy as jnp
    leaves = out if isinstance(out, (tuple, list)) else [out]
    for leaf in leaves:
        if leaf is None or not jnp.issubdtype(leaf.dtype, jnp.floating):
            continue
        if bool(jnp.any(~jnp.isfinite(leaf))):
            msg = f"Operator {name} output contains NaN/Inf"
            if flags.flag("FLAGS_check_nan_inf_level") == 0:
                raise FloatingPointError(msg)
            print(f"[check_nan_inf] {msg}")
