"""Activation ops.

Reference: ``paddle/phi/kernels/activation_kernel.h`` +
``python/paddle/nn/functional/activation.py``.  All are single fusable
elementwise jax expressions (XLA fuses them into the surrounding matmul
epilogue on TPU), with hand-written grads for the hot ones.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import apply, register_op


def _unary(name, fn, grad_fn=None, save_out=False, static_argnames=()):
    if grad_fn is None:
        op = register_op(name, fn, static_argnames=static_argnames)
    else:
        def fwd(x, **attrs):
            out = fn(x, **attrs)
            return out, (out if save_out else x)

        def bwd(saved, g, **attrs):
            return (grad_fn(saved, g, **attrs),)

        op = register_op(name, fn, fwd=fwd, bwd=bwd,
                         static_argnames=static_argnames)
    return op


relu_op = _unary("relu", jax.nn.relu,
                 lambda out, g: g * (out > 0).astype(g.dtype), save_out=True)
relu6_op = _unary("relu6", jax.nn.relu6,
                  lambda x, g: g * ((x > 0) & (x < 6)).astype(g.dtype))
sigmoid_op = _unary("sigmoid", jax.nn.sigmoid,
                    lambda out, g: g * out * (1 - out), save_out=True)
tanh_op = _unary("tanh", jnp.tanh,
                 lambda out, g: g * (1 - out * out), save_out=True)
silu_op = _unary(
    "silu", jax.nn.silu,
    lambda x, g: g * (jax.nn.sigmoid(x) * (1 + x * (1 - jax.nn.sigmoid(x)))))


def _gelu_fn(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


gelu_op = register_op("gelu", _gelu_fn, static_argnames=("approximate",))

leaky_relu_op = _unary(
    "leaky_relu",
    lambda x, negative_slope=0.01: jax.nn.leaky_relu(x, negative_slope),
    lambda x, g, negative_slope=0.01: g * jnp.where(
        x >= 0, jnp.ones_like(x), jnp.full_like(x, negative_slope)),
    static_argnames=("negative_slope",))

elu_op = register_op("elu", lambda x, alpha=1.0: jax.nn.elu(x, alpha),
                     static_argnames=("alpha",))
selu_op = register_op(
    "selu",
    lambda x, scale=1.0507009873554805, alpha=1.6732632423543772:
    scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)),
    static_argnames=("scale", "alpha"))
celu_op = register_op("celu", lambda x, alpha=1.0: jax.nn.celu(x, alpha),
                      static_argnames=("alpha",))
softplus_op = register_op(
    "softplus",
    lambda x, beta=1.0, threshold=20.0: jnp.where(
        x * beta > threshold, x, jnp.logaddexp(x * beta, 0.0) / beta),
    static_argnames=("beta", "threshold"))
softsign_op = _unary("softsign", jax.nn.soft_sign,
                     lambda x, g: g / jnp.square(1 + jnp.abs(x)))
hardtanh_op = register_op(
    "hardtanh", lambda x, min=-1.0, max=1.0: jnp.clip(x, min, max),
    static_argnames=("min", "max"))
hardsigmoid_op = register_op(
    "hardsigmoid",
    lambda x, slope=1 / 6, offset=0.5: jnp.clip(slope * x + offset, 0.0, 1.0),
    static_argnames=("slope", "offset"))
hardswish_op = _unary(
    "hardswish", lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0,
    lambda x, g: g * jnp.where(
        x <= -3, jnp.zeros_like(x),
        jnp.where(x >= 3, jnp.ones_like(x), (2 * x + 3) / 6)))
swish_op = _unary("swish", jax.nn.silu,
                  lambda x, g: g * (jax.nn.sigmoid(x)
                                    * (1 + x * (1 - jax.nn.sigmoid(x)))))
mish_op = register_op(
    "mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
tanhshrink_op = register_op("tanhshrink", lambda x: x - jnp.tanh(x))
softshrink_op = register_op(
    "softshrink",
    lambda x, threshold=0.5: jnp.where(
        x > threshold, x - threshold,
        jnp.where(x < -threshold, x + threshold, jnp.zeros_like(x))),
    static_argnames=("threshold",))
hardshrink_op = register_op(
    "hardshrink",
    lambda x, threshold=0.5: jnp.where(
        jnp.abs(x) > threshold, x, jnp.zeros_like(x)),
    static_argnames=("threshold",))
thresholded_relu_op = register_op(
    "thresholded_relu",
    lambda x, threshold=1.0, value=0.0: jnp.where(
        x > threshold, x, jnp.full_like(x, value)),
    static_argnames=("threshold", "value"))
log_sigmoid_op = register_op("log_sigmoid", jax.nn.log_sigmoid)


def _prelu_plain(x, weight, data_format="NCHW"):
    if weight.ndim == 1 and weight.shape[0] > 1:
        shape = ((1, -1) + (1,) * (x.ndim - 2)) if data_format == "NCHW" \
            else ((1,) * (x.ndim - 1) + (-1,))
        w = weight.reshape(shape)
    else:
        w = weight
    return jnp.where(x >= 0, x, w * x)


prelu_op = register_op("prelu", _prelu_plain,
                       static_argnames=("data_format",))


def _glu_plain(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


glu_op = register_op("glu", _glu_plain, static_argnames=("axis",))


def _swiglu_plain(x, y=None):
    """Reference: phi fused swiglu (phi/kernels/fusion/); silu(x) * y."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


swiglu_op = register_op("swiglu", _swiglu_plain)
