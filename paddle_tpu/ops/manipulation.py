"""Shape/layout manipulation + indexing ops.

Reference: ``python/paddle/tensor/manipulation.py`` and the corresponding
ops.yaml entries (reshape/transpose/concat/split/gather/...).  Grad pairings
mirror backward.yaml (e.g. ``concat_grad`` splits the cotangent;
``gather_grad`` scatter-adds).  All static attributes (shapes, axes) are jit
static args, so XLA sees only static-shape programs — the tiling-friendly
form for the MXU.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from .registry import apply, register_op


def _t(x):
    return tuple(int(v) for v in x) if x is not None else None


# -- cast -------------------------------------------------------------------

cast_op = register_op(
    "cast", lambda x, dtype: x.astype(dtype),
    fwd=lambda x, dtype: (x.astype(dtype), x),
    bwd=lambda x, g, dtype: (g.astype(x.dtype),),
    static_argnames=("dtype",))


def cast(x, dtype):
    return apply(cast_op, x, dtype=dtype_mod.convert_dtype(dtype))


# -- reshape family ---------------------------------------------------------

reshape_op = register_op(
    "reshape", lambda x, shape: jnp.reshape(x, shape),
    fwd=lambda x, shape: (jnp.reshape(x, shape), x),
    bwd=lambda x, g, shape: (jnp.reshape(g, jnp.shape(x)),),
    static_argnames=("shape",))


def reshape(x, shape, name=None):
    from ..core.tensor import Tensor

    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    return apply(reshape_op, x, shape=tuple(shape))


transpose_op = register_op(
    "transpose", lambda x, perm: jnp.transpose(x, perm),
    fwd=lambda x, perm: (jnp.transpose(x, perm), None),
    bwd=lambda saved, g, perm: (jnp.transpose(g, _inv_perm(perm)),),
    static_argnames=("perm",))


def _inv_perm(perm):
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    return tuple(inv)


def transpose(x, perm, name=None):
    return apply(transpose_op, x, perm=_t(perm))


def t(x, name=None):
    if x.ndim < 2:
        return assign(x)
    return transpose(x, list(range(x.ndim - 2)) + [x.ndim - 1, x.ndim - 2])


squeeze_op = register_op(
    "squeeze", lambda x, axis=None: jnp.squeeze(x, axis=axis),
    fwd=lambda x, axis=None: (jnp.squeeze(x, axis=axis), x),
    bwd=lambda x, g, axis=None: (jnp.reshape(g, jnp.shape(x)),),
    static_argnames=("axis",))


def squeeze(x, axis=None, name=None):
    # Out-of-range axes pass through raw so the squeeze InferMeta
    # validator rejects them (silently wrapping with % would accept
    # axis=5 on a 2-D input).
    if isinstance(axis, (list, tuple)):
        if all(-x.ndim <= int(a) < x.ndim for a in axis):
            axis = tuple(int(a) % x.ndim for a in axis)
            axis = tuple(a for a in axis if x.shape[a] == 1)
            if not axis:
                return assign(x)
        else:
            axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
        if -x.ndim <= axis < x.ndim:
            axis %= x.ndim
            if x.shape[axis] != 1:
                return assign(x)
    return apply(squeeze_op, x, axis=axis)


unsqueeze_op = register_op(
    "unsqueeze", lambda x, axis: jnp.expand_dims(x, axis),
    fwd=lambda x, axis: (jnp.expand_dims(x, axis), x),
    bwd=lambda x, g, axis: (jnp.reshape(g, jnp.shape(x)),),
    static_argnames=("axis",))


def unsqueeze(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    else:
        axis = int(axis)
    return apply(unsqueeze_op, x, axis=axis)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    from . import infermeta

    nd = x.ndim
    if nd == 0:
        return reshape(x, [1])
    # host path (rides reshape with a precomputed shape), so the axis
    # attrs never reach registry.apply's validator hook — check by hand
    infermeta.validate("flatten", (x,), {"start_axis": start_axis,
                                         "stop_axis": stop_axis})
    start = start_axis % nd
    stop = stop_axis % nd
    shape = x.shape
    new_shape = (list(shape[:start])
                 + [int(np.prod(shape[start:stop + 1]))]
                 + list(shape[stop + 1:]))
    return reshape(x, new_shape)


expand_op = register_op(
    "expand", lambda x, shape: jnp.broadcast_to(x, shape),
    fwd=lambda x, shape: (jnp.broadcast_to(x, shape), x),
    bwd=lambda x, g, shape: (_unbroadcast_to(g, jnp.shape(x)),),
    static_argnames=("shape",))


def _unbroadcast_to(g, shape):
    from .math import unbroadcast

    return unbroadcast(g, shape).reshape(shape)


def expand(x, shape, name=None):
    shape = [x.shape[i - (len(shape) - x.ndim)] if int(s) == -1 else int(s)
             for i, s in enumerate(shape)]
    return apply(expand_op, x, shape=tuple(shape))


broadcast_to = expand


def expand_as(x, y, name=None):
    from . import infermeta

    # host path (rides expand with the target's shape), so the pair
    # never reaches registry.apply's validator hook — check by hand
    infermeta.validate("expand_as", (x,),
                       {"target_shape": tuple(y.shape)})
    return expand(x, y.shape)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


tile_op = register_op(
    "tile", lambda x, repeat_times: jnp.tile(x, repeat_times),
    static_argnames=("repeat_times",))


def tile(x, repeat_times, name=None):
    return apply(tile_op, x, repeat_times=_t(repeat_times))


# -- concat / split / stack -------------------------------------------------

def _concat_plain(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def _concat_fwd(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis), xs


def _concat_bwd(xs, g, axis=0):
    sizes = [jnp.shape(x)[axis] for x in xs]
    splits = list(np.cumsum(sizes))[:-1]
    return tuple(jnp.split(g, splits, axis=axis))


concat_op = register_op("concat", _concat_plain, fwd=_concat_fwd,
                        bwd=_concat_bwd, static_argnames=("axis",))


def concat(x, axis=0, name=None):
    from ..core.tensor import Tensor

    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(concat_op, *x, axis=int(axis))


def _stack_plain(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


stack_op = register_op(
    "stack", _stack_plain,
    fwd=lambda *xs, axis=0: (jnp.stack(xs, axis=axis), len(xs)),
    bwd=lambda n, g, axis=0: tuple(
        jnp.squeeze(p, axis=axis)
        for p in jnp.split(g, jnp.shape(g)[axis], axis=axis)),
    static_argnames=("axis",))


def stack(x, axis=0, name=None):
    return apply(stack_op, *x, axis=int(axis))


def _stack_bwd_fix():
    pass


split_op = register_op(
    "split",
    lambda x, indices=None, axis=0: tuple(jnp.split(x, indices, axis=axis)),
    fwd=lambda x, indices=None, axis=0: (
        tuple(jnp.split(x, indices, axis=axis)), None),
    bwd=lambda saved, gs, axis=0, indices=None: (
        jnp.concatenate(gs, axis=axis),),
    static_argnames=("indices", "axis"), n_outputs=0)


def split(x, num_or_sections, axis=0, name=None):
    from ..core.tensor import Tensor

    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    if not -x.ndim <= axis < x.ndim:
        # Out-of-range axis goes through raw so the split InferMeta
        # validator rejects it with the reference-style message.
        return list(apply(split_op, x,
                          indices=(num_or_sections
                                   if isinstance(num_or_sections, int)
                                   else tuple(num_or_sections)),
                          axis=axis))
    axis %= x.ndim
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        indices = int(num_or_sections)
        n_out = num_or_sections
    else:
        sections = [s if s != -1 else dim - sum(
            v for v in num_or_sections if v != -1)
            for s in num_or_sections]
        indices = tuple(int(v) for v in np.cumsum(sections)[:-1])
        n_out = len(sections)
    split_op.n_outputs = n_out
    outs = apply(split_op, x, indices=indices, axis=axis)
    split_op.n_outputs = 0
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    from . import infermeta

    # host path (delegates to split), so the count/axis attrs never
    # reach registry.apply's validator hook — check by hand
    infermeta.validate("chunk", (x,), {"chunks": int(chunks),
                                       "axis": int(axis)})
    return split(x, chunks, axis)


def unstack(x, axis=0, num=None, name=None):
    axis = int(axis) % x.ndim
    parts = split(x, x.shape[axis], axis)
    return [squeeze(p, axis) for p in parts]


def unbind(x, axis=0):
    from . import infermeta

    # host path (split + squeeze), so the axis attr never reaches
    # registry.apply's validator hook — check by hand before the % wrap
    infermeta.validate("unbind", (x,), {"axis": axis})
    return unstack(x, axis)


# -- flip / roll / pad ------------------------------------------------------

flip_op = register_op(
    "flip", lambda x, axis: jnp.flip(x, axis),
    fwd=lambda x, axis: (jnp.flip(x, axis), None),
    bwd=lambda s, g, axis: (jnp.flip(g, axis),),
    static_argnames=("axis",))


def flip(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    else:
        axis = int(axis)
    return apply(flip_op, x, axis=axis)


roll_op = register_op(
    "roll", lambda x, shifts, axis=None: jnp.roll(x, shifts, axis),
    fwd=lambda x, shifts, axis=None: (jnp.roll(x, shifts, axis), None),
    bwd=lambda s, g, shifts, axis=None: (
        jnp.roll(g, tuple(-v for v in shifts)
                 if isinstance(shifts, tuple) else -shifts, axis),),
    static_argnames=("shifts", "axis"))


def roll(x, shifts, axis=None, name=None):
    shifts = _t(shifts) if isinstance(shifts, (list, tuple)) else int(shifts)
    axis = _t(axis) if isinstance(axis, (list, tuple)) else (
        int(axis) if axis is not None else None)
    return apply(roll_op, x, shifts=shifts, axis=axis)


pad_op = register_op(
    "pad", lambda x, pad_width, mode="constant", value=0.0: (
        jnp.pad(x, pad_width, mode=mode, constant_values=value)
        if mode == "constant" else jnp.pad(x, pad_width, mode=mode)),
    static_argnames=("pad_width", "mode", "value"))


def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):
    """paddle.nn.functional.pad with int-list pad (last-dim-first pairs)."""
    nd = x.ndim
    if len(pad) == 2 * nd:
        pairs = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(nd)]
        pad_width = tuple(pairs)
    else:
        # pad applies to trailing dims, paddle order: last dim first.
        n = len(pad) // 2
        pairs = [(0, 0)] * nd
        for i in range(n):
            dim = nd - 1 - i
            pairs[dim] = (int(pad[2 * i]), int(pad[2 * i + 1]))
        pad_width = tuple(pairs)
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    return apply(pad_op, x, pad_width=pad_width, mode=jmode,
                 value=float(value))


# -- gather / scatter / index ops ------------------------------------------

def _gather_plain(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def _gather_fwd(x, index, axis=0):
    return jnp.take(x, index, axis=axis), (x, index)


def _gather_bwd(saved, g, axis=0):
    x, index = saved
    z = jnp.zeros(jnp.shape(x), g.dtype)
    return (_index_add(z, index, g, axis).astype(x.dtype), None)


def _index_add(z, index, g, axis):
    # The module-level ``slice`` op (paddle API parity) shadows the builtin;
    # ``builtins_slice`` is this module's alias for it.
    idx = [builtins_slice(None)] * z.ndim
    idx[axis] = index
    return z.at[tuple(idx)].add(g)


gather_op = register_op("gather", _gather_plain, fwd=_gather_fwd,
                        bwd=_gather_bwd, static_argnames=("axis",),
                        nondiff_argnums=(1,))


def gather(x, index, axis=0, name=None):
    from ..core.tensor import Tensor

    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(index, Tensor) and index.ndim > 1:
        index = reshape(index, [-1])
    return apply(gather_op, x, index, axis=int(axis))


index_select = gather


def _take_along_fwd(x, index, axis=0):
    return jnp.take_along_axis(x, index, axis=axis), (x, index)


def _take_along_bwd(saved, g, axis=0):
    x, index = saved
    z = jnp.zeros(jnp.shape(x), g.dtype)
    return (z.at[_along_axis_idx(index, axis, jnp.shape(x))].add(g)
            .astype(x.dtype), None)


def _along_axis_idx(index, axis, shape):
    nd = len(shape)
    axis = axis % nd
    idxs = []
    for d in range(nd):
        if d == axis:
            idxs.append(index)
        else:
            r = jnp.arange(index.shape[d])
            r = r.reshape([-1 if i == d else 1 for i in range(nd)])
            idxs.append(jnp.broadcast_to(r, index.shape))
    return tuple(idxs)


take_along_axis_op = register_op(
    "take_along_axis",
    lambda x, index, axis=0: jnp.take_along_axis(x, index, axis=axis),
    fwd=_take_along_fwd, bwd=_take_along_bwd, static_argnames=("axis",),
    nondiff_argnums=(1,))


def take_along_axis(x, indices, axis, broadcast=True, name=None):
    return apply(take_along_axis_op, x, indices, axis=int(axis))


def _put_along_fwd(x, index, value, axis=0, reduce="assign"):
    out = _put_along_plain(x, index, value, axis, reduce)
    # multiply's backward needs the forward output; other reduces don't —
    # don't hold the extra residual for them.
    keep = out if reduce in ("multiply", "mul") else None
    return out, (x, index, value, keep)


def _put_along_plain(x, index, value, axis=0, reduce="assign"):
    ii = _along_axis_idx(index, axis, jnp.shape(x))
    if reduce == "assign":
        return x.at[ii].set(value)
    if reduce == "add":
        return x.at[ii].add(value)
    if reduce == "multiply" or reduce == "mul":
        return x.at[ii].multiply(value)
    raise ValueError(f"unsupported reduce {reduce}")


def _put_along_bwd(saved, g, axis=0, reduce="assign"):
    x, index, value, out = saved
    ii = _along_axis_idx(index, axis, jnp.shape(x))
    gv = g[ii]
    if reduce == "assign":
        gx = g.at[ii].set(jnp.zeros_like(gv))
    elif reduce in ("multiply", "mul"):
        # y = x * prod(values written to the cell): dx scales by the full
        # product (g.at[ii].multiply applies every factor, duplicate
        # indices included); dvalue_j = g * out/value_j (product of x and
        # the OTHER factors).  value_j == 0 falls back to g*x — exact when
        # indices are unique, best-effort for duplicated zero writes.
        vb = jnp.broadcast_to(value, gv.shape).astype(g.dtype)
        gx = g.at[ii].multiply(vb)
        gv = gv * jnp.where(vb == 0, x[ii].astype(gv.dtype),
                            out[ii].astype(gv.dtype) / jnp.where(
                                vb == 0, jnp.ones_like(vb), vb))
    else:  # add
        gx = g
    if jnp.ndim(value) == 0:
        gv = jnp.sum(gv)
    return gx, None, gv.astype(jnp.result_type(gv))


put_along_axis_op = register_op(
    "put_along_axis", _put_along_plain, fwd=_put_along_fwd,
    bwd=_put_along_bwd, static_argnames=("axis", "reduce"),
    nondiff_argnums=(1,))


def put_along_axis(x, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    return apply(put_along_axis_op, x, indices, values, axis=int(axis),
                 reduce=reduce)


def scatter(x, index, updates, overwrite=True, name=None):
    """paddle.scatter: writes rows of ``updates`` at row ``index`` of x."""
    op = scatter_op if overwrite else scatter_add_op
    return apply(op, x, index, updates)


def _scatter_fwd(x, index, updates):
    return x.at[index].set(updates), (x, index)


def _scatter_bwd(saved, g, **_):
    x, index = saved
    gu = g[index]
    gx = g.at[index].set(jnp.zeros_like(gu))
    return gx, None, gu


scatter_op = register_op(
    "scatter", lambda x, index, updates: x.at[index].set(updates),
    fwd=_scatter_fwd, bwd=_scatter_bwd, nondiff_argnums=(1,))

scatter_add_op = register_op(
    "scatter_add", lambda x, index, updates: x.at[index].add(updates),
    fwd=lambda x, index, updates: (x.at[index].add(updates), (x, index)),
    bwd=lambda saved, g, **_: (g, None, g[saved[1]]),
    nondiff_argnums=(1,))


def scatter_nd_add(x, index, updates, name=None):
    return apply(scatter_nd_add_op, x, index, updates)


def _snd_plain(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


scatter_nd_add_op = register_op(
    "scatter_nd_add", _snd_plain,
    fwd=lambda x, index, updates: (_snd_plain(x, index, updates), index),
    bwd=lambda index, g, **_: (
        g, None, g[tuple(jnp.moveaxis(index, -1, 0))]),
    nondiff_argnums=(1,))


def gather_nd(x, index, name=None):
    return apply(gather_nd_op, x, index)


def _gnd_plain(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


gather_nd_op = register_op(
    "gather_nd", _gnd_plain,
    fwd=lambda x, index: (_gnd_plain(x, index), (x, index)),
    bwd=lambda saved, g, **_: (
        jnp.zeros(jnp.shape(saved[0]), g.dtype).at[
            tuple(jnp.moveaxis(saved[1], -1, 0))].add(g).astype(
                saved[0].dtype), None),
    nondiff_argnums=(1,))


# -- where / masked ---------------------------------------------------------

where_op = register_op(
    "where", jnp.where,
    fwd=lambda c, x, y: (jnp.where(c, x, y), (c, x, y)),
    bwd=lambda saved, g: (
        None,
        _where_unbroadcast(saved[0], g, saved[1], True),
        _where_unbroadcast(saved[0], g, saved[2], False)),
    nondiff_argnums=(0,))


def _where_unbroadcast(c, g, x, take_true):
    from .math import unbroadcast

    gx = jnp.where(c, g, jnp.zeros_like(g)) if take_true else \
        jnp.where(c, jnp.zeros_like(g), g)
    return unbroadcast(gx, jnp.shape(x))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(where_op, condition, x, y)


def nonzero(x, as_tuple=False):
    from ..core.tensor import Tensor
    from . import infermeta

    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("nonzero", (arr,), {})
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v[:, None], dtype=jnp.int64))
                     for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=jnp.int64))


def masked_select(x, mask, name=None):
    """Differentiable bool-mask selection (concrete mask; grads flow back
    to x via getitem's vjp — scatter-add at the selected positions)."""
    from ..core.tensor import Tensor
    from . import infermeta

    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    # host path (getitem), so it never passes registry.apply's
    # validator hook — fire the InferMeta check by hand
    infermeta.validate("masked_select", (x._data, m), {})
    return getitem(x, Tensor(jnp.asarray(m.astype(bool))))


def masked_fill(x, mask, value, name=None):
    from ..core.tensor import Tensor

    if isinstance(value, Tensor):
        value = value._data
    return apply(masked_fill_op, x, mask, value)


masked_fill_op = register_op(
    "masked_fill", lambda x, mask, value: jnp.where(mask, value, x),
    fwd=lambda x, mask, value: (jnp.where(mask, value, x), mask),
    bwd=lambda mask, g, **_: (jnp.where(mask, jnp.zeros_like(g), g), None,
                              None),
    nondiff_argnums=(1, 2))


# -- sort / topk / unique ---------------------------------------------------

topk_op = register_op(
    "topk", lambda x, k, axis=-1, largest=True: _topk(x, k, axis, largest),
    static_argnames=("k", "axis", "largest"), n_outputs=2)


def _topk(x, k, axis, largest):
    # SPMD rule (reference top_k spmd rule: batch dims pass through):
    # ``jax.lax.top_k`` replicates its output under GSPMD, silently
    # all-gathering a batch-sharded operand.  A variadic ``lax.sort``
    # propagates the batch sharding, so topk is routed through one
    # stable key sort carrying the index payload; negating the key for
    # ``largest`` keeps top_k's lowest-index-first tie order.
    xm = jnp.moveaxis(x, axis, -1)
    iota = jax.lax.broadcasted_iota(jnp.int32, xm.shape, xm.ndim - 1)
    keys = -xm if largest else xm
    sk, si = jax.lax.sort((keys, iota), dimension=-1, num_keys=1,
                          is_stable=True)
    vals = -sk[..., :k] if largest else sk[..., :k]
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(si[..., :k], -1, axis).astype(jnp.int64))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    from ..core.tensor import Tensor

    if isinstance(k, Tensor):
        k = int(k.item())
    return apply(topk_op, x, k=int(k), axis=int(axis), largest=bool(largest))


sort_op = register_op(
    "sort", lambda x, axis=-1, descending=False: (
        -jnp.sort(-x, axis=axis) if descending else jnp.sort(x, axis=axis)),
    static_argnames=("axis", "descending"))
argsort_op = register_op(
    "argsort", lambda x, axis=-1, descending=False: (
        jnp.argsort(-x, axis=axis) if descending
        else jnp.argsort(x, axis=axis)).astype(jnp.int64),
    static_argnames=("axis", "descending"))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return apply(sort_op, x, axis=int(axis), descending=bool(descending))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    return apply(argsort_op, x, axis=int(axis), descending=bool(descending))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    from ..core.tensor import Tensor
    from . import infermeta

    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("unique", (arr,), {"axis": axis})
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    from ..core.tensor import Tensor
    from . import infermeta

    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    # host path (pure numpy), so the attrs never reach registry.apply's
    # validator hook — check by hand
    infermeta.validate("unique_consecutive", (arr,),
                       {"axis": axis, "dtype": dtype})
    if arr.ndim == 0 or arr.size == 0:
        return Tensor(jnp.asarray(arr))
    flat = arr.reshape(-1) if axis is None else arr
    keep = np.concatenate([[True], flat[1:] != flat[:-1]]) \
        if axis is None else None
    out = flat[keep]
    results = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        results.append(Tensor(jnp.asarray(inv, dtype=np.int64)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, flat.size))
        results.append(Tensor(jnp.asarray(counts, dtype=np.int64)))
    return results[0] if len(results) == 1 else tuple(results)


# -- misc -------------------------------------------------------------------

assign_op = register_op(
    "assign", lambda x: jnp.asarray(x),
    fwd=lambda x: (jnp.asarray(x), None),
    bwd=lambda s, g: (g,))


def assign(x, output=None):
    from ..core.tensor import Tensor

    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    out = apply(assign_op, x)
    if output is not None:
        output.set_value(out)
        return output
    return out


tril_op = register_op(
    "tril", lambda x, diagonal=0: jnp.tril(x, diagonal),
    fwd=lambda x, diagonal=0: (jnp.tril(x, diagonal), None),
    bwd=lambda s, g, diagonal=0: (jnp.tril(g, diagonal),),
    static_argnames=("diagonal",))
triu_op = register_op(
    "triu", lambda x, diagonal=0: jnp.triu(x, diagonal),
    fwd=lambda x, diagonal=0: (jnp.triu(x, diagonal), None),
    bwd=lambda s, g, diagonal=0: (jnp.triu(g, diagonal),),
    static_argnames=("diagonal",))


def tril(x, diagonal=0, name=None):
    return apply(tril_op, x, diagonal=int(diagonal))


def triu(x, diagonal=0, name=None):
    return apply(triu_op, x, diagonal=int(diagonal))


diag_op = register_op(
    "diag", lambda x, offset=0: jnp.diag(x, offset),
    static_argnames=("offset",))


def diag(x, offset=0, padding_value=0, name=None):
    return apply(diag_op, x, offset=int(offset))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(diagonal_op, x, offset=int(offset), axis1=int(axis1),
                 axis2=int(axis2))


diagonal_op = register_op(
    "diagonal",
    lambda x, offset=0, axis1=0, axis2=1: jnp.diagonal(
        x, offset, axis1, axis2),
    static_argnames=("offset", "axis1", "axis2"))

repeat_interleave_op = register_op(
    "repeat_interleave",
    # per-element repeats ride as a tuple (static args must hash);
    # jnp.repeat wants an array back
    lambda x, repeats, axis=None: jnp.repeat(
        x, np.asarray(repeats) if isinstance(repeats, tuple)
        else repeats, axis=axis),
    static_argnames=("repeats", "axis"))


def repeat_interleave(x, repeats, axis=None, name=None):
    from ..core.tensor import Tensor

    if isinstance(repeats, Tensor):
        repeats = tuple(int(v) for v in repeats.numpy().tolist())
    return apply(repeat_interleave_op, x, repeats=repeats,
                 axis=int(axis) if axis is not None else None)


def one_hot(x, num_classes, name=None):
    return apply(one_hot_op, x, num_classes=int(num_classes))


one_hot_op = register_op(
    "one_hot",
    lambda x, num_classes: jax.nn.one_hot(x, num_classes,
                                          dtype=jnp.float32),
    static_argnames=("num_classes",))


def meshgrid(*args, **kwargs):
    from ..core.tensor import Tensor
    from . import infermeta

    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    datas = [a._data if isinstance(a, Tensor) else a for a in args]
    # host path (list-of-Tensors out), so it never passes
    # registry.apply's validation hook — validate here
    infermeta.validate("meshgrid", datas, {})
    outs = jnp.meshgrid(*datas, indexing="ij")
    return [Tensor(o) for o in outs]


def moveaxis(x, source, destination, name=None):
    return apply(moveaxis_op, x,
                 source=_t(source) if isinstance(source, (list, tuple))
                 else int(source),
                 destination=_t(destination)
                 if isinstance(destination, (list, tuple))
                 else int(destination))


moveaxis_op = register_op(
    "moveaxis",
    lambda x, source, destination: jnp.moveaxis(x, source, destination),
    static_argnames=("source", "destination"))


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError("as_strided is not supported on TPU layouts")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


import builtins  # noqa: E402

builtins_slice = builtins.slice


def slice(x, axes, starts, ends):  # noqa: A001
    from ..core.tensor import Tensor

    idx = [builtins_slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        s = int(s.item()) if isinstance(s, Tensor) else int(s)
        e = int(e.item()) if isinstance(e, Tensor) else int(e)
        idx[ax] = builtins_slice(s, e)
    return getitem(x, tuple(idx))


# -- getitem / setitem ------------------------------------------------------


def _normalize_index(x, idx):
    """Convert Tensors inside an index to jax arrays."""
    from ..core.tensor import Tensor

    if not isinstance(idx, tuple):
        idx = (idx,)
    out = []
    for it in idx:
        if isinstance(it, Tensor):
            d = it._data
            out.append(d)
        elif isinstance(it, (list, np.ndarray)):
            arr = np.asarray(it)
            out.append(jnp.asarray(arr))
        else:
            out.append(it)
    return tuple(out)


def getitem(x, idx):
    from ..autograd import engine as _engine
    from ..core.tensor import Tensor

    jidx = _normalize_index(x, idx)

    # Boolean-mask indexing yields data-dependent shapes: the MASK must be
    # concrete (numpy), but the gather itself stays a jax op so gradients
    # flow (scatter-add backward via vjp) — the reference differentiates
    # through bool-mask selection too.
    has_bool = builtins.any(
        hasattr(it, "dtype") and it.dtype == jnp.bool_ for it in jidx)
    if has_bool:
        jidx = tuple(np.asarray(it) if hasattr(it, "dtype")
                     and it.dtype == jnp.bool_ else it for it in jidx)

    need_grad = _engine.is_grad_enabled() and not x.stop_gradient
    if not need_grad:
        return Tensor(x._data[jidx])
    out_data, vjp_fn = jax.vjp(lambda a: a[jidx], x._data)
    node = _engine.GradNode(_getitem_opdef, vjp_fn, [x], {},
                            vjp_fallback=True, diff_idx=[0])
    out = Tensor(out_data, stop_gradient=False)
    node.bind_outputs([out])
    return out


class _FakeOp:
    name = "getitem"
    jit_bwd = None


_getitem_opdef = _FakeOp()


def setitem(x, idx, value):
    """In-place __setitem__ with autograd (functional under the hood)."""
    from ..autograd import engine as _engine
    from ..core.tensor import Tensor

    jidx = _normalize_index(x, idx)
    has_bool = builtins.any(
        hasattr(it, "dtype") and it.dtype == jnp.bool_ for it in jidx)
    if isinstance(value, Tensor):
        vdata = value._data
    else:
        vdata = jnp.asarray(value, dtype=x.dtype)
    if has_bool:
        # where-based masked assignment (keeps shapes static).
        if len(jidx) == 1:
            mask = jidx[0]
            new = jnp.where(
                mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim)),
                vdata, x._data)
        else:
            raise NotImplementedError("mixed bool advanced setitem")
        x._data = new.astype(x.dtype)
        x._grad_node = None
        return x

    need_grad = (_engine.is_grad_enabled()
                 and (not x.stop_gradient
                      or (isinstance(value, Tensor)
                          and not value.stop_gradient)))
    if not need_grad:
        x._data = x._data.at[jidx].set(vdata)
        return x

    # Snapshot x's pre-mutation autograd identity into a proxy so the new
    # node's input edge points at the OLD producer, not at x itself (which
    # is about to be re-bound to the new node — a self-loop otherwise).
    proxy = _autograd_proxy(x)
    inputs = [proxy, value if isinstance(value, Tensor) else vdata]
    out_data, vjp_fn = jax.vjp(
        lambda a, v: a.at[jidx].set(v.astype(a.dtype)), x._data, vdata)
    node = _engine.GradNode(_setitem_opdef, vjp_fn, inputs, {},
                            vjp_fallback=True, diff_idx=[0, 1])
    out = Tensor(out_data, stop_gradient=False)
    node.bind_outputs([out])
    # Paddle inplace semantics: x now refers to the new value/node.
    x._data = out._data
    x._grad_node = node
    x._out_slot = 0
    x.stop_gradient = False
    return x


def _autograd_proxy(t):
    """Copy of t carrying its current autograd edge (for inplace ops)."""
    from ..core.tensor import Tensor

    p = Tensor(t._data, stop_gradient=t.stop_gradient)
    p._grad_node = t._grad_node
    p._out_slot = t._out_slot
    p._hooks = t._hooks
    return p


class _FakeSetOp:
    name = "setitem"
    jit_bwd = None


_setitem_opdef = _FakeSetOp()
