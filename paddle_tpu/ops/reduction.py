"""Reduction ops.

Reference: ``python/paddle/tensor/math.py`` (sum/mean/...) and stat ops,
kernel pairing ``reduce_sum``/``reduce_sum_grad`` etc. in
``paddle/phi/ops/yaml/ops.yaml``; grad semantics mirror
``phi/kernels/funcs/reduce_function.h`` (broadcast the output cotangent back
over the reduced axes).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import apply, register_op


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _restore_shape(g, x, axis, keepdim):
    """Reshape/broadcast the reduced cotangent back to x's shape."""
    if axis is None:
        return jnp.broadcast_to(g, jnp.shape(x))
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a % x.ndim for a in axes)
    if not keepdim:
        g = jnp.expand_dims(g, axes)
    return jnp.broadcast_to(g, jnp.shape(x))


def _sum_fwd(x, axis=None, keepdim=False):
    return jnp.sum(x, axis=axis, keepdims=keepdim), x


def _sum_bwd(x, g, axis=None, keepdim=False):
    return (_restore_shape(g, x, axis, keepdim).astype(x.dtype),)


sum_op = register_op("reduce_sum",
                     lambda x, axis=None, keepdim=False: jnp.sum(
                         x, axis=axis, keepdims=keepdim),
                     fwd=_sum_fwd, bwd=_sum_bwd,
                     static_argnames=("axis", "keepdim"))


def _mean_fwd(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim), x


def _mean_bwd(x, g, axis=None, keepdim=False):
    import numpy as np

    shape = jnp.shape(x)
    if axis is None:
        n = int(np.prod(shape)) if shape else 1
    else:
        axes = axis if isinstance(axis, tuple) else (axis,)
        n = int(np.prod([shape[a % len(shape)] for a in axes]))
    return ((_restore_shape(g, x, axis, keepdim) / n).astype(x.dtype),)


mean_op = register_op("reduce_mean",
                      lambda x, axis=None, keepdim=False: jnp.mean(
                          x, axis=axis, keepdims=keepdim),
                      fwd=_mean_fwd, bwd=_mean_bwd,
                      static_argnames=("axis", "keepdim"))


def _minmax_op(name, fn):
    def plain(x, axis=None, keepdim=False):
        return fn(x, axis=axis, keepdims=keepdim)

    def fwd(x, axis=None, keepdim=False):
        out = fn(x, axis=axis, keepdims=keepdim)
        return out, (x, out)

    def bwd(saved, g, axis=None, keepdim=False):
        x, out = saved
        full_out = _restore_shape(out, x, axis, keepdim)
        full_g = _restore_shape(g, x, axis, keepdim)
        mask = (x == full_out).astype(g.dtype)
        # Split ties evenly, matching the reference's max_grad semantics of
        # distributing gradient over all argmax positions equally is NOT what
        # paddle does (paddle picks all). Keep all-positions like jnp.
        denom = jnp.sum(mask, axis=axis, keepdims=True) if axis is not None \
            else jnp.sum(mask)
        denom = jnp.maximum(denom, 1).astype(g.dtype)
        denom_full = _restore_shape(
            denom if axis is not None and True else denom, x, axis, True) \
            if axis is not None else denom
        return ((full_g * mask / (denom_full if axis is not None else denom)
                 ).astype(x.dtype),)

    return register_op(name, plain, fwd=fwd, bwd=bwd,
                       static_argnames=("axis", "keepdim"))


max_op = _minmax_op("reduce_max", jnp.max)
min_op = _minmax_op("reduce_min", jnp.min)

prod_op = register_op("reduce_prod",
                      lambda x, axis=None, keepdim=False: jnp.prod(
                          x, axis=axis, keepdims=keepdim),
                      static_argnames=("axis", "keepdim"))
any_op = register_op("reduce_any",
                     lambda x, axis=None, keepdim=False: jnp.any(
                         x, axis=axis, keepdims=keepdim),
                     static_argnames=("axis", "keepdim"))
all_op = register_op("reduce_all",
                     lambda x, axis=None, keepdim=False: jnp.all(
                         x, axis=axis, keepdims=keepdim),
                     static_argnames=("axis", "keepdim"))
amax_op = register_op("amax",
                      lambda x, axis=None, keepdim=False: jnp.amax(
                          x, axis=axis, keepdims=keepdim),
                      static_argnames=("axis", "keepdim"))
amin_op = register_op("amin",
                      lambda x, axis=None, keepdim=False: jnp.amin(
                          x, axis=axis, keepdims=keepdim),
                      static_argnames=("axis", "keepdim"))
logsumexp_op = register_op(
    "logsumexp",
    lambda x, axis=None, keepdim=False: jax_logsumexp(x, axis, keepdim),
    static_argnames=("axis", "keepdim"))


def jax_logsumexp(x, axis, keepdim):
    from jax.scipy.special import logsumexp as lse

    return lse(x, axis=axis, keepdims=keepdim)


argmax_op = register_op(
    "argmax",
    lambda x, axis=None, keepdim=False, dtype=jnp.int64: (
        jnp.argmax(x, axis=axis, keepdims=keepdim).astype(dtype)
        if axis is not None else jnp.argmax(x).astype(dtype)),
    static_argnames=("axis", "keepdim", "dtype"))
argmin_op = register_op(
    "argmin",
    lambda x, axis=None, keepdim=False, dtype=jnp.int64: (
        jnp.argmin(x, axis=axis, keepdims=keepdim).astype(dtype)
        if axis is not None else jnp.argmin(x).astype(dtype)),
    static_argnames=("axis", "keepdim", "dtype"))

cumsum_op = register_op(
    "cumsum", lambda x, axis=None: (jnp.cumsum(x, axis=axis)
                                    if axis is not None
                                    else jnp.cumsum(x.reshape(-1))),
    fwd=lambda x, axis=None: ((jnp.cumsum(x, axis=axis)
                               if axis is not None
                               else jnp.cumsum(x.reshape(-1))), x),
    bwd=lambda x, g, axis=None: (
        (jnp.flip(jnp.cumsum(jnp.flip(g, axis), axis=axis), axis)
         if axis is not None
         else jnp.reshape(jnp.flip(jnp.cumsum(jnp.flip(g, 0), axis=0), 0),
                          jnp.shape(x))),),
    static_argnames=("axis",))
cumprod_op = register_op(
    "cumprod", lambda x, dim=None: jnp.cumprod(x, axis=dim),
    static_argnames=("dim",))
cummax_op = register_op(
    "cummax", lambda x, axis=None: jax_cummax(x, axis),
    static_argnames=("axis",), n_outputs=2)
cummin_op = register_op(
    "cummin", lambda x, axis=None: jax_cummin(x, axis),
    static_argnames=("axis",), n_outputs=2)


def jax_cummax(x, axis):
    import jax

    vals = jax.lax.associative_scan(jnp.maximum, x, axis=axis)
    # indices: positions of running max
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    sel = jnp.where(x == vals, idx, 0)
    inds = jax.lax.associative_scan(jnp.maximum, sel, axis=axis)
    return vals, inds.astype(jnp.int64)


def jax_cummin(x, axis):
    vals, inds = jax_cummax(-x, axis)
    return -vals, inds


# -- Python-level APIs ------------------------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    out = apply(sum_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))
    if dtype is not None:
        from . import manipulation

        out = manipulation.cast(out, dtype)
    return out


def mean(x, axis=None, keepdim=False, name=None):
    return apply(mean_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(max_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(min_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def amax(x, axis=None, keepdim=False, name=None):
    return apply(amax_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def amin(x, axis=None, keepdim=False, name=None):
    return apply(amin_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    out = apply(prod_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))
    if dtype is not None:
        from . import manipulation

        out = manipulation.cast(out, dtype)
    return out


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(any_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply(all_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(logsumexp_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core import dtype as dt

    return apply(argmax_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim),
                 dtype=dt.convert_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core import dtype as dt

    return apply(argmin_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim),
                 dtype=dt.convert_dtype(dtype))


def cumsum(x, axis=None, dtype=None, name=None):
    out = apply(cumsum_op, x, axis=_norm_axis(axis))
    if dtype is not None:
        from . import manipulation

        out = manipulation.cast(out, dtype)
    return out


def cumprod(x, dim=None, dtype=None, name=None):
    out = apply(cumprod_op, x, dim=_norm_axis(dim))
    if dtype is not None:
        from . import manipulation

        out = manipulation.cast(out, dtype)
    return out


def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        from . import manipulation

        x = manipulation.reshape(x, [-1])
        axis = 0
    return apply(cummax_op, x, axis=int(axis))


def cummin(x, axis=None, dtype="int64", name=None):
    if axis is None:
        from . import manipulation

        x = manipulation.reshape(x, [-1])
        axis = 0
    return apply(cummin_op, x, axis=int(axis))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    m = mean(x, axis=axis, keepdim=True)
    sq = multiply_diff(x, m)
    out = mean(sq, axis=axis, keepdim=keepdim)
    if unbiased:
        import numpy as np

        shape = x.shape
        if axis is None:
            n = int(np.prod(shape)) if shape else 1
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            n = int(np.prod([shape[a % len(shape)] for a in axes]))
        if n > 1:
            from . import math as m_ops

            out = m_ops.scale(out, scale=n / (n - 1))
    return out


def multiply_diff(x, m):
    from . import math as m_ops

    d = m_ops.subtract(x, m)
    return m_ops.multiply(d, d)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    from . import math as m_ops

    return m_ops.sqrt(var(x, axis=axis, unbiased=unbiased, keepdim=keepdim))


def numel(x, name=None):
    from ..core.tensor import Tensor

    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    from . import math as m_ops
    from . import manipulation

    nz = manipulation.cast(m_ops.not_equal(x, 0), "int64")
    return sum(nz, axis=axis, keepdim=keepdim)


nanmean_op = register_op(
    "nanmean", lambda x, axis=None, keepdim=False: jnp.nanmean(
        x, axis=axis, keepdims=keepdim),
    static_argnames=("axis", "keepdim"))


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(nanmean_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))

nansum_op = register_op(
    "nansum", lambda x, axis=None, keepdim=False: jnp.nansum(
        x, axis=axis, keepdims=keepdim),
    static_argnames=("axis", "keepdim"))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply(nansum_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


median_op = register_op(
    "median", lambda x, axis=None, keepdim=False: jnp.median(
        x, axis=axis, keepdims=keepdim),
    static_argnames=("axis", "keepdim"))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply(median_op, x, axis=_norm_axis(axis), keepdim=bool(keepdim))


quantile_op = register_op(
    "quantile", lambda x, q, axis=None, keepdim=False: jnp.quantile(
        x, q, axis=axis, keepdims=keepdim),
    static_argnames=("q", "axis", "keepdim"))


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply(quantile_op, x, q=float(q) if not isinstance(q, (list, tuple))
                 else tuple(q), axis=_norm_axis(axis), keepdim=bool(keepdim))
