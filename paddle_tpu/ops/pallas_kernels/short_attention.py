"""Fused short-sequence attention kernel (self-authored Pallas TPU).

Covers the BERT-class shape regime (Sq == Sk == S <= ~1024, D <= 128)
where the whole [S, S] score matrix of one (batch, head) fits VMEM, so
attention needs NO online-softmax blocking at all: one program per
(batch, head) computes scores -> softmax -> dropout -> @V entirely
on-chip.  HBM sees only q/k/v/out ([S, D] each) and an [S] logsumexp —
the [B, H, S, S] probabilities and their dropout masks NEVER touch HBM.
Dropout derives its mask from a counter-based in-kernel hash of
(seed, batch, head, element), so the backward pass regenerates a
bit-identical mask instead of storing it (r4 BERT profile: probs + mask traffic
was ~60 ms of a ~180 ms step).

Reference analog: paddle/phi/kernels/fusion/gpu/fused_attention_op
(fused QKV attention with in-kernel curand dropout); re-designed here
around VMEM capacity instead of shared-memory tiling.

The backward is hand-derived (custom_vjp below):
    P  = softmax(s);  O = (P .* M / keep) @ V        (M = dropout mask)
    dV = (P .* M / keep)^T @ dO
    dP = (dO @ V^T) .* M / keep
    dS = P .* (dP - rowsum(dP .* P))                 (softmax VJP)
    dQ = dS @ K * scale;   dK = dS^T @ Q * scale
verified against the einsum+bernoulli reference path in
tests/test_short_attention.py (exact mask parity included).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _keep_mask(seed_ref, shape, keep_prob):
    """Dropout keep-mask from a counter-based hash of (seed, program,
    element index) — NOT the stateful pltpu PRNG: the hardware stream's
    element order is a kernel-layout detail, so a stream drawn in the
    backward kernel would not reproduce the forward's mask.  A pure
    hash of the element counter is bit-identical in any kernel by
    construction (murmur3-style finalizer; ample quality for dropout).
    """
    b = pl.program_id(0)
    h = pl.program_id(1)
    nh = pl.num_programs(1)
    per_program = (seed_ref[0] + (b * nh + h) * 747796405).astype(
        jnp.uint32)
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    x = rows * jnp.uint32(shape[1]) + cols + per_program
    x = x * jnp.uint32(0x9E3779B9)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    threshold = jnp.uint32(min(int(keep_prob * 4294967296.0),
                               4294967295))
    return x < threshold


def _scores(q_ref, k_ref, scale, causal):
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        S = s.shape[0]
        row = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        s = jnp.where(col <= row, s, _NEG_INF)
    return s


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale, dropout_p, causal):
    s = _scores(q_ref, k_ref, scale, causal)
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=1, keepdims=True)
    p = e / l
    lse_ref[0, 0, 0] = (m + jnp.log(l))[:, 0]
    if dropout_p > 0.0:
        keep = _keep_mask(seed_ref, p.shape, 1.0 - dropout_p)
        p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
    v = v_ref[0, 0].astype(jnp.float32)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _bwd_kernel(seed_ref, q_ref, k_ref, v_ref, lse_ref, g_ref,
                dq_ref, dk_ref, dv_ref, *, scale, dropout_p, causal):
    s = _scores(q_ref, k_ref, scale, causal)
    p = jnp.exp(s - lse_ref[0, 0, 0][:, None])
    g = g_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    if dropout_p > 0.0:
        keep = _keep_mask(seed_ref, p.shape, 1.0 - dropout_p)
        inv = 1.0 / (1.0 - dropout_p)
        pd = jnp.where(keep, p * inv, 0.0)
    else:
        pd = p
    # dV = (P.*M/keep)^T @ g
    dv = jax.lax.dot_general(pd, g, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    # dP = (g @ V^T) .* M/keep
    dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if dropout_p > 0.0:
        dp = jnp.where(keep, dp * inv, 0.0)
    ds = p * (dp - jnp.sum(dp * p, axis=1, keepdims=True))
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32) * scale
    dk = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32) * scale
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bh_spec(S, D):
    return pl.BlockSpec((1, 1, S, D),
                        lambda b, h, *_: (b, h, 0, 0))


def _lse_spec(S):
    # [B, H, 1, S]: a (1, 1, 1, S) block keeps the last two dims
    # tile-legal (1 == the array's own dim, S % 128 == 0).
    return pl.BlockSpec((1, 1, 1, S), lambda b, h, *_: (b, h, 0, 0))


def _fwd_call_impl(q, k, v, seed, scale, dropout_p, causal):
    B, H, S, D = q.shape
    kernel = functools.partial(_fwd_kernel, scale=scale,
                               dropout_p=dropout_p, causal=causal)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H),
        in_specs=[_bh_spec(S, D)] * 3,
        out_specs=[_bh_spec(S, D), _lse_spec(S)],
    )
    # Mosaic rejects the i64 grid/index constants that global x64 mode
    # introduces — trace the kernel with x64 off regardless of caller.
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, 1, S), jnp.float32),
            ],
        )(seed, q, k, v)
    return out, lse


def _bwd_call(q, k, v, lse, g, seed, scale, dropout_p, causal):
    B, H, S, D = q.shape
    kernel = functools.partial(_bwd_kernel, scale=scale,
                               dropout_p=dropout_p, causal=causal)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H),
        in_specs=[_bh_spec(S, D)] * 3 + [_lse_spec(S), _bh_spec(S, D)],
        out_specs=[_bh_spec(S, D)] * 3,
    )
    with jax.enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[jax.ShapeDtypeStruct((B, H, S, D), q.dtype)] * 3,
        )(seed, q, k, v, lse, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def short_attention(q, k, v, seed, scale=None, dropout_p=0.0,
                    causal=False):
    """Fused attention for [B, H, S, D] with S*S scores resident in
    VMEM.  ``seed`` (int32 scalar array) drives in-kernel dropout; the
    backward regenerates the identical mask from the same seed."""
    out, _ = _fwd_call_impl(q, k, v, _seed_arr(seed),
                            _scale_of(scale, q), float(dropout_p),
                            bool(causal))
    return out


def _scale_of(scale, q):
    import math

    return float(scale) if scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])


def _seed_arr(seed):
    return jnp.atleast_1d(jnp.asarray(seed, jnp.int32))


def _vjp_fwd(q, k, v, seed, scale, dropout_p, causal):
    out, lse = _fwd_call_impl(q, k, v, _seed_arr(seed),
                              _scale_of(scale, q), float(dropout_p),
                              bool(causal))
    return out, (q, k, v, lse, seed)


def _vjp_bwd(scale, dropout_p, causal, res, g):
    q, k, v, lse, seed = res
    dq, dk, dv = _bwd_call(q, k, v, lse, g, _seed_arr(seed),
                           _scale_of(scale, q), float(dropout_p),
                           bool(causal))
    return dq, dk, dv, None


short_attention.defvjp(_vjp_fwd, _vjp_bwd)
