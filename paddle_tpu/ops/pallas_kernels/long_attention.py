"""Q-blocked causal attention kernel with fused RoPE (self-authored).

The llama-regime companion to ``short_attention``: at S ~ 2048-8192,
D=128, one (batch, head)'s FULL K/V is only S*D*2*2 bytes (1 MB at
S=2048 bf16) — it fits VMEM outright.  So instead of flash-attention's
K-blocking + online-softmax machinery, each program holds K/V whole
and computes one q block's ENTIRE score row [block_q, S] in VMEM:
plain softmax, no running max/sum rescaling, one MXU pass per block.
(PERF.md r3: the stock flash kernel ran ~3x off the attention
roofline at this shape; its K-block pipeline is built for S where K/V
can't be resident — pure overhead here.)

RoPE is fused: q/k rotate INSIDE the kernel from an [S, D/2] cos/sin
table (reference fused_rope kernel, phi/kernels/fusion/gpu/
fused_rope); the rotated q/k never touch HBM, and the backward
de-rotates dq/dk with the transpose rotation (RoPE is orthogonal:
d(rope(x)) = rope^T(dout)).

Backward: dV/dP need the probs; they are recomputed from the saved
logsumexp per q block (same as fwd, one extra MXU pass).  dK/dV
accumulate across q blocks by making the q-block axis the INNERMOST
grid dimension and zero-initializing on its first step (TPU grids run
sequentially, so += into the output block is well-defined).

Layout: q/k/v [B, H, S, D]; causal only (the regime where this kernel
is selected); lse saved as [B, H, 1, S] (tile-legal, cf.
short_attention).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -2.3819763e38  # most-negative bf16-representable


def _rope(x, cos, sin, sign=1.0):
    """Rotate pairs (even, odd) of the last dim; sign=-1 applies the
    transpose (inverse) rotation."""
    d2 = x.shape[-1] // 2
    x1 = x[..., :d2]
    x2 = x[..., d2:]
    return jnp.concatenate(
        [x1 * cos - sign * x2 * sin, sign * x1 * sin + x2 * cos],
        axis=-1)


def _fwd_kernel(q_ref, k_ref, v_ref, cos_ref, sin_ref, o_ref, lse_ref,
                *, scale, block_q, causal, use_rope):
    qi = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)          # [S, D]
    v = v_ref[0, 0].astype(jnp.float32)
    q = q_ref[0, 0].astype(jnp.float32)          # [block_q, D]
    if use_rope:
        cos = cos_ref[0]                          # [S, D/2]
        sin = sin_ref[0]
        # block-row slice via ref indexing (Mosaic has no
        # dynamic_slice primitive on loaded values)
        q = _rope(q, cos_ref[0, pl.ds(qi * block_q, block_q)],
                  sin_ref[0, pl.ds(qi * block_q, block_q)])
        k = _rope(k, cos, sin)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        S = k.shape[0]
        row = (jax.lax.broadcasted_iota(jnp.int32,
                                        (block_q, S), 0)
               + qi * block_q)
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 1)
        s = jnp.where(col <= row, s, _NEG)
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=1, keepdims=True)
    p = e / l
    lse_ref[0, 0, 0] = (m + jnp.log(l))[:, 0]
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, cos_ref, sin_ref, lse_ref, g_ref,
                dq_ref, dk_ref, dv_ref, *, scale, block_q, causal,
                use_rope):
    qi = pl.program_id(2)
    k0 = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    q = q_ref[0, 0].astype(jnp.float32)
    if use_rope:
        cos = cos_ref[0]
        sin = sin_ref[0]
        cos_q = cos_ref[0, pl.ds(qi * block_q, block_q)]
        sin_q = sin_ref[0, pl.ds(qi * block_q, block_q)]
        q = _rope(q, cos_q, sin_q)
        k = _rope(k0, cos, sin)
    else:
        k = k0
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    S = k.shape[0]
    if causal:
        row = (jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 0)
               + qi * block_q)
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 1)
        s = jnp.where(col <= row, s, _NEG)
    p = jnp.exp(s - lse_ref[0, 0, 0][:, None])   # [block_q, S]
    g = g_ref[0, 0].astype(jnp.float32)          # [block_q, D]

    dv_blk = jax.lax.dot_general(p, g, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - jnp.sum(dp * p, axis=1, keepdims=True)) * scale
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dk_blk = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    if use_rope:
        # de-rotate: d(rope(x))/dx is the transpose rotation
        dq = _rope(dq, cos_q, sin_q, sign=-1.0)
        dk_blk = _rope(dk_blk, cos, sin, sign=-1.0)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    # accumulate dk/dv over the (innermost, sequential) q-block axis
    @pl.when(qi == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    dk_ref[0, 0] += dk_blk.astype(dk_ref.dtype)
    dv_ref[0, 0] += dv_blk.astype(dv_ref.dtype)


def _specs(S, D, block_q, d2):
    qspec = pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0))
    kvspec = pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0))
    rspec = pl.BlockSpec((1, S, d2), lambda b, h, i: (0, 0, 0))
    lspec = pl.BlockSpec((1, 1, 1, block_q),
                         lambda b, h, i: (b, h, 0, i))
    return qspec, kvspec, rspec, lspec


def _fwd_call(q, k, v, cos, sin, scale, block_q, causal, use_rope):
    B, H, S, D = q.shape
    nq = S // block_q
    qspec, kvspec, rspec, lspec = _specs(S, D, block_q, D // 2)
    kernel = functools.partial(_fwd_kernel, scale=scale,
                               block_q=block_q, causal=causal,
                               use_rope=use_rope)
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
            kernel,
            grid=(B, H, nq),
            in_specs=[qspec, kvspec, kvspec, rspec, rspec],
            out_specs=[qspec, lspec],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, 1, S), jnp.float32),
            ],
        )(q, k, v, cos, sin)
    return out, lse


def _bwd_call(q, k, v, cos, sin, lse, g, scale, block_q, causal,
              use_rope):
    # the bwd holds ~4 [block_q, S] f32 intermediates (s, p, dp, ds);
    # a smaller block than the fwd keeps it inside scoped VMEM
    block_q = min(block_q, 256)
    B, H, S, D = q.shape
    nq = S // block_q
    qspec, kvspec, rspec, lspec = _specs(S, D, block_q, D // 2)
    kernel = functools.partial(_bwd_kernel, scale=scale,
                               block_q=block_q, causal=causal,
                               use_rope=use_rope)
    with jax.enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid=(B, H, nq),
            in_specs=[qspec, kvspec, kvspec, rspec, rspec, lspec,
                      qspec],
            out_specs=[qspec, kvspec, kvspec],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, S, D), jnp.float32),
                jax.ShapeDtypeStruct((B, H, S, D), jnp.float32),
            ],
        )(q, k, v, cos, sin, lse, g)


def _rope_tables(S, D, base, dtype):
    inv = 1.0 / (base ** (jnp.arange(0, D // 2, dtype=jnp.float32)
                          * 2.0 / D))
    ang = jnp.arange(S, dtype=jnp.float32)[:, None] * inv[None, :]
    return (jnp.cos(ang).astype(dtype)[None],
            jnp.sin(ang).astype(dtype)[None])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def long_attention(q, k, v, scale=None, block_q=512, causal=True,
                   rope_base=None):
    """[B, H, S, D] causal attention, K/V VMEM-resident, optional
    fused RoPE (rope_base=10000.0 enables it).  S % block_q == 0."""
    out, _ = _fwd_impl(q, k, v, scale, block_q, causal, rope_base)
    return out


def _scale_of(scale, q):
    import math

    return float(scale) if scale is not None \
        else 1.0 / math.sqrt(q.shape[-1])


def _fwd_impl(q, k, v, scale, block_q, causal, rope_base):
    B, H, S, D = q.shape
    use_rope = rope_base is not None
    if use_rope:
        cos, sin = _rope_tables(S, D, float(rope_base), jnp.float32)
    else:
        cos = jnp.zeros((1, S, D // 2), jnp.float32)
        sin = cos
    return _fwd_call(q, k, v, cos, sin, _scale_of(scale, q),
                     int(block_q), bool(causal), use_rope)


def _vjp_fwd(q, k, v, scale, block_q, causal, rope_base):
    out, lse = _fwd_impl(q, k, v, scale, block_q, causal, rope_base)
    return out, (q, k, v, lse)


def _vjp_bwd(scale, block_q, causal, rope_base, res, g):
    q, k, v, lse = res
    B, H, S, D = q.shape
    use_rope = rope_base is not None
    if use_rope:
        cos, sin = _rope_tables(S, D, float(rope_base), jnp.float32)
    else:
        cos = jnp.zeros((1, S, D // 2), jnp.float32)
        sin = cos
    dq, dk, dv = _bwd_call(q, k, v, cos, sin, lse, g,
                           _scale_of(scale, q), int(block_q),
                           bool(causal), use_rope)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


long_attention.defvjp(_vjp_fwd, _vjp_bwd)
