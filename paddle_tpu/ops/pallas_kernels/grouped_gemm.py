"""Grouped expert GEMM Pallas kernel (self-authored, #5).

Reference analog: the fused expert FFN kernels behind
``incubate/distributed/models/moe`` (phi/kernels/fusion MoE GEMMs) —
the role, not the design.  Technique lineage: MegaBlocks (Gale et al.,
2022) grouped GEMM over sort-dispatched expert buckets, replacing the
GShard mask-matmul formulation.

TPU design: tokens arrive already bucketed ``[E, C, H]`` (sort-based
dispatch, ``distributed/utils/moe_utils.sort_dispatch``).  One kernel
runs BOTH expert matmuls for every expert — grid ``(E, C/bc, F/bf)``
with the F-block axis innermost so each ``[bc, H]`` row block
accumulates its second GEMM into a VMEM f32 scratch across F blocks:

    h  = act(x_blk @ w1[e][:, fblk] + b1[e][fblk])   # [bc, bf], VMEM
    acc += h @ w2[e][fblk, :]                        # [bc, H],  VMEM
    out = acc + b2[e]          (written once, at the last F block)

The ``[E, C, F]`` hidden activation — the big HBM intermediate of the
batched-einsum path — never exists: each ``[bc, bf]`` tile of it lives
and dies in VMEM.  Per-expert weights stream through VMEM one
``[H, bf]`` / ``[bf, H]`` panel at a time, so arbitrary ``F`` fits the
16 MB budget.  The activation is applied per F block (elementwise, so
blocking over F is exact).

Backward is a hand-written VJP over saved ``(x, w1, b1, w2)`` — the
hidden activation is recomputed (checkpoint semantics; keeping it
would re-create exactly the HBM buffer the kernel exists to avoid) and
the derivative of the activation comes from ``jax.vjp`` of the same
elementwise function, so any supported activation differentiates
correctly.  The dw/dx contractions are plain batched jnp einsums — MXU
work XLA already schedules well (same split as rms_norm's dw).

Routing: ``PT_GROUPED_GEMM`` ∈ {auto, pallas, einsum}.  ``auto`` takes
the kernel on TPU when the shape gate passes (H and F tile to 128
lanes) and the batched-einsum fallback otherwise; ``pallas`` forces
the kernel (interpreter mode off-TPU — test machinery, not a fast
path).  Tiles ``(bc, bf)`` come from the autotune cache
(``grouped_gemm_blocks``, ops/autotune.py) like fa_blocks/paged_decode.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: default (row-block, f-block) tile: ~6 MB of VMEM live per program
#: (w1/w2 panels 2 MB each f32 + x/acc row blocks), safely under the
#: 16 MB budget with Pallas' input double-buffering.
_DEFAULT_BLOCKS = (128, 256)


def _act_fn(name):
    if name == "gelu":
        # Match ops.gelu (exact erf form), not jax.nn.gelu's tanh default.
        return lambda v: jax.nn.gelu(v, approximate=False)
    return getattr(jax.nn, name)


def _interpret():
    return jax.default_backend() != "tpu"


def blocks(hidden, ffn):
    """(row_block, f_block) for an [*, hidden] x [hidden, ffn] expert —
    the autotune cache's winner when one is on record, else the
    default.  The f block must divide ffn; a stale cached winner that
    doesn't is discarded rather than obeyed."""
    from .. import autotune as _autotune

    bc, bf = _autotune.lookup("grouped_gemm_blocks", (hidden, ffn),
                              default=_DEFAULT_BLOCKS)
    bf = min(int(bf), ffn)
    while ffn % bf != 0 and bf > 1:
        bf //= 2
    if ffn % bf != 0:
        bf = ffn
    return int(bc), bf


def _kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, acc, *,
            activation, n_fblocks):
    j = pl.program_id(2)
    x = x_ref[0].astype(jnp.float32)                 # [bc, H]
    w1 = w1_ref[0].astype(jnp.float32)               # [H, bf]
    h = _act_fn(activation)(
        jax.lax.dot(x, w1, preferred_element_type=jnp.float32)
        + b1_ref[0].astype(jnp.float32))             # [bc, bf]
    contrib = jax.lax.dot(h, w2_ref[0].astype(jnp.float32),
                          preferred_element_type=jnp.float32)  # [bc, H]

    @pl.when(j == 0)
    def _init():
        acc[...] = contrib + b2_ref[0].astype(jnp.float32)

    @pl.when(j > 0)
    def _accum():
        acc[...] += contrib

    @pl.when(j == n_fblocks - 1)
    def _flush():
        o_ref[0] = acc[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("activation",))
def _pallas_ffn(x, w1, b1, w2, b2, activation):
    E, C, H = x.shape
    F = w1.shape[-1]
    bc, bf = blocks(H, F)
    bc = min(bc, max(8, -(-C // 8) * 8))  # tiny C: one padded row block
    pad = -C % bc
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    rows = x.shape[1]
    kernel = functools.partial(_kernel, activation=activation,
                               n_fblocks=F // bf)
    # Mosaic rejects i64 grid/index constants from the repo's global
    # x64 mode — trace x64-off like every other kernel in this package.
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(E, rows // bc, F // bf),
            in_specs=[
                pl.BlockSpec((1, bc, H), lambda e, i, j: (e, i, 0)),
                pl.BlockSpec((1, H, bf), lambda e, i, j: (e, 0, j)),
                pl.BlockSpec((1, 1, bf), lambda e, i, j: (e, 0, j)),
                pl.BlockSpec((1, bf, H), lambda e, i, j: (e, j, 0)),
                pl.BlockSpec((1, 1, H), lambda e, i, j: (e, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, bc, H), lambda e, i, j: (e, i, 0)),
            out_shape=jax.ShapeDtypeStruct((E, rows, H), x.dtype),
            scratch_shapes=[pltpu.VMEM((bc, H), jnp.float32)],
            interpret=_interpret(),
        )(x, w1, b1, w2, b2)
    return out[:, :C]


def einsum_ffn(x, w1, b1, w2, b2, activation):
    """Batched-einsum fallback — the pre-fusion expert FFN body.  The
    [E, C, F] hidden activation round-trips HBM here; this is the
    baseline the kernel is measured against."""
    h = _act_fn(activation)(jnp.einsum("ech,ehf->ecf", x, w1) + b1)
    return jnp.einsum("ecf,efh->ech", h, w2) + b2


# -- int8 weights (PT_QUANT=int8, r19) --------------------------------------

def _qkernel(x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref, b2_ref,
             o_ref, acc, *, activation, n_fblocks):
    """Same tiling as ``_kernel`` with int8 expert weights: the weight
    panels stream HBM→VMEM at half/quarter the bytes and the per-output-
    channel f32 scales are applied to the f32 products right next to
    the MXU dots (scales commute with the contractions; s2 is constant
    across F blocks, so scaling each contribution before accumulation
    is exact)."""
    j = pl.program_id(2)
    x = x_ref[0].astype(jnp.float32)                 # [bc, H]
    w1 = w1_ref[0].astype(jnp.float32)               # [H, bf] (int8 in)
    h = _act_fn(activation)(
        jax.lax.dot(x, w1, preferred_element_type=jnp.float32)
        * s1_ref[0] + b1_ref[0].astype(jnp.float32))  # [bc, bf]
    contrib = jax.lax.dot(h, w2_ref[0].astype(jnp.float32),
                          preferred_element_type=jnp.float32) \
        * s2_ref[0]                                   # [bc, H]

    @pl.when(j == 0)
    def _init():
        acc[...] = contrib + b2_ref[0].astype(jnp.float32)

    @pl.when(j > 0)
    def _accum():
        acc[...] += contrib

    @pl.when(j == n_fblocks - 1)
    def _flush():
        o_ref[0] = acc[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("activation",))
def _pallas_ffn_q(x, qw1, s1, b1, qw2, s2, b2, activation):
    E, C, H = x.shape
    F = qw1.shape[-1]
    bc, bf = blocks(H, F)
    bc = min(bc, max(8, -(-C // 8) * 8))  # tiny C: one padded row block
    pad = -C % bc
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    rows = x.shape[1]
    kernel = functools.partial(_qkernel, activation=activation,
                               n_fblocks=F // bf)
    s1 = s1.astype(jnp.float32)
    s2 = s2.astype(jnp.float32)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(E, rows // bc, F // bf),
            in_specs=[
                pl.BlockSpec((1, bc, H), lambda e, i, j: (e, i, 0)),
                pl.BlockSpec((1, H, bf), lambda e, i, j: (e, 0, j)),
                pl.BlockSpec((1, 1, bf), lambda e, i, j: (e, 0, j)),
                pl.BlockSpec((1, 1, bf), lambda e, i, j: (e, 0, j)),
                pl.BlockSpec((1, bf, H), lambda e, i, j: (e, j, 0)),
                pl.BlockSpec((1, 1, H), lambda e, i, j: (e, 0, 0)),
                pl.BlockSpec((1, 1, H), lambda e, i, j: (e, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, bc, H), lambda e, i, j: (e, i, 0)),
            out_shape=jax.ShapeDtypeStruct((E, rows, H), x.dtype),
            scratch_shapes=[pltpu.VMEM((bc, H), jnp.float32)],
            interpret=_interpret(),
        )(x, qw1, s1, b1, qw2, s2, b2)
    return out[:, :C]


# -- custom VJP over the kernel ------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused(x, w1, b1, w2, b2, activation):
    return _pallas_ffn(x, w1, b1, w2, b2, activation)


def _fused_f(x, w1, b1, w2, b2, activation):
    return (_pallas_ffn(x, w1, b1, w2, b2, activation),
            (x, w1, b1, w2, b2))


def _fused_b(activation, saved, dy):
    x, w1, b1, w2, b2 = saved
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    pre = jnp.einsum("ech,ehf->ecf", x32, w1.astype(jnp.float32)) \
        + b1.astype(jnp.float32)
    h, act_vjp = jax.vjp(_act_fn(activation), pre)
    dw2 = jnp.einsum("ecf,ech->efh", h, dy32).astype(w2.dtype)
    db2 = jnp.sum(dy32, axis=1, keepdims=True).astype(b2.dtype)
    dh = jnp.einsum("ech,efh->ecf", dy32, w2.astype(jnp.float32))
    dpre = act_vjp(dh)[0]
    dw1 = jnp.einsum("ech,ecf->ehf", x32, dpre).astype(w1.dtype)
    db1 = jnp.sum(dpre, axis=1, keepdims=True).astype(b1.dtype)
    dx = jnp.einsum("ecf,ehf->ech", dpre,
                    w1.astype(jnp.float32)).astype(x.dtype)
    return dx, dw1, db1, dw2, db2


_fused.defvjp(_fused_f, _fused_b)


# -- routing ----------------------------------------------------------------

def supported(hidden, ffn, on_tpu):
    """Shape gate for the compiled (non-interpret) kernel: both GEMM
    minor dims must tile to 128 lanes.  Off-TPU the interpreter imposes
    no tiling, but auto routing takes the einsum path there
    (kernel-in-interpreter is test machinery, not a fast path)."""
    if not on_tpu:
        return False
    return hidden % 128 == 0 and ffn % 128 == 0


def resolve_impl(hidden, ffn, impl=None):
    """'pallas' or 'einsum' for this shape.  ``impl``/PT_GROUPED_GEMM
    ∈ {auto, pallas, einsum}; auto = kernel on TPU when the shape gate
    passes."""
    impl = (impl or os.environ.get("PT_GROUPED_GEMM", "auto")).lower()
    if impl not in ("auto", "pallas", "einsum"):
        raise ValueError(
            f"PT_GROUPED_GEMM={impl!r}: expected auto|pallas|einsum")
    if impl == "auto":
        return "pallas" if supported(
            hidden, ffn, jax.default_backend() == "tpu") else "einsum"
    return impl


def grouped_ffn(x, w1, b1, w2, b2, activation="gelu", impl=None):
    """Grouped expert FFN over bucketed tokens.

    x [E, C, H]; w1 [E, H, F]; b1 [E, 1, F]; w2 [E, F, H]; b2 [E, 1, H]
    -> [E, C, H].  Differentiable on both routes (custom VJP over the
    kernel, native AD over the einsum fallback).

    ``w1``/``w2`` may instead be ``QuantizedLinear`` dicts
    (``ops.quant``): int8 ``qweight`` + per-output-channel f32
    ``scale``.  That path is inference-only (no VJP — training
    differentiates the dense weights) and fuses the dequant into the
    kernel; the einsum route dequantizes first.
    """
    from .. import quant as _quant

    if _quant.is_quantized(w1) or _quant.is_quantized(w2):
        if not (_quant.is_quantized(w1) and _quant.is_quantized(w2)):
            raise ValueError(
                "grouped_ffn: w1 and w2 must both be quantized")
        F = w1["qweight"].shape[-1]
        if resolve_impl(x.shape[-1], F, impl) == "pallas":
            return _pallas_ffn_q(x, w1["qweight"], w1["scale"], b1,
                                 w2["qweight"], w2["scale"], b2,
                                 activation)
        return einsum_ffn(x, _quant.dequantize(w1["qweight"],
                                               w1["scale"], x.dtype),
                          b1,
                          _quant.dequantize(w2["qweight"], w2["scale"],
                                            x.dtype),
                          b2, activation)
    if resolve_impl(x.shape[-1], w1.shape[-1], impl) == "pallas":
        return _fused(x, w1, b1, w2, b2, activation)
    return einsum_ffn(x, w1, b1, w2, b2, activation)


def grouped_ffn_spmd_rule(mesh, x_spec, w1_spec, b1_spec, w2_spec,
                          b2_spec):
    """SPMD rule: the expert (leading) dim may shard — programs are
    independent per expert, and all five operands must carry the same
    expert sharding (the EP layout global_scatter delivers); C, H and F
    are kernel-internal and must be replicated.  Output follows x."""
    return (tuple(x_spec)[:1] or (None,)) + (None, None)


_HANDLE = None


def handle():
    """Custom-op handle (lazy — registration is global).  Registered as
    ``grouped_expert_gemm`` so out-of-tree callers get dispatch/AMP/tape
    semantics; the MoE body calls ``grouped_ffn`` directly (it already
    runs inside a registered op's trace)."""
    global _HANDLE
    if _HANDLE is None:
        from ...utils.cpp_extension import register_custom_op

        _HANDLE = register_custom_op(
            "grouped_expert_gemm", grouped_ffn,
            static_argnames=("activation", "impl"),
            spmd_rule=grouped_ffn_spmd_rule)
    return _HANDLE
