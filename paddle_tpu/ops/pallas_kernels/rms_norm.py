"""Fused RMSNorm Pallas TPU kernel (self-authored).

Reference analog: ``paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu``
(fused residual+rmsnorm family) — the role, not the design.  TPU design:
one program per row-block with the whole hidden dim resident in VMEM, so
the normalization is a single HBM round-trip (read x, write out + rstd)
instead of XLA's usual two-pass reduce + scale.  The backward reuses the
saved rstd (no re-reduction for the mean-square) and computes dx in one
fused pass; dw is a plain jnp contraction over the saved tensors (MXU
work XLA already fuses optimally).

    fwd:  rstd = rsqrt(mean(x^2) + eps);  out = x * rstd * w
    bwd:  dxhat = dy * w;  xhat = x * rstd
          dx = rstd * (dxhat - xhat * mean(dxhat * xhat, -1))
          dw = sum_rows(dy * xhat)

Registered through the public custom-op API (utils/cpp_extension.py
``register_custom_op``) with this VJP and an SPMD rule (batch dims
propagate, hidden dim must be replicated), gated into
``nn.functional.rms_norm`` by ``FLAGS_use_fused_rms_norm``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_ROWS = 256


def _fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)          # [rows, 1]
    o_ref[...] = (x * rstd * w_ref[...].astype(jnp.float32)).astype(
        o_ref.dtype)
    rstd_ref[...] = rstd.astype(jnp.float32)


def _bwd_kernel(x_ref, w_ref, rstd_ref, dy_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    rstd = rstd_ref[...]                     # [rows, 1]
    dxhat = dy * w
    xhat = x * rstd
    m = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - xhat * m)).astype(dx_ref.dtype)


def _interpret():
    return jax.default_backend() != "tpu"


def _block_rows(h):
    """Row-block size for hidden width ``h`` — the autotune cache's
    winner when one is on record (ops/autotune.py), else the measured
    256 default."""
    from .. import autotune as _autotune

    return int(_autotune.lookup("rms_norm_block_rows", (h,),
                                default=_BLOCK_ROWS))


def _pad_rows(n, br=_BLOCK_ROWS):
    return -n % br


@functools.partial(jax.jit, static_argnames=("eps",))
def _fused_fwd_2d(x2, w, eps):
    n, h = x2.shape
    br = _block_rows(h)
    pad = _pad_rows(n, br)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    rows = x2.shape[0]
    grid = (rows // br,)
    # Mosaic rejects i64 grid/index constants from global x64 mode.
    with jax.enable_x64(False):
        out, rstd = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=grid,
            in_specs=[
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((1, h), lambda i: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((br, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((rows, h), x2.dtype),
                jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(x2, w.reshape(1, h))
    return out[:n], rstd[:n, 0]


@jax.jit
def _fused_bwd_2d(x2, w, rstd, dy2):
    n, h = x2.shape
    br = _block_rows(h)
    pad = _pad_rows(n, br)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        dy2 = jnp.pad(dy2, ((0, pad), (0, 0)))
        rstd = jnp.pad(rstd, (0, pad), constant_values=1.0)
    rows = x2.shape[0]
    grid = (rows // br,)
    with jax.enable_x64(False):
        dx = pl.pallas_call(
            _bwd_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((1, h), lambda i: (0, 0)),
                pl.BlockSpec((br, 1), lambda i: (i, 0)),
                pl.BlockSpec((br, h), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, h), x2.dtype),
            interpret=_interpret(),
        )(x2, w.reshape(1, h), rstd.reshape(-1, 1), dy2)
    return dx[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused(x, w, epsilon):
    h = x.shape[-1]
    out, _rstd = _fused_fwd_2d(x.reshape(-1, h), w, float(epsilon))
    return out.reshape(x.shape)


def _fused_f(x, w, epsilon):
    h = x.shape[-1]
    out, rstd = _fused_fwd_2d(x.reshape(-1, h), w, float(epsilon))
    return out.reshape(x.shape), (x, w, rstd)


def _fused_b(epsilon, saved, dy):
    x, w, rstd = saved
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    dy2 = dy.reshape(-1, h)
    dx = _fused_bwd_2d(x2, w, rstd, dy2).reshape(x.shape)
    xhat = x2.astype(jnp.float32) * rstd[:, None]
    dw = jnp.sum(dy2.astype(jnp.float32) * xhat, axis=0).astype(w.dtype)
    return dx, dw


_fused.defvjp(_fused_f, _fused_b)


def fused_rms_norm_fn(x, w, *, epsilon=1e-6):
    """Forward over jnp arrays (custom-op ``fn``) — differentiable under
    pure jax AD (custom_vjp) so the compiled train step's value_and_grad
    and remat both route through the hand-written backward."""
    return _fused(x, w, float(epsilon))


def fused_rms_norm_fwd(x, w, *, epsilon=1e-6):
    """custom-op ``fwd`` (eager tape): returns (out, saved)."""
    return _fused_f(x, w, float(epsilon))


def fused_rms_norm_vjp(saved, dy, *, epsilon=1e-6):
    """custom-op ``vjp`` (eager tape): (dx, dw)."""
    return _fused_b(float(epsilon), saved, dy)


def fused_rms_norm_spmd_rule(mesh, x_spec, w_spec):
    """SPMD rule: every batch dim of x propagates; the hidden (last) dim
    must be replicated (one row's full reduction lives in one kernel
    program); the weight is replicated."""
    spec = tuple(x_spec)[:-1] + (None,)
    return spec


_HANDLE = None


def handle():
    """The registered custom-op handle (lazy: registration is global)."""
    global _HANDLE
    if _HANDLE is None:
        from ...utils.cpp_extension import register_custom_op

        _HANDLE = register_custom_op(
            "fused_rms_norm", fused_rms_norm_fn, vjp=fused_rms_norm_vjp,
            fwd=fused_rms_norm_fwd, static_argnames=("epsilon",),
            spmd_rule=fused_rms_norm_spmd_rule)
    return _HANDLE
