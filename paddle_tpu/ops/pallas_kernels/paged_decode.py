"""Fused paged-decode attention kernel (self-authored, #4).

Reference analog: ``paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu`` — single-token decode attention
against a block-table (paged) KV cache, the kernel behind the
reference's continuous-batching serving path.  The role, not the
design.

TPU design: one program per (sequence, kv-head).  The program DMAs the
sequence's block-table window — ``pages_per_seq`` pages of
``[page_size, head_dim]`` K and V — from the HBM page pool into VMEM
scratch (all copies started before any is waited on, so the gather is
one pipelined burst), then computes the whole decode attention for that
head group in VMEM:

    scores = q_group @ K_window^T * scale      [group, S_window]
    p      = softmax(scores  masked to length)
    out    = p @ V_window                      [group, head_dim]

No online-softmax machinery: a decode window is S_window = pages_per_seq
* page_size tokens, and one head's K+V window at S=1024, D=128 bf16 is
512 KB — it fits VMEM outright (same VMEM-residency argument as
``long_attention``).  GQA rides free: the q rows of one program are the
``H // KV`` query heads sharing that KV head.

What this fuses (vs ``inference/paged._dense_paged_attention``): the
jnp path materializes the gathered dense cache [B, KV, T, D] (x2) in
HBM, then runs einsum -> mask -> softmax -> einsum as separate XLA
fusions over HBM round-trips.  Here the page gather lands directly in
VMEM and every intermediate (scores, probs) lives and dies there; HBM
traffic is the theoretical floor (read each page once, write [B, H, D]
once).

Layout contract (matches PagedKVCache):
  q            [B, KV, G, D]   (G = H // KV query heads per KV head)
  k/v_pages    [KV, P, ps, D]  (the pool; P = total pages)
  lengths      [B]   int32     valid tokens per sequence
  page_indices [B, pps] int32  each sequence's block-table window
returns        [B, KV, G, D]

TPU constraints (callers gate, inference/paged.py): D % 128 == 0 (lane
tiling), page_size % 8 == 0 (f32 sublane tiling of the DMA'd page).
Off-TPU the kernel runs in interpreter mode (tests); serving uses the
dense jnp path there.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(len_ref, tbl_ref, q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf,
            sem, *, page_size, pages_per_seq, scale):
    b = pl.program_id(0)
    kv = pl.program_id(1)
    # Keep every scalar explicitly i32: the repo's global x64 mode turns
    # weak Python-int constants into i64 at lowering, and a mixed
    # i32/i64 divide fails StableHLO verification (interpret mode) and
    # Mosaic (compiled).
    length = len_ref[b]
    npages = pl.cdiv(length, jnp.int32(page_size))

    def page_dma(i, pool, buf):
        """HBM pool page -> VMEM window row block, one async copy."""
        return pltpu.make_async_copy(
            pool.at[kv, tbl_ref[b, i]],
            buf.at[pl.ds(i * page_size, page_size)],
            sem)

    # Start EVERY needed page copy before waiting on any (the DMA engine
    # pipelines them); zero the window tail instead — VMEM scratch holds
    # garbage from the previous program, and a NaN bit pattern in V
    # would poison p @ V even at p == 0.
    for i in range(pages_per_seq):
        @pl.when(i < npages)
        def _start():
            page_dma(i, k_hbm, k_buf).start()
            page_dma(i, v_hbm, v_buf).start()

        @pl.when(i >= npages)
        def _zero():
            k_buf[pl.ds(i * page_size, page_size)] = jnp.zeros(
                (page_size, k_buf.shape[-1]), k_buf.dtype)
            v_buf[pl.ds(i * page_size, page_size)] = jnp.zeros(
                (page_size, v_buf.shape[-1]), v_buf.dtype)

    for i in range(pages_per_seq):
        @pl.when(i < npages)
        def _wait():
            page_dma(i, k_hbm, k_buf).wait()
            page_dma(i, v_hbm, v_buf).wait()

    q = q_ref[0, 0].astype(jnp.float32) * jnp.float32(scale)  # [G, D]
    k = k_buf[...].astype(jnp.float32)               # [S_window, D]
    v = v_buf[...].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    S = k.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (q.shape[0], S), 1)
    s = jnp.where(col < length, s, jnp.float32(-1e30))
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.sum(e, axis=1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("scale",))
def _call(q, k_pages, v_pages, lengths, page_indices, scale):
    B, KV, G, D = q.shape
    ps = k_pages.shape[2]
    pps = page_indices.shape[1]
    kernel = functools.partial(_kernel, page_size=ps, pages_per_seq=pps,
                               scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # lengths + page table
        grid=(B, KV),
        in_specs=[
            pl.BlockSpec((1, 1, G, D),
                         lambda b, kv, lens, tbl: (b, kv, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, G, D),
                               lambda b, kv, lens, tbl: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((pps * ps, D), k_pages.dtype),
            pltpu.VMEM((pps * ps, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    # Mosaic rejects i64 grid/index constants from the repo's global
    # x64 mode — trace x64-off like every other kernel in this package.
    with jax.enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, KV, G, D), q.dtype),
            interpret=_interpret(),
        )(jnp.asarray(lengths, jnp.int32),
          jnp.asarray(page_indices, jnp.int32), q, k_pages, v_pages)


def paged_decode(q, k_pages, v_pages, lengths, page_indices, scale=None):
    """Fused paged-decode attention over the page pool.

    q [B, H, D] (H % KV == 0); k/v_pages [KV, P, ps, D]; lengths [B];
    page_indices [B, pps].  Returns [B, H, D].  Pure function of its
    arguments (no custom VJP: decode is inference-only).
    """
    B, H, D = q.shape
    KV = k_pages.shape[0]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, KV, H // KV, D)
    out = _call(qg, k_pages, v_pages, lengths, page_indices,
                float(scale))
    return out.reshape(B, H, D)


def _kernel_quant(len_ref, tbl_ref, ks_ref, vs_ref, q_ref, k_hbm, v_hbm,
                  o_ref, k_buf, v_buf, sem, *, page_size, pages_per_seq,
                  scale):
    """Int8-page variant (PT_QUANT=int8): the pools ride HBM→VMEM as
    int8 (half the bytes of bf16 — the decode step IS this stream) and
    the per-page f32 scales arrive via scalar prefetch; dequant is a
    per-page broadcast multiply on the f32 window right next to the MXU
    dots.  Math past the dequant is identical to ``_kernel``."""
    b = pl.program_id(0)
    kv = pl.program_id(1)
    length = len_ref[b]
    npages = pl.cdiv(length, jnp.int32(page_size))

    def page_dma(i, pool, buf):
        return pltpu.make_async_copy(
            pool.at[kv, tbl_ref[b, i]],
            buf.at[pl.ds(i * page_size, page_size)],
            sem)

    for i in range(pages_per_seq):
        @pl.when(i < npages)
        def _start():
            page_dma(i, k_hbm, k_buf).start()
            page_dma(i, v_hbm, v_buf).start()

        @pl.when(i >= npages)
        def _zero():
            k_buf[pl.ds(i * page_size, page_size)] = jnp.zeros(
                (page_size, k_buf.shape[-1]), k_buf.dtype)
            v_buf[pl.ds(i * page_size, page_size)] = jnp.zeros(
                (page_size, v_buf.shape[-1]), v_buf.dtype)

    for i in range(pages_per_seq):
        @pl.when(i < npages)
        def _wait():
            page_dma(i, k_hbm, k_buf).wait()
            page_dma(i, v_hbm, v_buf).wait()

    # Per-row dequant scale for the window: row r belongs to window page
    # r // page_size, whose pool page id is tbl[b, i] — a static unroll
    # over the (small) page window turns the SMEM scale gathers into a
    # [S_window, 1] VMEM vector.
    S = k_buf.shape[0]
    row_page = jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0) \
        // jnp.int32(page_size)
    k_scale = jnp.zeros((S, 1), jnp.float32)
    v_scale = jnp.zeros((S, 1), jnp.float32)
    for i in range(pages_per_seq):
        pid = tbl_ref[b, i]
        k_scale = jnp.where(row_page == i, ks_ref[kv, pid], k_scale)
        v_scale = jnp.where(row_page == i, vs_ref[kv, pid], v_scale)

    q = q_ref[0, 0].astype(jnp.float32) * jnp.float32(scale)  # [G, D]
    k = k_buf[...].astype(jnp.float32) * k_scale     # [S_window, D]
    v = v_buf[...].astype(jnp.float32) * v_scale
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    col = jax.lax.broadcasted_iota(jnp.int32, (q.shape[0], S), 1)
    s = jnp.where(col < length, s, jnp.float32(-1e30))
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.sum(e, axis=1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale",))
def _call_quant(q, k_pages, v_pages, lengths, page_indices, k_scales,
                v_scales, scale):
    B, KV, G, D = q.shape
    ps = k_pages.shape[2]
    pps = page_indices.shape[1]
    kernel = functools.partial(_kernel_quant, page_size=ps,
                               pages_per_seq=pps, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # lengths + page table + k/v page scales
        grid=(B, KV),
        in_specs=[
            pl.BlockSpec((1, 1, G, D),
                         lambda b, kv, lens, tbl, ks, vs: (b, kv, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, G, D),
                               lambda b, kv, lens, tbl, ks, vs:
                               (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((pps * ps, D), k_pages.dtype),
            pltpu.VMEM((pps * ps, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    with jax.enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, KV, G, D), q.dtype),
            interpret=_interpret(),
        )(jnp.asarray(lengths, jnp.int32),
          jnp.asarray(page_indices, jnp.int32),
          jnp.asarray(k_scales, jnp.float32),
          jnp.asarray(v_scales, jnp.float32), q, k_pages, v_pages)


def paged_decode_quant(q, k_pages, v_pages, lengths, page_indices,
                       k_scales, v_scales, scale=None):
    """Fused paged-decode attention over an int8 page pool.

    Same layout contract as :func:`paged_decode` with int8 pools plus
    per-page f32 scales ``[KV, P]`` (one per (kv-head, page), kept with
    the page table by PagedKVCache).
    """
    B, H, D = q.shape
    KV = k_pages.shape[0]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, KV, H // KV, D)
    out = _call_quant(qg, k_pages, v_pages, lengths, page_indices,
                      k_scales, v_scales, float(scale))
    return out.reshape(B, H, D)


def supported(head_dim, page_size, on_tpu):
    """Shape gate for the compiled (non-interpret) kernel: D must tile
    to 128 lanes and a page must tile to 8 f32 sublanes.  Off-TPU the
    interpreter imposes no tiling, but serving takes the dense path
    there (kernel-in-interpreter is test machinery, not a fast path)."""
    if not on_tpu:
        return False
    return head_dim % 128 == 0 and page_size % 8 == 0


def supported_quant(head_dim, page_size, on_tpu):
    """Gate for the int8-page kernel: int8 sublane tiling is 32, so the
    per-page DMA slices need page_size % 32 == 0 (vs 8 for the f32/bf16
    pools)."""
    if not on_tpu:
        return False
    return head_dim % 128 == 0 and page_size % 32 == 0


def paged_decode_spmd_rule(mesh, q_spec, k_spec, v_spec, len_spec,
                           tbl_spec):
    """SPMD rule: shard the batch dim (grid axis 0 — programs are
    independent per sequence) and/or the head dim (grid axis 1 — the
    pools' KV axis must carry the same sharding); D and the page axes
    are kernel-internal and must be replicated.  Output follows q."""
    return tuple(q_spec)[:2] + (None,)


def paged_decode_quant_spmd_rule(mesh, q_spec, k_spec, v_spec, len_spec,
                                 tbl_spec, ks_spec, vs_spec):
    """Same sharding story as :func:`paged_decode_spmd_rule`; the scale
    tables must carry the pools' KV sharding and are otherwise
    kernel-internal."""
    return tuple(q_spec)[:2] + (None,)


_HANDLE = None
_HANDLE_QUANT = None


def handle():
    """Custom-op handle (lazy — registration is global).  Registered as
    ``fused_paged_decode``: the dense fallback already owns the dynamic
    op name ``paged_decode_attention`` via ``cached_apply``, and custom
    ops must not shadow an existing name."""
    global _HANDLE
    if _HANDLE is None:
        from ...utils.cpp_extension import register_custom_op

        _HANDLE = register_custom_op(
            "fused_paged_decode", paged_decode,
            static_argnames=("scale",),
            spmd_rule=paged_decode_spmd_rule)
    return _HANDLE


def handle_quant():
    """Custom-op handle for the int8-page kernel, registered as
    ``fused_paged_decode_quant`` (same lazy-global pattern)."""
    global _HANDLE_QUANT
    if _HANDLE_QUANT is None:
        from ...utils.cpp_extension import register_custom_op

        _HANDLE_QUANT = register_custom_op(
            "fused_paged_decode_quant", paged_decode_quant,
            static_argnames=("scale",),
            spmd_rule=paged_decode_quant_spmd_rule)
    return _HANDLE_QUANT
