"""Int8-weight matmul Pallas kernel with fused dequant (#6, r19).

The serving-side weight GEMM for ``PT_QUANT=int8``: activations stay in
the compute dtype, the weight rides HBM→VMEM as int8 (half the bytes of
bf16 — decode is bandwidth-bound, so the weight stream IS the decode
step cost), and the per-output-channel f32 scale is applied to the f32
accumulator right next to the MXU op:

    acc[bm, bn] += x_blk @ qw_blk.astype(f32)        (K-block innermost)
    out = (acc * scale[bn]) * 1                      (flushed once)

Per-OUTPUT-channel scales commute with the K contraction, which is what
makes the late multiply exact w.r.t. dequant-then-dot.  Grid is
``(M/bm, N/bn, K/bk)`` with K innermost so each ``[bm, bn]`` output
tile accumulates across K blocks in VMEM f32 scratch (same
accumulate-then-flush shape as ``grouped_gemm``).

Routing mirrors the package convention: ``PT_QUANT_MATMUL`` ∈
{auto, pallas, einsum}; auto takes the kernel on TPU when K and N tile
to 128 lanes, else the caller's dequant-then-dot fallback
(``ops/quant.qmatmul``).  Tiles come from the autotune cache under
``quant_matmul_blocks``.  Inference-only: no VJP (quantized weights are
a serving artifact; training differentiates the dense weights).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: (row, col, contraction) tile.  int8 min tile is (32, 128); 512 on
#: the K axis keeps the MXU fed while one [bk, bn] int8 panel is 64 KB.
_DEFAULT_BLOCKS = (128, 256, 512)


def _interpret():
    return jax.default_backend() != "tpu"


def blocks(m, k, n):
    """(bm, bn, bk) for an [m, k] x [k, n] GEMM — the autotune winner
    when on record, clamped so bn divides n and bk divides k (callers
    gate k % 128 == n % 128 == 0; m is padded)."""
    from .. import autotune as _autotune

    bm, bn, bk = _autotune.lookup("quant_matmul_blocks", (k, n),
                                  default=_DEFAULT_BLOCKS)
    bn = min(int(bn), n)
    while n % bn != 0 and bn > 1:
        bn //= 2
    if n % bn != 0:
        bn = n
    bk = min(int(bk), k)
    while k % bk != 0 and bk > 1:
        bk //= 2
    if k % bk != 0:
        bk = k
    return int(bm), bn, bk


def _kernel(x_ref, w_ref, s_ref, o_ref, acc, *, n_kblocks):
    kb = pl.program_id(2)
    part = jax.lax.dot(x_ref[...].astype(jnp.float32),
                       w_ref[...].astype(jnp.float32),
                       preferred_element_type=jnp.float32)  # [bm, bn]

    @pl.when(kb == 0)
    def _init():
        acc[...] = part

    @pl.when(kb > 0)
    def _accum():
        acc[...] += part

    @pl.when(kb == n_kblocks - 1)
    def _flush():
        o_ref[...] = (acc[...] * s_ref[...]).astype(o_ref.dtype)


@jax.jit
def _pallas_qmm(x, qweight, scale):
    M, K = x.shape
    N = qweight.shape[-1]
    bm, bn, bk = blocks(M, K, N)
    bm = min(bm, max(8, -(-M // 8) * 8))  # tiny M: one padded row block
    pad = -M % bm
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rows = x.shape[0]
    kernel = functools.partial(_kernel, n_kblocks=K // bk)
    # Mosaic rejects i64 grid/index constants from the repo's global
    # x64 mode — trace x64-off like every other kernel in this package.
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(rows // bm, N // bn, K // bk),
            in_specs=[
                pl.BlockSpec((bm, bk), lambda i, j, kb: (i, kb)),
                pl.BlockSpec((bk, bn), lambda i, j, kb: (kb, j)),
                pl.BlockSpec((1, bn), lambda i, j, kb: (0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, kb: (i, j)),
            out_shape=jax.ShapeDtypeStruct((rows, N), x.dtype),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            interpret=_interpret(),
        )(x, qweight, scale)
    return out[:M]


def supported(k, n, on_tpu):
    """Shape gate for the compiled kernel: both the contraction and the
    output minor dim must tile to 128 lanes.  Off-TPU auto routing
    takes the dequant-then-dot fallback (interpreter mode is test
    machinery, not a fast path)."""
    if not on_tpu:
        return False
    return k % 128 == 0 and n % 128 == 0


def use_pallas(x_shape, w_shape, impl=None):
    """Route [M, K] x [K, N].  ``impl``/PT_QUANT_MATMUL ∈
    {auto, pallas, einsum}."""
    impl = (impl or os.environ.get("PT_QUANT_MATMUL", "auto")).lower()
    if impl not in ("auto", "pallas", "einsum"):
        raise ValueError(
            f"PT_QUANT_MATMUL={impl!r}: expected auto|pallas|einsum")
    if impl == "auto":
        return supported(w_shape[-2], w_shape[-1],
                         jax.default_backend() == "tpu")
    return impl == "pallas"


def quant_matmul(x, qweight, scale):
    """``x [M, K] @ int8 qweight [K, N] * scale [1, N] -> [M, N]`` in
    ``x.dtype``, dequant fused into the kernel flush."""
    return _pallas_qmm(x, qweight, scale.astype(jnp.float32))


def quant_matmul_spmd_rule(mesh, x_spec, w_spec, s_spec):
    """SPMD rule: the row (batch·token) dim may shard — output tiles
    are independent per row block; K/N are kernel-internal (the scale
    must ride with its N shard, so both stay replicated).  Output
    follows x's leading dim."""
    return (tuple(x_spec)[:1] or (None,)) + (None,)


_HANDLE = None


def handle():
    """Custom-op handle (lazy — registration is global).  Registered as
    ``quant_matmul`` for out-of-tree callers; the serving executor calls
    ``ops.quant.qmatmul`` directly (it already runs inside a registered
    program's trace)."""
    global _HANDLE
    if _HANDLE is None:
        from ...utils.cpp_extension import register_custom_op

        _HANDLE = register_custom_op(
            "quant_matmul", quant_matmul,
            spmd_rule=quant_matmul_spmd_rule)
    return _HANDLE
