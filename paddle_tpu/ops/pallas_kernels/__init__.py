"""Self-authored Pallas TPU kernels (the repo's analog of the
reference's hand-written fusion kernels, paddle/phi/kernels/fusion/).

Unlike ``jax.experimental.pallas.ops.tpu`` stock kernels, these are
designed for this framework's hot paths and profiles:

- ``short_attention``: fused attention + softmax + DROPOUT for short
  sequences (BERT-class S<=1024), where materializing [B,H,S,S] probs
  and their dropout masks in HBM dominated the step (r4 profile:
  ~60 ms of a 180 ms BERT step).
- ``grouped_gemm``: both expert matmuls of a sort-dispatched MoE step
  for all experts in one kernel (MegaBlocks-style), the [E, C, F]
  hidden activation VMEM-resident per tile instead of an HBM
  round-trip.
"""
from .grouped_gemm import grouped_ffn  # noqa: F401
from .short_attention import short_attention  # noqa: F401
