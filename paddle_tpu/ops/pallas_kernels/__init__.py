"""Self-authored Pallas TPU kernels (the repo's analog of the
reference's hand-written fusion kernels, paddle/phi/kernels/fusion/).

Unlike ``jax.experimental.pallas.ops.tpu`` stock kernels, these are
designed for this framework's hot paths and profiles:

- ``short_attention``: fused attention + softmax + DROPOUT for short
  sequences (BERT-class S<=1024), where materializing [B,H,S,S] probs
  and their dropout masks in HBM dominated the step (r4 profile:
  ~60 ms of a 180 ms BERT step).
- ``grouped_gemm``: both expert matmuls of a sort-dispatched MoE step
  for all experts in one kernel (MegaBlocks-style), the [E, C, F]
  hidden activation VMEM-resident per tile instead of an HBM
  round-trip.  Also carries the int8-expert-weight variant
  (``PT_QUANT=int8``) with dequant fused at the MXU.
- ``paged_decode``: single-token decode attention over the paged KV
  pool, one pipelined DMA burst per (sequence, kv-head); the
  ``_quant`` variant streams int8 pages with per-page scales via
  scalar prefetch.
- ``quant_matmul``: activation x int8-weight GEMM with the
  per-output-channel dequant applied to the f32 accumulator at flush —
  the serving weight matmul under ``PT_QUANT=int8``.
"""
from .grouped_gemm import grouped_ffn  # noqa: F401
# NOTE: the quant_matmul FUNCTION is deliberately not re-exported here —
# it would shadow the submodule name; callers go through ops.quant.qmatmul.
from . import quant_matmul  # noqa: F401
from .short_attention import short_attention  # noqa: F401
