"""Self-authored Pallas TPU kernels (the repo's analog of the
reference's hand-written fusion kernels, paddle/phi/kernels/fusion/).

Unlike ``jax.experimental.pallas.ops.tpu`` stock kernels, these are
designed for this framework's hot paths and profiles:

- ``short_attention``: fused attention + softmax + DROPOUT for short
  sequences (BERT-class S<=1024), where materializing [B,H,S,S] probs
  and their dropout masks in HBM dominated the step (r4 profile:
  ~60 ms of a 180 ms BERT step).
"""
from .short_attention import short_attention  # noqa: F401
