"""In-place op variants (``x.abs_()`` / ``paddle.abs_(x)``).

Reference: the ``*_`` entries in ``python/paddle/tensor/__init__.py``
(generated inplace kernels).  Under jax arrays are immutable, so
"in-place" here means paddle's *observable* contract: compute the
result, rebind it as the tensor's value (same Tensor object returned),
and keep version counting / tape semantics via ``set_value``.
"""
from __future__ import annotations

from ..core.tensor import Tensor

# (inplace name, functional source module attr) — bound lazily so this
# module can import before the functional namespace is assembled.
_UNARY = [
    "abs", "acos", "asin", "atan", "asinh", "acosh", "atanh", "ceil",
    "cos", "cosh", "digamma", "erf", "exp", "expm1", "floor", "frac",
    "lgamma", "log", "log10", "log1p", "log2", "logical_not", "neg",
    "reciprocal", "rsqrt", "sigmoid", "sin", "sinh", "sqrt", "square",
    "tan", "tanh", "trunc", "i0", "sinc", "logit", "nan_to_num",
    "bitwise_not", "gammaln", "sgn",
]
_BINARY = [
    "add", "subtract", "multiply", "divide", "remainder", "mod",
    "floor_divide", "floor_mod", "pow", "bitwise_and", "bitwise_or",
    "bitwise_xor", "logical_and", "logical_or", "logical_xor",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "gcd", "lcm", "copysign", "hypot", "ldexp",
    "nextafter", "gammainc", "gammaincc", "atan2", "fmax", "fmin",
    "maximum", "minimum", "bitwise_left_shift", "bitwise_right_shift",
]
_OTHER = [
    # (name, functional name) with pass-through args
    ("clip", "clip"), ("scale", "scale"), ("lerp", "lerp"),
    ("cumsum", "cumsum"), ("cumprod", "cumprod"),
    ("renorm", "renorm"), ("round", "round"),
    ("masked_fill", "masked_fill"), ("masked_scatter",
                                     "masked_scatter"),
    ("index_add", "index_add"), ("index_fill", "index_fill"),
    ("scatter", "scatter"), ("put_along_axis", "put_along_axis"),
    ("tril", "tril"), ("triu", "triu"), ("reshape", "reshape"),
    ("flatten", "flatten"), ("squeeze", "squeeze"),
    ("unsqueeze", "unsqueeze"), ("transpose", "transpose"),
    ("t", "t"), ("cast", "cast"), ("multigammaln", "multigammaln"),
    ("polygamma", "polygamma"), ("multiply", "multiply"),
    ("addmm", "addmm"), ("erfinv", "erfinv"),
]
# where_ is NOT generated: paddle.where_(cond, x, y) writes into x
# (the 2nd argument), not cond — it gets a hand-written wrapper.


def _make_inplace(func_name):
    def _inplace(x, *args, **kwargs):
        from .. import ops
        from .manipulation import _autograd_proxy

        if not isinstance(x, Tensor):
            raise TypeError(
                f"{func_name}_ requires a paddle Tensor, got {type(x)}")
        # Route through the autograd proxy so the recorded edge keeps
        # pointing at the OLD producer (no self-loop after rebind) —
        # same contract as Tensor.add_ in ops/__init__.
        out = getattr(ops, func_name)(_autograd_proxy(x), *args,
                                      **kwargs)
        x._data = out._data
        x._grad_node = out._grad_node
        x._out_slot = out._out_slot
        x.stop_gradient = out.stop_gradient and x.stop_gradient
        return x

    _inplace.__name__ = func_name + "_"
    _inplace.__doc__ = (f"In-place variant of ``{func_name}`` "
                        f"(reference tensor inplace API).")
    return _inplace


def where_(condition, x, y, name=None):
    """reference paddle.where_: writes the where result into ``x``."""
    from .. import ops
    from .manipulation import _autograd_proxy

    out = ops.where(condition, _autograd_proxy(x), y)
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_slot = out._out_slot
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    return x


def install(namespace):
    """Create every ``<op>_`` wrapper whose functional op exists in
    ``namespace`` (the assembled paddle_tpu.ops module)."""
    created = {"where_": where_}
    for name in set(_UNARY) | set(_BINARY) | {o[1] for o in _OTHER}:
        if hasattr(namespace, name):
            wrapper = _make_inplace(name)
            created[name + "_"] = wrapper
    return created
