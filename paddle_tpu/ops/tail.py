"""Op long tail, round 4 (VERDICT r3 missing #1: the ~150-op breadth
sprint).

Reference: ``python/paddle/tensor/{math,manipulation,creation,linalg,
stat,search,einsum}.py`` — each wrapper names its reference
counterpart by function name (the reference implements these as
ops.yaml kernels; here each is one fused jnp program dispatched
through the registry, with vjp-fallback gradients).
"""
from __future__ import annotations

import itertools
import math as _math

import numpy as np

import jax
import jax.numpy as jnp

from .extra import _simple
from .registry import apply, register_op

_sp = jax.scipy.special


# -- complex / elementwise tail ---------------------------------------------

real = _simple("real", lambda x: jnp.real(x))
imag = _simple("imag", lambda x: jnp.imag(x))
conj = _simple("conj", lambda x: jnp.conj(x))
angle = _simple("angle", lambda x: jnp.angle(x))
isreal = _simple("isreal", lambda x: jnp.isreal(x))
isneginf = _simple("isneginf", lambda x: jnp.isneginf(x))
isposinf = _simple("isposinf", lambda x: jnp.isposinf(x))
signbit = _simple("signbit", lambda x: jnp.signbit(x))
sinc = _simple("sinc", lambda x: jnp.sinc(x))
nextafter = _simple("nextafter", jnp.nextafter)


def _polar(abs, angle):
    return (abs * jnp.cos(angle)) + 1j * (abs * jnp.sin(angle))


polar = _simple("polar", _polar)
sgn = _simple(
    "sgn",
    lambda x: (jnp.where(x == 0, 0, x / jnp.abs(x))
               if jnp.iscomplexobj(x) else jnp.sign(x)))


def _logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x) - jnp.log1p(-x)


logit = _simple("logit", _logit, static=("eps",))
round = _simple(
    "round",
    lambda x, decimals=0: jnp.round(x, decimals), static=("decimals",))

# -- special functions -------------------------------------------------------

gammaln = _simple("gammaln", lambda x: _sp.gammaln(x))
gammainc = _simple("gammainc", lambda x, y: _sp.gammainc(x, y))
gammaincc = _simple("gammaincc", lambda x, y: _sp.gammaincc(x, y))


def _multigammaln(x, p):
    i = jnp.arange(1, p + 1, dtype=x.dtype)
    return (p * (p - 1) / 4.0 * _math.log(_math.pi)
            + jnp.sum(_sp.gammaln(x[..., None] + (1 - i) / 2.0), -1))


multigammaln = _simple("multigammaln", _multigammaln, static=("p",))
i0e = _simple("i0e", lambda x: _sp.i0e(x))
i1 = _simple("i1", lambda x: _sp.i1(x))
i1e = _simple("i1e", lambda x: _sp.i1e(x))
polygamma = _simple(
    "polygamma", lambda x, n: _sp.polygamma(n, x), static=("n",))

# -- construction / manipulation tail ---------------------------------------

_hstack_op = register_op("hstack", lambda *xs: jnp.hstack(xs))
_vstack_op = register_op("vstack", lambda *xs: jnp.vstack(xs))
_block_diag_op = register_op(
    "block_diag",
    lambda *xs: jax.scipy.linalg.block_diag(
        *[jnp.atleast_2d(x) for x in xs]))
_add_n_op = register_op("add_n", lambda *xs: sum(xs[1:], xs[0]))
_cartesian_prod_op = register_op(
    "cartesian_prod",
    lambda *xs: jnp.stack(
        [g.ravel() for g in jnp.meshgrid(*xs, indexing="ij")], -1))


def hstack(x, name=None):
    """reference manipulation.hstack(list)."""
    return apply(_hstack_op, *x)


def vstack(x, name=None):
    """reference manipulation.vstack(list)."""
    return apply(_vstack_op, *x)


def block_diag(inputs, name=None):
    """reference creation.block_diag(list)."""
    return apply(_block_diag_op, *inputs)


def add_n(inputs, name=None):
    """reference math.add_n(list)."""
    if not isinstance(inputs, (list, tuple)):
        return inputs
    return apply(_add_n_op, *inputs)


def cartesian_prod(x, name=None):
    """reference math.cartesian_prod(list of 1-D tensors)."""
    return apply(_cartesian_prod_op, *x)


def _combinations_impl(x, r, with_replacement):
    n = x.shape[0]
    pick = (itertools.combinations_with_replacement
            if with_replacement else itertools.combinations)
    idx = np.asarray(list(pick(range(n), r)), np.int32)
    if idx.size == 0:
        return jnp.zeros((0, r), x.dtype)
    return x[idx]


combinations = _simple(
    "combinations",
    lambda x, r=2, with_replacement=False: _combinations_impl(
        x, r, with_replacement),
    static=("r", "with_replacement"))
reverse = _simple(
    "reverse", lambda x, axis: jnp.flip(x, axis), static=("axis",))


def _crop(x, shape=None, offsets=None):
    shape = list(x.shape) if shape is None else list(shape)
    offsets = [0] * x.ndim if offsets is None else list(offsets)
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]
    sl = tuple(jnp.s_[o:o + s] for o, s in zip(offsets, shape))
    return x[sl]


_crop_op = register_op("crop", _crop,
                       static_argnames=("shape", "offsets"))


def crop(x, shape=None, offsets=None, name=None):
    """reference creation.crop."""
    return apply(_crop_op, x,
                 shape=None if shape is None else tuple(shape),
                 offsets=None if offsets is None else tuple(offsets))


def _unflatten(x, axis, shape):
    axis = axis % x.ndim
    shape = tuple(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape = tuple(x.shape[axis] // known if s == -1 else s
                      for s in shape)
    return x.reshape(x.shape[:axis] + shape + x.shape[axis + 1:])


unflatten = _simple("unflatten", _unflatten, static=("axis", "shape"))


def view_as(x, other):
    """reference manipulation.view_as: reshape to other's shape."""
    from . import reshape

    return reshape(x, list(other.shape))


def _strided_slice(x, axes, starts, ends, strides):
    sl = [jnp.s_[:]] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        sl[ax] = jnp.s_[s:e:st]
    return x[tuple(sl)]


_strided_slice_op = register_op(
    "strided_slice", _strided_slice,
    static_argnames=("axes", "starts", "ends", "strides"))


def strided_slice(x, axes, starts, ends, strides, name=None):
    """reference manipulation.strided_slice."""
    return apply(_strided_slice_op, x, axes=tuple(axes),
                 starts=tuple(starts), ends=tuple(ends),
                 strides=tuple(strides))


def _scatter_nd(index, updates, shape):
    # duplicate indices accumulate, matching the reference kernel.
    out = jnp.zeros(shape, updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return out.at[idx].add(updates)


scatter_nd = _simple("scatter_nd", _scatter_nd, static=("shape",))


def _diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    axis1 = axis1 % x.ndim
    axis2 = axis2 % x.ndim
    xm = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    n, m = xm.shape[-2], xm.shape[-1]
    rows = jnp.arange(min(n, m - offset) if offset >= 0
                      else min(n + offset, m))
    if offset >= 0:
        r, c = rows, rows + offset
    else:
        r, c = rows - offset, rows
    out = xm.at[..., r, c].set(y)
    return jnp.moveaxis(out, (-2, -1), (axis1, axis2))


diagonal_scatter = _simple(
    "diagonal_scatter", _diagonal_scatter,
    static=("offset", "axis1", "axis2"))


def _masked_scatter(x, mask, value):
    mask_b = jnp.broadcast_to(mask, x.shape)
    flat_v = value.reshape(-1)
    # k-th True position takes value[k]: positions = cumsum(mask) - 1
    pos = jnp.cumsum(mask_b.reshape(-1)) - 1
    take = flat_v[jnp.clip(pos, 0, flat_v.shape[0] - 1)]
    return jnp.where(mask_b, take.reshape(x.shape), x)


masked_scatter = _simple("masked_scatter", _masked_scatter)


def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


index_sample = _simple("index_sample", _index_sample)


def _multiplex(index, *inputs):
    stacked = jnp.stack(inputs, 0)  # [k, B, ...]
    idx = index.reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def multiplex(inputs, index, name=None):
    """reference tensor/math.multiplex(inputs, index): row b of the
    output comes from inputs[index[b]][b]."""
    return apply(_multiplex_op, index, *inputs)


_multiplex_op = register_op("multiplex", _multiplex)


def _shard_index(x, index_num, nshards, shard_id, ignore_value):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    inside = (x >= lo) & (x < lo + shard_size)
    return jnp.where(inside, x - lo, ignore_value)


shard_index = _simple(
    "shard_index",
    lambda x, index_num, nshards, shard_id, ignore_value=-1:
    _shard_index(x, index_num, nshards, shard_id, ignore_value),
    static=("index_num", "nshards", "shard_id", "ignore_value"))


def _reduce_as(x, target_shape):
    extra = len(x.shape) - len(target_shape)
    axes = list(range(extra))
    for i, t in enumerate(target_shape):
        if x.shape[extra + i] != t:
            axes.append(extra + i)
    out = jnp.sum(x, axis=tuple(axes), keepdims=True)
    return out.reshape(target_shape)


def reduce_as(x, target, name=None):
    """reference math.reduce_as: sum x down to target's shape."""
    return apply(_reduce_as_op, x,
                 target_shape=tuple(int(d) for d in target.shape))


_reduce_as_op = register_op("reduce_as", _reduce_as,
                            static_argnames=("target_shape",))


def _isin(x, test_x, assume_unique, invert):
    out = jnp.isin(x, test_x, invert=invert)
    return out


isin = _simple(
    "isin",
    lambda x, test_x, assume_unique=False, invert=False: _isin(
        x, test_x, assume_unique, invert),
    static=("assume_unique", "invert"))

# creation-style index helpers (int outputs, no grad)
tril_indices = _simple(
    "tril_indices",
    lambda row, col=None, offset=0: jnp.stack(
        jnp.tril_indices(row, offset, col if col is not None else row)),
    static=("row", "col", "offset"))
triu_indices = _simple(
    "triu_indices",
    lambda row, col=None, offset=0: jnp.stack(
        jnp.triu_indices(row, offset, col if col is not None else row)),
    static=("row", "col", "offset"))


def shape(x):
    """reference tensor/attribute.shape: runtime shape as int32 tensor."""
    from ..core.tensor import Tensor

    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.asarray(np.asarray(data.shape, np.int32)))


def is_empty(x):
    from ..core.tensor import Tensor

    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.asarray(data.size == 0))


def is_integer(x):
    data = getattr(x, "_data", x)
    return jnp.issubdtype(data.dtype, jnp.integer)


def is_complex(x):
    data = getattr(x, "_data", x)
    return jnp.issubdtype(data.dtype, jnp.complexfloating)


def is_floating_point(x):
    data = getattr(x, "_data", x)
    return jnp.issubdtype(data.dtype, jnp.floating)


# -- stat tail ---------------------------------------------------------------

nanquantile = _simple(
    "nanquantile",
    lambda x, q, axis=None, keepdim=False: jnp.nanquantile(
        x, q, axis=axis, keepdims=keepdim),
    static=("q", "axis", "keepdim"))


def _pdist(x, p):
    n = x.shape[-2]
    i, j = np.triu_indices(n, 1)
    d = x[..., i, :] - x[..., j, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(d), -1)
    return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)


pdist = _simple("pdist", lambda x, p=2.0: _pdist(x, p), static=("p",))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """reference tensor/linalg.histogramdd.  Returns (hist, edges)."""
    from ..core.tensor import Tensor

    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    w = weights._data if isinstance(weights, Tensor) else weights
    if isinstance(bins, (list, tuple)) and len(bins) and \
            hasattr(bins[0], "__len__"):
        bins = [np.asarray(getattr(b, "_data", b)) for b in bins]
    hist, edges = jnp.histogramdd(data, bins=bins, range=ranges,
                                  density=density, weights=w)
    return Tensor(hist), [Tensor(e) for e in edges]


def _cumulative_trapezoid(y, x, dx, axis):
    y = jnp.moveaxis(y, axis, -1)
    if x is not None:
        x = jnp.moveaxis(jnp.broadcast_to(x, y.shape), axis, -1) \
            if x.ndim > 1 else x
        d = jnp.diff(x, axis=-1)
    else:
        d = dx
    avg = (y[..., 1:] + y[..., :-1]) / 2.0
    out = jnp.cumsum(avg * d, -1)
    return jnp.moveaxis(out, -1, axis)


cumulative_trapezoid = _simple(
    "cumulative_trapezoid",
    lambda y, x=None, dx=1.0, axis=-1: _cumulative_trapezoid(
        y, x, dx, axis),
    static=("dx", "axis"))

# -- linalg tail -------------------------------------------------------------

mv = _simple("mv", lambda x, vec: jnp.matmul(x, vec))
vecdot = _simple(
    "vecdot",
    lambda x, y, axis=-1: jnp.sum(jnp.conj(x) * y, axis=axis),
    static=("axis",))


def _householder_product(x, tau):
    return jax.lax.linalg.householder_product(x, tau)


householder_product = _simple("householder_product",
                              _householder_product)


def _geqrf(x):
    # LAPACK-packed Householder QR (R in/above the diagonal, reflector
    # vectors below it, with implicit unit diagonal) — the exact format
    # jax.lax.linalg.householder_product consumes.  The column loop is
    # static (k = min(m, n)) so it traces to one fused program.
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    a = x
    taus = []
    for j in range(k):
        col = a[..., j:, j]
        normx = jnp.sqrt(jnp.sum(col * col, -1))
        alpha = col[..., 0]
        sign = jnp.where(alpha >= 0, 1.0, -1.0).astype(x.dtype)
        u1 = alpha + sign * normx
        safe = jnp.abs(u1) > 0
        v = jnp.where(safe[..., None], col / jnp.where(
            safe, u1, 1.0)[..., None], 0.0)
        v = v.at[..., 0].set(1.0)
        tau = jnp.where(safe & (normx > 0),
                        sign * u1 / jnp.where(normx > 0, normx, 1.0),
                        0.0)
        # apply reflector to the trailing block only — earlier columns
        # already hold packed reflector vectors
        w = jnp.einsum("...i,...ij->...j", v, a[..., j:, j:])
        a = a.at[..., j:, j:].add(
            -tau[..., None, None] * v[..., :, None] * w[..., None, :])
        # pack v below the diagonal
        a = a.at[..., j + 1:, j].set(v[..., 1:])
        taus.append(tau)
    return a, jnp.stack(taus, -1).astype(x.dtype)


_geqrf_op = register_op("geqrf", _geqrf, n_outputs=2)


def geqrf(x, name=None):
    """reference linalg.geqrf: householder QR factors (a, tau)."""
    return apply(_geqrf_op, x)


def _ormqr(x, tau, other, left, transpose):
    # build the FULL m x m Q (LAPACK ormqr applies the square Q): pad
    # the packed reflectors out to m columns with zero taus.
    m, k = x.shape[-2], x.shape[-1]
    if k < m:
        pad_a = [(0, 0)] * (x.ndim - 1) + [(0, m - k)]
        pad_t = [(0, 0)] * (tau.ndim - 1) + [(0, m - k)]
        x = jnp.pad(x, pad_a)
        tau = jnp.pad(tau, pad_t)
    q = jax.lax.linalg.householder_product(x, tau)
    if transpose:
        q = jnp.swapaxes(q, -2, -1)
    return jnp.matmul(q, other) if left else jnp.matmul(other, q)


ormqr = _simple(
    "ormqr",
    lambda x, tau, other, left=True, transpose=False: _ormqr(
        x, tau, other, left, transpose),
    static=("left", "transpose"))


def _cholesky_inverse(x, upper):
    L = jnp.swapaxes(x, -2, -1) if upper else x
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -2, -1), linv)


cholesky_inverse = _simple(
    "cholesky_inverse",
    lambda x, upper=False: _cholesky_inverse(x, upper),
    static=("upper",))


def _frexp(x):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


_frexp_op = register_op("frexp", _frexp, n_outputs=2)


def frexp(x, name=None):
    """reference math.frexp -> (mantissa, exponent)."""
    return apply(_frexp_op, x)


def _logical_rshift(a, b):
    u = a.astype(jnp.uint32 if a.dtype.itemsize == 4 else jnp.uint64) \
        if jnp.issubdtype(a.dtype, jnp.signedinteger) else a
    out = jax.lax.shift_right_logical(u, u.dtype.type(0) + b.astype(
        u.dtype))
    return out.astype(a.dtype)


_logical_rshift_op = register_op("bitwise_right_shift_logical",
                                 _logical_rshift)


def bitwise_left_shift(x, y, is_arithmetic=True, name=None):
    """reference math.bitwise_left_shift (left shift is identical in
    arithmetic and logical modes)."""
    from . import left_shift

    return left_shift(x, y)


def bitwise_right_shift(x, y, is_arithmetic=True, name=None):
    """reference math.bitwise_right_shift; is_arithmetic=False is a
    logical shift (zero-fill) via an unsigned reinterpret."""
    from . import right_shift

    if is_arithmetic:
        return right_shift(x, y)
    return apply(_logical_rshift_op, x, y)
