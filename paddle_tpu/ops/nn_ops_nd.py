"""N-dimensional conv/pool op family (1d/3d + adaptive/unpool/lp/
fractional variants), round 4 breadth sprint.

Reference: ``python/paddle/nn/functional/{conv,pooling}.py`` — conv1d_
transpose:693, conv3d:1260, conv3d_transpose:1468, the pooling file's
{max,avg,lp}_pool{1,2,3}d, adaptive_*_pool*, max_unpool*,
fractional_max_pool* (phi kernels pool_kernel.cc/unpool_kernel.cc).
Each lowers to one ``lax.reduce_window``/``conv_general_dilated``
program; channel-first layouts throughout (NCL/NCHW/NCDHW like the
reference defaults).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_op


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        assert len(v) == n, (v, n)
        return tuple(int(x) for x in v)
    return (int(v),) * n


# -- conv tail ---------------------------------------------------------------

def _conv1d_transpose_plain(x, w, stride=1, padding=0, output_padding=0,
                            dilation=1, groups=1):
    # [N, C, L] x [Cin, Cout/g, K]
    k = w.shape[2]
    pad = [(dilation * (k - 1) - padding,
            dilation * (k - 1) - padding + output_padding)]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCH", "IOH", "NCH"))
    if w.dtype != x.dtype:
        w = w.astype(x.dtype)
    w = jnp.flip(w, axis=-1)  # transposed conv mirrors the kernel
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1,), padding=pad, lhs_dilation=(stride,),
        rhs_dilation=(dilation,), dimension_numbers=dn,
        feature_group_count=groups)


conv1d_transpose_op = register_op(
    "conv1d_transpose", _conv1d_transpose_plain,
    static_argnames=("stride", "padding", "output_padding", "dilation",
                     "groups"))


def _conv3d_plain(x, w, stride=(1, 1, 1), padding=(0, 0, 0),
                  dilation=(1, 1, 1), groups=1):
    if w.dtype != x.dtype:
        w = w.astype(x.dtype)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NCDHW", "OIDHW", "NCDHW"))
    pad = [(p, p) for p in padding]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)


conv3d_op = register_op(
    "conv3d", _conv3d_plain,
    static_argnames=("stride", "padding", "dilation", "groups"))


def _conv3d_transpose_plain(x, w, stride=(1, 1, 1), padding=(0, 0, 0),
                            output_padding=(0, 0, 0),
                            dilation=(1, 1, 1), groups=1):
    if w.dtype != x.dtype:
        w = w.astype(x.dtype)
    w = jnp.flip(w, axis=(-3, -2, -1))  # mirrored kernel (see 2d)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NCDHW", "IODHW", "NCDHW"))
    pad = [(dilation[i] * (w.shape[2 + i] - 1) - padding[i],
            dilation[i] * (w.shape[2 + i] - 1) - padding[i]
            + output_padding[i]) for i in range(3)]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1, 1), padding=pad,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)


conv3d_transpose_op = register_op(
    "conv3d_transpose", _conv3d_transpose_plain,
    static_argnames=("stride", "padding", "output_padding", "dilation",
                     "groups"))


# -- generic channel-first pooling ------------------------------------------

def _ceil_extension(L, k, s, p):
    """High-side padding extension for ceil_mode, with the reference
    rule that a window starting entirely inside the RIGHT padding is
    dropped: extend only while the extra window's start < L."""
    rem = (L + 2 * p - k) % s
    if rem == 0:
        return 0
    floor_out = (L + 2 * p - k) // s + 1
    start = floor_out * s - p  # start index of the candidate window
    if start >= L:
        return 0
    return s - rem


def _pool_nd(x, kernel, stride, padding, nd, op, exclusive=True,
             ceil_mode=False, divisor_override=None):
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    extra = [(_ceil_extension(x.shape[2 + i], kernel[i], stride[i],
                              padding[i]) if ceil_mode else 0)
             for i in range(nd)]
    if op == "max":
        pads = ((0, 0), (0, 0)) + tuple(
            (p, p + e) for p, e in zip(padding, extra))
        neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
               else jnp.iinfo(x.dtype).min)
        return jax.lax.reduce_window(x, neg, jax.lax.max, window,
                                     strides, pads)
    # fast path: no padding/ceil/override -> the divisor is the
    # compile-time constant prod(kernel); one reduce_window, no pad copy
    if (divisor_override is None and not any(padding)
            and not any(extra)):
        pads0 = ((0, 0), (0, 0)) + ((0, 0),) * nd
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window,
                                       strides, pads0)
        return summed / float(np.prod(kernel))
    # avg: pad the data explicitly so the DIVISOR semantics are exact —
    # exclusive=True counts real elements only; exclusive=False
    # (count_include_pad) counts real + declared padding but NEVER the
    # implicit ceil extension; divisor_override replaces the count.
    widths = ((0, 0), (0, 0)) + tuple((p, p) for p in padding)
    xp = jnp.pad(x, widths)
    pads = ((0, 0), (0, 0)) + tuple((0, e) for e in extra)
    summed = jax.lax.reduce_window(xp, 0.0, jax.lax.add, window,
                                   strides, pads)
    if divisor_override is not None:
        return summed / float(divisor_override)
    if exclusive:
        mask = jnp.pad(jnp.ones_like(x), widths)
    else:
        mask = jnp.ones_like(xp)
    counts = jax.lax.reduce_window(mask, 0.0, jax.lax.add, window,
                                   strides, pads)
    return summed / counts


def _mk_pool(name, nd, op):
    def plain(x, kernel_size, stride, padding, ceil_mode=False,
              exclusive=True, divisor_override=None):
        return _pool_nd(x, kernel_size, stride, padding, nd, op,
                        exclusive, ceil_mode, divisor_override)

    return register_op(name, plain, static_argnames=(
        "kernel_size", "stride", "padding", "ceil_mode", "exclusive",
        "divisor_override"))


max_pool1d_op = _mk_pool("max_pool1d", 1, "max")
max_pool3d_op = _mk_pool("max_pool3d", 3, "max")
avg_pool1d_op = _mk_pool("avg_pool1d", 1, "avg")
avg_pool3d_op = _mk_pool("avg_pool3d", 3, "avg")
avg_pool2d_g_op = _mk_pool("avg_pool2d_g", 2, "avg")


def _lp_pool_nd(x, kernel_size, stride, padding, norm_type):
    window = (1, 1) + kernel_size
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding)
    if norm_type == float("inf"):
        neg = -jnp.inf
        return jax.lax.reduce_window(jnp.abs(x), neg, jax.lax.max,
                                     window, strides, pads)
    powed = jnp.abs(x) ** norm_type
    s = jax.lax.reduce_window(powed, 0.0, jax.lax.add, window, strides,
                              pads)
    return s ** (1.0 / norm_type)


lp_pool1d_op = register_op(
    "lp_pool1d",
    lambda x, kernel_size, stride, padding, norm_type: _lp_pool_nd(
        x, kernel_size, stride, padding, norm_type),
    static_argnames=("kernel_size", "stride", "padding", "norm_type"))
lp_pool2d_op = register_op(
    "lp_pool2d",
    lambda x, kernel_size, stride, padding, norm_type: _lp_pool_nd(
        x, kernel_size, stride, padding, norm_type),
    static_argnames=("kernel_size", "stride", "padding", "norm_type"))


# -- adaptive pooling --------------------------------------------------------

def _adaptive_regions(in_size, out_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-(np.arange(1, out_size + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool_nd(x, output_size, op):
    """Adaptive pooling via per-output-region slicing (regions are
    host-computed from static shapes; the reference kernel's
    start/end index formula, pooling.py AdaptiveAvgPool)."""
    spatial = x.shape[2:]
    nd = len(spatial)
    out = x
    # pool one axis at a time: axis k of the output indexes regions
    for axis in range(nd):
        in_size, out_size = out.shape[2 + axis], output_size[axis]
        starts, ends = _adaptive_regions(in_size, out_size)
        cols = []
        for s, e in zip(starts, ends):
            sl = [slice(None)] * out.ndim
            sl[2 + axis] = slice(int(s), int(e))
            region = out[tuple(sl)]
            red = (jnp.max if op == "max" else jnp.mean)(
                region, axis=2 + axis, keepdims=True)
            cols.append(red)
        out = jnp.concatenate(cols, axis=2 + axis)
    return out


def _mk_adaptive(name, op):
    def plain(x, output_size):
        return _adaptive_pool_nd(x, output_size, op)

    return register_op(name, plain, static_argnames=("output_size",))


adaptive_avg_pool1d_op = _mk_adaptive("adaptive_avg_pool1d", "avg")
adaptive_avg_pool3d_op = _mk_adaptive("adaptive_avg_pool3d", "avg")
adaptive_max_pool1d_op = _mk_adaptive("adaptive_max_pool1d", "max")
adaptive_max_pool2d_op = _mk_adaptive("adaptive_max_pool2d", "max")
adaptive_max_pool3d_op = _mk_adaptive("adaptive_max_pool3d", "max")


# -- max pooling with indices + unpool --------------------------------------

def _max_pool_with_index_nd(x, kernel_size, stride, padding):
    """Returns (pooled, flat_indices) — indices over the flattened
    spatial dims, matching the reference unpool contract."""
    spatial = x.shape[2:]
    nd = len(spatial)
    flat_spatial = int(np.prod(spatial))
    idx = jnp.arange(flat_spatial).reshape(spatial)
    idx = jnp.broadcast_to(idx, x.shape).astype(jnp.int32)
    window = (1, 1) + kernel_size
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding)
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
        jnp.iinfo(x.dtype).min

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = (bv > av) | ((bv == av) & (bi < ai))
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    vals, idxs = jax.lax.reduce_window(
        (x, idx), (jnp.asarray(neg, x.dtype), jnp.asarray(
            flat_spatial, jnp.int32)),
        reducer, window, strides, pads)
    return vals, idxs


def _max_pool_with_index_fwd(x, kernel_size, stride, padding):
    vals, idxs = _max_pool_with_index_nd(x, kernel_size, stride,
                                         padding)
    # residuals must be arrays (jit rejects dtype objects) and shapes
    # crossing the jit boundary become tracers — carry a zeros template
    # with x's shape+dtype instead
    return (vals, idxs), (idxs, jnp.zeros(x.shape, x.dtype))


def _max_pool_with_index_bwd(saved, g, kernel_size=None, stride=None,
                             padding=None):
    # variadic reduce_window has no JAX transpose rule; the argmax
    # indices ARE the backward routing: scatter-add dvals there.
    idxs, proto = saved
    x_shape = proto.shape
    gv = g[0] if isinstance(g, (tuple, list)) else g
    N, C = x_shape[:2]
    flat = int(np.prod(x_shape[2:]))
    out = jnp.zeros((N, C, flat), gv.dtype)
    out = jax.vmap(jax.vmap(lambda o, vv, ii: o.at[ii].add(vv)))(
        out, gv.reshape(N, C, -1),
        idxs.reshape(N, C, -1).astype(jnp.int32))
    return (out.reshape(x_shape).astype(proto.dtype),)


max_pool_with_index_op = register_op(
    "max_pool_with_index", _max_pool_with_index_nd, n_outputs=2,
    fwd=_max_pool_with_index_fwd, bwd=_max_pool_with_index_bwd,
    static_argnames=("kernel_size", "stride", "padding"))


def _max_unpool_nd(pooled, indices, out_spatial):
    """Scatter pooled values back to their argmax positions."""
    N, C = pooled.shape[:2]
    flat_out = int(np.prod(out_spatial))
    p = pooled.reshape(N, C, -1)
    i = indices.reshape(N, C, -1).astype(jnp.int32)
    out = jnp.zeros((N, C, flat_out), pooled.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, vv, ii: o.at[ii].set(vv)))(out, p, i)
    return out.reshape((N, C) + tuple(out_spatial))


def _max_unpool_fwd(pooled, indices, out_spatial):
    return _max_unpool_nd(pooled, indices, out_spatial), (indices,)


def _max_unpool_bwd(saved, g, out_spatial=None):
    (indices,) = saved  # indices.shape == pooled.shape (static)
    p_shape = indices.shape
    N, C = p_shape[:2]
    gf = g.reshape(N, C, -1)
    ii = indices.reshape(N, C, -1).astype(jnp.int32)
    dp = jax.vmap(jax.vmap(lambda gg, jj: gg[jj]))(gf, ii)
    return (dp.reshape(p_shape), None)


max_unpool_op = register_op(
    "max_unpool", _max_unpool_nd, fwd=_max_unpool_fwd,
    bwd=_max_unpool_bwd, static_argnames=("out_spatial",))


# -- fractional max pooling --------------------------------------------------

def _fractional_regions(in_size, out_size, u):
    """Pseudo-random region boundaries (reference
    fractional_max_pool: alpha = in/out, b_i = ceil(alpha*(i+u)))."""
    alpha = in_size / out_size
    idx = np.arange(out_size + 1, dtype=np.float64)
    bounds = np.ceil(alpha * (idx + u)).astype(np.int64) - \
        int(np.ceil(alpha * u))
    bounds = np.clip(bounds, 0, in_size)
    bounds[-1] = in_size
    return bounds


def _fractional_max_pool_nd(x, output_size, us):
    spatial = x.shape[2:]
    nd = len(spatial)
    out = x
    for axis in range(nd):
        in_size, out_size = out.shape[2 + axis], output_size[axis]
        bounds = _fractional_regions(in_size, out_size, us[axis])
        cols = []
        for i in range(out_size):
            sl = [slice(None)] * out.ndim
            s, e = int(bounds[i]), max(int(bounds[i + 1]),
                                       int(bounds[i]) + 1)
            sl[2 + axis] = slice(s, min(e, in_size))
            cols.append(jnp.max(out[tuple(sl)], axis=2 + axis,
                                keepdims=True))
        out = jnp.concatenate(cols, axis=2 + axis)
    return out


fractional_max_pool_op = register_op(
    "fractional_max_pool", _fractional_max_pool_nd,
    static_argnames=("output_size", "us"))
