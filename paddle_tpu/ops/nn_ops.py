"""Neural-network compute ops.

Reference kernels: ``paddle/phi/kernels/`` conv/pool/norm/embedding/
softmax/dropout (+ fused attention under ``phi/kernels/fusion/``), exposed
via ``python/paddle/nn/functional/``.  TPU-native: convs and attention map
to ``jax.lax`` convolutions / dot_general so XLA tiles them on the MXU;
norms are written as fusable elementwise chains (XLA fuses the whole
normalize+scale+shift into one kernel); dropout uses the counter-based PRNG.

NHWC vs NCHW: the reference defaults to NCHW.  We accept both and keep the
public default NCHW for API parity, transposing at the boundary — XLA's
layout assignment makes this free inside a jit region.
"""
from __future__ import annotations

import os
import functools as _functools

import numpy as np

import jax
import jax.numpy as jnp

from .registry import apply, register_op
from .random import default_generator


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


# -- convolution ------------------------------------------------------------

def _conv_dtype(x, w):
    """XLA convs reject mixed dtypes; follow the activation stream's
    dtype (bf16-first mixed precision: a fp32 master weight joins a
    bf16 stream as bf16 — the reference amp O2 conv behavior).  Applied
    by every conv variant."""
    if w.dtype != x.dtype:
        w = w.astype(x.dtype)
    return w


def _conv2d_plain(x, w, stride=(1, 1), padding=(0, 0), dilation=(1, 1),
                  groups=1, data_format="NCHW"):
    w = _conv_dtype(x, w)
    if isinstance(padding, str):
        pad = padding
    else:
        pad = [(padding[0], padding[0]), (padding[1], padding[1])]
    nhwc = os.environ.get("PT_CONV_NHWC")
    if data_format == "NCHW" and (nhwc == "1" or (
            nhwc is None and jax.default_backend() == "tpu")):
        # Compute in NHWC — the TPU's native conv layout (+8% measured
        # on the ResNet-50 bench); boundary transposes cancel between
        # layers under XLA.  PT_CONV_NHWC=0 restores direct NCHW.
        dn = jax.lax.conv_dimension_numbers(
            (x.shape[0], x.shape[2], x.shape[3], x.shape[1]),
            (w.shape[2], w.shape[3], w.shape[1], w.shape[0]),
            ("NHWC", "HWIO", "NHWC"))
        out = jax.lax.conv_general_dilated(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(w, (2, 3, 1, 0)),
            window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        return jnp.transpose(out, (0, 3, 1, 2))
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "HWIO", "NHWC"))
    return jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=None)


conv2d_op = register_op(
    "conv2d", _conv2d_plain,
    static_argnames=("stride", "padding", "dilation", "groups",
                     "data_format"))


def conv2d_raw(x, weight, stride=1, padding=0, dilation=1, groups=1,
               data_format="NCHW"):
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = _pair(padding)
    return apply(conv2d_op, x, weight, stride=_pair(stride), padding=pad,
                 dilation=_pair(dilation), groups=int(groups),
                 data_format=data_format)


def _conv1d_plain(x, w, stride=1, padding=0, dilation=1, groups=1):
    w = _conv_dtype(x, w)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCH", "OIH", "NCH"))
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=[(padding, padding)],
        rhs_dilation=(dilation,), dimension_numbers=dn,
        feature_group_count=groups)


conv1d_op = register_op(
    "conv1d", _conv1d_plain,
    static_argnames=("stride", "padding", "dilation", "groups"))


def _conv2d_transpose_plain(x, w, stride=(1, 1), padding=(0, 0),
                            output_padding=(0, 0), dilation=(1, 1), groups=1,
                            data_format="NCHW"):
    w = _conv_dtype(x, w)
    # Transposed conv = lhs-dilated conv with the kernel spatially
    # MIRRORED (the gradient-of-conv identity); without the flip only
    # symmetric kernels came out right (r4 torch-parity fix).  The
    # spatial axes depend on the weight layout: IOHW -> (-2, -1),
    # HWIO -> (0, 1).
    w = jnp.flip(w, axis=(-2, -1) if data_format == "NCHW" else (0, 1))
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "IOHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "HWIO", "NHWC"))
    kh, kw = ((w.shape[2], w.shape[3]) if data_format == "NCHW"
              else (w.shape[0], w.shape[1]))
    pad = [(dilation[0] * (kh - 1) - padding[0],
            dilation[0] * (kh - 1) - padding[0] + output_padding[0]),
           (dilation[1] * (kw - 1) - padding[1],
            dilation[1] * (kw - 1) - padding[1] + output_padding[1])]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad,
        lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)


conv2d_transpose_op = register_op(
    "conv2d_transpose", _conv2d_transpose_plain,
    static_argnames=("stride", "padding", "output_padding", "dilation",
                     "groups", "data_format"))


# -- pooling ----------------------------------------------------------------

def _max_pool2d_plain(x, kernel_size, stride, padding, ceil_mode=False,
                      data_format="NCHW"):
    if data_format == "NCHW":
        window = (1, 1) + kernel_size
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0),
                (padding[0], padding[0]), (padding[1], padding[1]))
    else:
        window = (1,) + kernel_size + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0), (padding[0], padding[0]),
                (padding[1], padding[1]), (0, 0))
    # -inf init is required for jax's reduce_window max transpose rule.
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    return jax.lax.reduce_window(x, neg, jax.lax.max, window, strides, pads)


max_pool2d_op = register_op(
    "max_pool2d", _max_pool2d_plain,
    static_argnames=("kernel_size", "stride", "padding", "ceil_mode",
                     "data_format"))


def _avg_pool2d_plain(x, kernel_size, stride, padding, exclusive=True,
                      data_format="NCHW"):
    if data_format == "NCHW":
        window = (1, 1) + kernel_size
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0),
                (padding[0], padding[0]), (padding[1], padding[1]))
    else:
        window = (1,) + kernel_size + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0), (padding[0], padding[0]),
                (padding[1], padding[1]), (0, 0))
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if exclusive and (padding[0] or padding[1]):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides, pads)
        return summed / counts
    return summed / float(np.prod(kernel_size))


avg_pool2d_op = register_op(
    "avg_pool2d", _avg_pool2d_plain,
    static_argnames=("kernel_size", "stride", "padding", "exclusive",
                     "data_format"))


def _adaptive_avg_pool2d_plain(x, output_size, data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    oh, ow = output_size
    # When evenly divisible this is an exact mean-pool reshape.
    if h % oh == 0 and w % ow == 0:
        out = x.reshape(n, c, oh, h // oh, ow, w // ow).mean(axis=(3, 5))
    else:
        # General case: interval averages.
        hs = (np.arange(oh) * h // oh, ((np.arange(oh) + 1) * h + oh - 1) // oh)
        ws = (np.arange(ow) * w // ow, ((np.arange(ow) + 1) * w + ow - 1) // ow)
        rows = []
        for i in range(oh):
            cols = []
            for j in range(ow):
                cols.append(x[:, :, hs[0][i]:hs[1][i],
                              ws[0][j]:ws[1][j]].mean(axis=(2, 3)))
            rows.append(jnp.stack(cols, axis=-1))
        out = jnp.stack(rows, axis=-2)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


adaptive_avg_pool2d_op = register_op(
    "adaptive_avg_pool2d", _adaptive_avg_pool2d_plain,
    static_argnames=("output_size", "data_format"))


# -- normalization ----------------------------------------------------------

def _layer_norm_plain(x, weight=None, bias=None, epsilon=1e-5,
                      begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim)) \
        if begin_norm_axis != -1 else (x.ndim - 1,)
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    # Cast affine params to x's dtype — mixed-precision norms must not
    # promote the activation stream (see _rms_norm_plain).
    if weight is not None:
        out = out * weight.astype(out.dtype)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


layer_norm_op = register_op(
    "layer_norm", _layer_norm_plain,
    static_argnames=("epsilon", "begin_norm_axis"))


def _rms_norm_plain(x, weight=None, epsilon=1e-6):
    # Reference: phi/kernels/fusion rms_norm; compute in fp32 for stability.
    # The affine weight is cast to x's dtype: a fp32 master weight must NOT
    # promote a bf16 activation stream to fp32 (that silently turns every
    # downstream matmul into a slow fp32 MXU op).
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    out = out.astype(dt)
    if weight is not None:
        out = out * weight.astype(dt)
    return out


rms_norm_op = register_op("rms_norm", _rms_norm_plain,
                          static_argnames=("epsilon",))


def _batch_norm_infer(x, mean, var, weight=None, bias=None, epsilon=1e-5,
                      data_format="NCHW"):
    if data_format == "NCHW" and x.ndim == 4:
        shape = (1, -1, 1, 1)
    elif x.ndim == 2:
        shape = (1, -1)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    # Stats/affine params cast to x's dtype (see _layer_norm_plain): fp32
    # running stats must not promote a bf16 activation stream — that
    # silently turns every downstream conv/matmul into fp32 (and XLA
    # convs hard-reject mixed dtypes).
    dt = x.dtype
    inv = jax.lax.rsqrt(var.astype(jnp.float32).reshape(shape)
                        + epsilon).astype(dt)
    out = (x - mean.astype(dt).reshape(shape)) * inv
    if weight is not None:
        out = out * weight.astype(dt).reshape(shape)
    if bias is not None:
        out = out + bias.astype(dt).reshape(shape)
    return out


batch_norm_infer_op = register_op(
    "batch_norm_infer", _batch_norm_infer,
    static_argnames=("epsilon", "data_format"))


def _batch_norm_stats(x, data_format="NCHW"):
    axes = (0, 2, 3) if (data_format == "NCHW" and x.ndim == 4) else \
        tuple(i for i in range(x.ndim) if i != x.ndim - 1) if x.ndim > 2 \
        else (0,)
    if data_format == "NCHW" and x.ndim == 4:
        axes = (0, 2, 3)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    return mean, var


batch_norm_stats_op = register_op(
    "batch_norm_stats", _batch_norm_stats, n_outputs=2,
    static_argnames=("data_format",))


def _bn_axes_shape(ndim, data_format):
    if ndim == 2:
        return (0,), (1, -1)
    if data_format in ("NCHW", "NCL", "NCDHW"):  # channel-first, any rank
        return (0,) + tuple(range(2, ndim)), \
            (1, -1) + (1,) * (ndim - 2)
    return tuple(range(ndim - 1)), (1,) * (ndim - 1) + (-1,)


def _bn_train_fwd(x, w, b, epsilon=1e-5, data_format="NCHW"):
    """Fused training-mode batch norm (reference batch_norm_kernel.cu
    role).  One fp32 sum/sumsq pass for the stats (E[x²]−E[x]², a
    single multi-output XLA fusion) instead of jnp.mean + jnp.var's
    separate passes — profiled r4: reduction fusions were 52% of the
    ResNet step."""
    axes, shape = _bn_axes_shape(x.ndim, data_format)
    n = 1
    for a in axes:
        n *= x.shape[a]
    xf = x.astype(jnp.float32)
    s = jnp.sum(xf, axis=axes)
    ss = jnp.sum(xf * xf, axis=axes)
    mean = s / n
    var = jnp.maximum(ss / n - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + epsilon)
    dt = x.dtype
    xhat = (x - mean.astype(dt).reshape(shape)) \
        * inv.astype(dt).reshape(shape)
    y = xhat * w.astype(dt).reshape(shape) + b.astype(dt).reshape(shape)
    return (y, mean, var), (x, w, mean, inv)


def _bn_train_bwd(saved, g, epsilon=1e-5, data_format="NCHW"):
    """2-pass BN backward: one fused (Σgy, Σgy·x̂) reduction + one
    elementwise dx pass — replaces autodiff's per-term reductions."""
    x, w, mean, inv = saved
    gy = g[0] if isinstance(g, (tuple, list)) else g
    axes, shape = _bn_axes_shape(x.ndim, data_format)
    n = 1
    for a in axes:
        n *= x.shape[a]
    dt = x.dtype
    xhat = (x - mean.astype(dt).reshape(shape)) \
        * inv.astype(dt).reshape(shape)
    gyf = gy.astype(jnp.float32)
    dbeta = jnp.sum(gyf, axis=axes)
    dgamma = jnp.sum(gyf * xhat.astype(jnp.float32), axis=axes)
    wi = (w.astype(jnp.float32) * inv).astype(dt).reshape(shape)
    dx = wi * (gy
               - (dbeta / n).astype(dt).reshape(shape)
               - xhat * (dgamma / n).astype(dt).reshape(shape))
    return (dx, dgamma.astype(w.dtype), dbeta.astype(w.dtype))


batch_norm_train_op = register_op(
    "batch_norm_train",
    lambda x, w, b, epsilon=1e-5, data_format="NCHW":
    _bn_train_fwd(x, w, b, epsilon, data_format)[0],
    fwd=_bn_train_fwd, bwd=_bn_train_bwd, n_outputs=3,
    static_argnames=("epsilon", "data_format"))


def _group_norm_plain(x, weight=None, bias=None, epsilon=1e-5, groups=32,
                      data_format="NCHW"):
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    xg = x.reshape(n, groups, c // groups, *spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = (1, c) + (1,) * len(spatial)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


group_norm_op = register_op(
    "group_norm", _group_norm_plain,
    static_argnames=("epsilon", "groups", "data_format"))


# -- embedding --------------------------------------------------------------

def _embedding_plain(weight, ids, padding_idx=None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def _embedding_fwd(weight, ids, padding_idx=None):
    return _embedding_plain(weight, ids, padding_idx), (weight, ids)


def _embedding_bwd(saved, g, padding_idx=None):
    weight, ids = saved
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        g = g * mask.astype(g.dtype)
    gw = jnp.zeros(jnp.shape(weight), g.dtype)
    gw = gw.at[ids].add(g)
    return gw.astype(weight.dtype), None


embedding_op = register_op("embedding", _embedding_plain,
                           fwd=_embedding_fwd, bwd=_embedding_bwd,
                           static_argnames=("padding_idx",),
                           nondiff_argnums=(1,))


# -- softmax + cross entropy ------------------------------------------------

def _softmax_fwd(x, axis=-1):
    out = jax.nn.softmax(x, axis=axis)
    return out, out


def _softmax_bwd(out, g, axis=-1):
    inner = jnp.sum(out * g, axis=axis, keepdims=True)
    return (out * (g - inner),)


softmax_op = register_op("softmax",
                         lambda x, axis=-1: jax.nn.softmax(x, axis=axis),
                         fwd=_softmax_fwd, bwd=_softmax_bwd,
                         static_argnames=("axis",))

def _log_softmax_fwd(x, axis=-1):
    out = jax.nn.log_softmax(x, axis=axis)
    return out, out


def _log_softmax_bwd(out, g, axis=-1):
    return (g - jnp.exp(out) * jnp.sum(g, axis=axis, keepdims=True),)


log_softmax_op = register_op("log_softmax",
                             lambda x, axis=-1: jax.nn.log_softmax(
                                 x, axis=axis),
                             fwd=_log_softmax_fwd, bwd=_log_softmax_bwd,
                             static_argnames=("axis",))


def _softmax_ce_plain(logits, label, soft_label=False, ignore_index=-100,
                      axis=-1):
    # log_softmax in fp32: bf16 logits over a large vocab lose the loss
    # signal (reference softmax_with_cross_entropy also accumulates fp32).
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        return -jnp.sum(label * lsm, axis=axis, keepdims=True)
    nll = -jnp.take_along_axis(lsm, label[..., None].astype(jnp.int32),
                               axis=axis)
    if ignore_index is not None:
        mask = (label != ignore_index)[..., None]
        nll = jnp.where(mask, nll, jnp.zeros_like(nll))
    return nll


def _softmax_ce_fwd(logits, label, soft_label=False, ignore_index=-100,
                    axis=-1):
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(label * lsm, axis=axis, keepdims=True)
    else:
        nll = -jnp.take_along_axis(lsm, label[..., None].astype(jnp.int32),
                                   axis=axis)
        if ignore_index is not None:
            mask = (label != ignore_index)[..., None]
            nll = jnp.where(mask, nll, jnp.zeros_like(nll))
        loss = nll
    return loss, (lsm, label)


def _softmax_ce_bwd(saved, g, soft_label=False, ignore_index=-100, axis=-1):
    lsm, label = saved
    sm = jnp.exp(lsm)
    if soft_label:
        glogits = g * (sm * jnp.sum(label, axis=axis, keepdims=True) - label)
        return glogits, None
    oh = jax.nn.one_hot(label, lsm.shape[axis], dtype=lsm.dtype, axis=axis)
    if ignore_index is not None:
        valid = (label != ignore_index)[..., None].astype(lsm.dtype)
    else:
        valid = 1.0
    glogits = g * (sm - oh) * valid
    return glogits, None


softmax_with_cross_entropy_op = register_op(
    "softmax_with_cross_entropy", _softmax_ce_plain,
    fwd=_softmax_ce_fwd, bwd=_softmax_ce_bwd,
    static_argnames=("soft_label", "ignore_index", "axis"),
    nondiff_argnums=(1,))


# -- fused lm-head + cross entropy ------------------------------------------

@_functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(hidden, weight, labels, tied=False,
                               ignore_index=-100):
    """mean CE over ``hidden @ weight`` logits without materializing the
    fp32 log-softmax or a scatter in backward.

    hidden [N, H] (bf16 ok), weight [H, V] (or [V, H] when ``tied`` —
    an embedding table used as the output head), labels [N] int.
    Loss = mean over ALL rows with ignore_index rows contributing 0 —
    matching F.cross_entropy(reduction='mean', ignore_index=-100) on the
    same logits (reference softmax_with_cross_entropy semantics).

    Backward recomputes the logits (checkpoint-style) and forms
    d_logits = (softmax - onehot) directly in the logits dtype — the
    autodiff path through log_softmax+take_along_axis instead materializes
    a [N, V] fp32 tensor twice and a scatter-add, ~3x the HBM traffic at
    V=32k.  Reference parity: fused softmax_with_cross_entropy kernel
    (phi/kernels/gpu/cross_entropy_kernel.cu fused path)."""
    loss, _ = _flce_fwd(hidden, weight, labels, tied, ignore_index)
    return loss


def _flce_logits(hidden, weight, tied):
    if tied:
        return jnp.einsum("nh,vh->nv", hidden, weight)
    return jnp.einsum("nh,hv->nv", hidden, weight)


def _flce_fwd(hidden, weight, labels, tied, ignore_index):
    logits = _flce_logits(hidden, weight, tied)
    lf = logits.astype(jnp.float32)
    mx = jnp.max(lf, axis=-1)
    lse = mx + jnp.log(jnp.sum(jnp.exp(lf - mx[:, None]), axis=-1))
    lab = jnp.clip(labels, 0, logits.shape[-1] - 1).astype(jnp.int32)
    tgt = jnp.take_along_axis(lf, lab[:, None], axis=-1)[:, 0]
    valid = (labels != ignore_index)
    nll = jnp.where(valid, lse - tgt, 0.0)
    loss = jnp.mean(nll)
    return loss, (hidden, weight, labels, lse)


def _flce_bwd(tied, ignore_index, saved, g):
    hidden, weight, labels, lse = saved
    n, v = lse.shape[0], weight.shape[0] if tied else weight.shape[1]
    logits = _flce_logits(hidden, weight, tied)
    lab = jnp.clip(labels, 0, v - 1).astype(jnp.int32)
    valid = (labels != ignore_index)
    # softmax - onehot, scaled by g/N, zeroed on ignored rows; onehot via
    # fused iota compare (no scatter).
    sm = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    oh = (jax.lax.broadcasted_iota(jnp.int32, (n, v), 1) == lab[:, None])
    scale = (g / n)
    dlogits = ((sm - oh.astype(jnp.float32))
               * (valid.astype(jnp.float32) * scale)[:, None]
               ).astype(hidden.dtype)
    if tied:
        dh = jnp.einsum("nv,vh->nh", dlogits, weight)
        dw = jnp.einsum("nv,nh->vh", dlogits, hidden)
    else:
        dh = jnp.einsum("nv,hv->nh", dlogits, weight)
        dw = jnp.einsum("nh,nv->hv", hidden, dlogits)
    return dh.astype(hidden.dtype), dw.astype(weight.dtype), None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)


# -- dropout ----------------------------------------------------------------

def _dropout_fwd_key(x, key, p=0.5, mode="upscale_in_train"):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, jnp.shape(x))
    if mode == "upscale_in_train":
        out = jnp.where(mask, x / keep, jnp.zeros_like(x))
    else:
        out = jnp.where(mask, x, jnp.zeros_like(x))
    return out, mask


_dropout_jit = jax.jit(_dropout_fwd_key, static_argnames=("p", "mode"))


def _dropout_bwd(mask, g, p=0.5, mode="upscale_in_train"):
    keep = 1.0 - p
    if mode == "upscale_in_train":
        return (jnp.where(mask, g / keep, jnp.zeros_like(g)),)
    return (jnp.where(mask, g, jnp.zeros_like(g)),)


class _DropoutOp:
    """Dropout needs a fresh key per call, so it bypasses register_op's
    uniform jit wrapping and draws from the default generator."""

    name = "dropout"
    n_outputs = 1
    jit_bwd = staticmethod(jax.jit(_dropout_bwd,
                                   static_argnames=("p", "mode")))

    @staticmethod
    def fwd(x, p=0.5, mode="upscale_in_train"):
        return _dropout_jit(x, default_generator.next_fast_key(), p=p,
                            mode=mode)


dropout_op = _DropoutOp()


def dropout_raw(x, p=0.5, training=True, mode="upscale_in_train"):
    from ..autograd import engine as _engine
    from ..core.tensor import Tensor

    if not training:
        if mode == "downscale_in_infer" and p > 0.0:
            from . import math as _m

            return _m.scale(x, scale=1.0 - p)
        return x
    if p == 0.0:
        return x
    need_grad = _engine.is_grad_enabled() and not x.stop_gradient
    out_data, mask = dropout_op.fwd(x._data, p=float(p), mode=mode)
    out = Tensor(out_data, stop_gradient=not need_grad)
    if need_grad:
        node = _engine.GradNode(dropout_op, mask, [x],
                                {"p": float(p), "mode": mode})
        node.bind_outputs([out])
    return out


# -- attention --------------------------------------------------------------

def _fa_mod():
    from jax.experimental.pallas.ops.tpu import flash_attention as m

    return m


def _fit_block(block, n, floor=128):
    """Largest power-of-two-ish divisor of ``n`` that is <= ``block``
    (pallas requires seq_len % block == 0)."""
    block = min(block, n)
    while block > floor and n % block != 0:
        block //= 2
    return max(floor, block)


def _fa_block_sizes(q_seq_len, kv_seq_len, blocks=None):
    """Pallas flash-attention tile sizes.  ``blocks`` is a (block_q,
    block_k) pair; the default comes from the autotune cache
    (ops/autotune.py) — seeded with the v5e-measured 512/1024 (bigger q
    tiles than the library's 128 default keep the MXU busier per grid
    step), overridden by any per-shape measurement on record.  Tiles
    are clamped to divisors of the sequence lengths — pallas'
    _verify_block rejects non-dividing tiles (e.g. S=1536 with bk=1024)."""
    m = _fa_mod()
    from . import autotune as _autotune

    bq, bk = blocks if blocks is not None else _autotune.lookup(
        "fa_blocks", (q_seq_len, kv_seq_len), default=(512, 1024))
    bq = _fit_block(bq, q_seq_len)
    bk = _fit_block(bk, kv_seq_len)
    return m.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=_fit_block(512, bk),
        block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_k_dkv=_fit_block(512, bk), block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=_fit_block(512, bk),
        block_q_dq=bq)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_core(q, k, v, causal, scale, blocks):
    m = _fa_mod()
    bs = _fa_block_sizes(q.shape[2], k.shape[2], blocks)
    with jax.enable_x64(False):
        return m._flash_attention_impl(
            q, k, v, None, None, False, causal, scale,
            bs.block_b, bs.block_q, bs.block_k_major, bs.block_k, False)


def _flash_core_fwd(q, k, v, causal, scale, blocks):
    m = _fa_mod()
    bs = _fa_block_sizes(q.shape[2], k.shape[2], blocks)
    with jax.enable_x64(False):
        o, lse, mx = m._flash_attention_impl(
            q, k, v, None, None, True, causal, scale,
            bs.block_b, bs.block_q, bs.block_k_major, bs.block_k, False)
    return o, (q, k, v, o, lse, mx)


def _flash_core_bwd(causal, scale, blocks, res, do):
    m = _fa_mod()
    q, k, v, o, lse, mx = res
    bs = _fa_block_sizes(q.shape[2], k.shape[2], blocks)
    with jax.enable_x64(False):
        di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                     axis=-1)
        dk, dv = m._flash_attention_bwd_dkv(
            q, k, v, None, None, lse, mx, do, di,
            block_q_major=bs.block_q_major_dkv,
            block_k_major=bs.block_k_major_dkv,
            block_k=bs.block_k_dkv, block_q=bs.block_q_dkv,
            sm_scale=scale, causal=causal,
            mask_value=m.DEFAULT_MASK_VALUE, debug=False)
        dq, _ = m._flash_attention_bwd_dq(
            q, k, v, None, None, lse, mx, do, di,
            block_q_major=bs.block_q_dq, block_k_major=bs.block_k_major_dq,
            block_k=bs.block_k_dq, sm_scale=scale, causal=causal,
            mask_value=m.DEFAULT_MASK_VALUE, debug=False)
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _flash_attention_tpu(qt, kt, vt, causal, scale, blocks=None):
    """Pallas TPU flash attention ([B, H, S, D] layout), O(S)-memory.
    Reference parity: phi/kernels/gpu/flash_attn_kernel.h.

    Wraps the stock pallas kernel in our own custom_vjp so that BOTH the
    forward and backward kernel traces run with x64 disabled (the global
    x64 mode from core/dtype.py would make the kernels' weak-typed grid
    index arithmetic int64 and break mosaic lowering), and so the tile
    sizes are tunable (v5e-tuned defaults in _fa_block_sizes)."""
    return _flash_core(qt, kt, vt, bool(causal), float(scale), blocks)


def _sdpa_plain(q, k, v, mask=None, key=None, dropout=0.0, causal=False,
                scale=None, impl="auto", flash_blocks=None):
    """Scaled dot-product attention, [B, S, H, D] layout (paddle flash-attn
    layout, nn/functional/flash_attention.py).  Computed in the MXU-friendly
    [B, H, S, D] internally.  ``key`` enables attention dropout.

    GQA (k/v heads < q heads) is computed by grouped einsum — K/V are
    NEVER materialized at q-head count (the reference flash kernel gets
    this from its head-broadcast support; repeat_interleave would burn
    HBM bandwidth).

    impl: "einsum" = XLA fused softmax-attention; "short" = the
    self-authored VMEM-resident Pallas kernel (TPU, no mask, Sq==Sk,
    S<=1024, S%128==0, D in {64, 128}, no GQA; supports in-kernel
    dropout); "flash" = stock Pallas flash kernel (TPU, no
    mask/dropout, Sq==Sk, D%128==0, S%512==0); "auto" picks short
    where its whole-[S,S]-in-VMEM regime applies, flash for long
    causal sequences (S>=1024), einsum otherwise.  The Pallas paths
    round differently from einsum (bf16 MXU accumulation) and the
    short kernel's dropout mask comes from its in-kernel counter hash,
    not the host key stream.
    """
    B, Sq, H, D = q.shape
    Hkv, Sk = k.shape[2], k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    qt = jnp.swapaxes(q, 1, 2)  # B H S D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    on_tpu = jax.devices()[0].platform == "tpu"
    # Self-authored q-blocked kernel with VMEM-resident K/V
    # (pallas_kernels/long_attention): measured 2.2x the stock flash
    # kernel at the llama bench shape (S=2048 D=128 fwd+bwd 5.0ms vs
    # 11.0ms) — at these S one head's K/V fits VMEM, so flash's
    # K-block pipeline is pure overhead.  Falls back to the stock
    # kernel via impl="flash" (e.g. S too large for resident K/V).
    # S cap 2048: the bwd kernel holds ~4 [block_q, S] f32
    # intermediates; past S=2048 they exceed scoped VMEM (and only
    # S<=2048 is benchmarked) — longer sequences take the stock
    # flash path below.
    long_ok = (mask is None and key is None and Sq == Sk
               and D % 128 == 0 and Sq % 256 == 0 and Sq <= 2048
               and Hkv == H and on_tpu)
    if impl == "auto" and long_ok and causal and Sq >= 1024:
        from . import autotune as _autotune
        from .pallas_kernels.long_attention import long_attention

        block_q = int(_autotune.lookup("long_attention_block_q",
                                       (Sq, D), default=256))
        out = long_attention(qt, kt, vt, float(scale), block_q,
                             bool(causal), None)
        return jnp.swapaxes(out, 1, 2)
    # Self-authored short-sequence kernel (pallas_kernels/short_attention):
    # whole [S,S] scores VMEM-resident, in-kernel counter-hash dropout.
    # Beats einsum whenever one head's scores fit VMEM (S <= 1024) —
    # there the einsum path's HBM round-trips of [B,H,S,S] probs (and
    # dropout masks) dominate (r4 BERT profile).  Causal S == 1024 is
    # preempted by long_attention above.
    short_ok = (mask is None and Sq == Sk and Sq <= 1024
                and Sq % 128 == 0 and D % 64 == 0 and D <= 128
                and Hkv == H and on_tpu)
    use_short = short_ok and (impl == "auto" or impl == "short")
    if impl == "short" and not short_ok:
        raise ValueError(
            "impl='short' requires: TPU, no attn_mask, Sq == Sk <= "
            f"1024, seq % 128 == 0, head_dim % 64 == 0, no GQA; got "
            f"Sq={Sq} Sk={Sk} D={D} H={H} Hkv={Hkv} "
            f"mask={mask is not None}")
    if use_short:
        from .pallas_kernels import short_attention

        if key is not None:
            seed = jax.random.key_data(key).ravel()[-1].astype(jnp.int32)
            p_drop = float(dropout)
        else:
            seed = jnp.zeros((), jnp.int32)
            p_drop = 0.0
        with jax.enable_x64(False):
            out = short_attention(qt, kt, vt, seed, float(scale),
                                  p_drop, bool(causal))
        return jnp.swapaxes(out, 1, 2)

    flash_ok = (mask is None and key is None and Sq == Sk
                and D % 128 == 0 and Sq % 512 == 0
                and on_tpu)
    if impl == "flash" and not flash_ok:
        raise ValueError(
            "impl='flash' requires: TPU backend, no attn_mask, no dropout, "
            f"Sq == Sk, head_dim % 128 == 0, seq % 512 == 0; got "
            f"Sq={Sq} Sk={Sk} D={D} mask={mask is not None} "
            f"dropout={key is not None} "
            f"platform={jax.devices()[0].platform}")
    # stock flash kernel path (impl="flash", or auto shapes the
    # resident-K/V kernel can't take)
    use_flash = impl == "flash" or (impl == "auto" and flash_ok
                                    and causal and Sq >= 1024)
    if use_flash:
        if Hkv != H:
            kt = jnp.repeat(kt, H // Hkv, axis=1)
            vt = jnp.repeat(vt, H // Hkv, axis=1)
        out = _flash_attention_tpu(qt, kt, vt, causal, scale,
                                   blocks=flash_blocks)
        return jnp.swapaxes(out, 1, 2)

    grouped = Hkv != H
    if grouped:
        g = H // Hkv
        qt = qt.reshape(B, Hkv, g, Sq, D)
        logits = jnp.einsum("bngqd,bnkd->bngqk", qt, kt) * scale
    else:
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((Sq, Sk), dtype=bool), Sk - Sq)
        logits = jnp.where(causal_mask, logits,
                           jnp.finfo(logits.dtype).min)
    if mask is not None:
        if grouped and mask.ndim == 4:
            m = (mask.reshape(B, Hkv, H // Hkv, Sq, Sk)
                 if mask.shape[1] == H else mask[:, :, None])
            logits = logits + m
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1) \
        .astype(q.dtype)
    if key is not None and dropout > 0.0:
        keep = jax.random.bernoulli(key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout),
                          jnp.zeros_like(probs))
    if grouped:
        out = jnp.einsum("bngqk,bnkd->bngqd", probs, vt)
        out = out.reshape(B, H, Sq, D)
    else:
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


sdpa_op = register_op(
    "scaled_dot_product_attention", _sdpa_plain,
    static_argnames=("dropout", "causal", "scale", "impl", "flash_blocks"),
    nondiff_argnums=(3, 4))


# -- rope -------------------------------------------------------------------

def _rope_plain(q, k, cos, sin, position_ids=None, neox=True):
    """Rotary embedding on [B, S, H, D]; cos/sin are [S_max, D] tables.

    position_ids [B, S] selects table rows (left-padded / packed
    sequences); neox=True rotates half-split pairs, neox=False rotates
    interleaved even/odd pairs — matching the reference fused_rope's
    use_neox_rotary_style (phi/kernels/fusion fused_rope).
    """
    if position_ids is not None:
        c = cos[position_ids][:, :, None, :]   # [B, S, 1, D]
        s = sin[position_ids][:, :, None, :]
    else:
        S = q.shape[1]
        c = cos[None, :S, None, :]
        s = sin[None, :S, None, :]

    if neox:
        def rot(x):
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([-x2, x1], axis=-1)
    else:
        def rot(x):
            x1 = x[..., 0::2]
            x2 = x[..., 1::2]
            return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)

    return q * c + rot(q) * s, k * c + rot(k) * s


fused_rope_op = register_op("fused_rotary_position_embedding", _rope_plain,
                            n_outputs=2, static_argnames=("neox",),
                            nondiff_argnums=(4,))


# -- interpolate (nearest/bilinear) ----------------------------------------

def _interp_plain(x, size, mode="nearest", align_corners=False,
                  data_format="NCHW"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[mode]
    out = jax.image.resize(x, (x.shape[0], size[0], size[1], x.shape[3]),
                           method=method)
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


interpolate_op = register_op(
    "interpolate", _interp_plain,
    static_argnames=("size", "mode", "align_corners", "data_format"))
