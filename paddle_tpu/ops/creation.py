"""Tensor creation ops.

Reference: ``python/paddle/tensor/creation.py`` (zeros/ones/full/arange/
eye/linspace/tril/triu/empty...).  Creation is cheap on TPU when it stays in
XLA (iota/broadcast fuse into consumers), so everything here is jnp.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor, to_tensor  # noqa: F401


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def _dt(dtype, default=None):
    if dtype is None:
        return default or dtype_mod.get_default_dtype()
    return dtype_mod.convert_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = dtype_mod.get_default_dtype()
        else:
            dtype = dtype_mod.get_default_dtype()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor) else x,
                                 dtype=dtype_mod.convert_dtype(dtype) if dtype else None))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(x._data if isinstance(x, Tensor) else x,
                                dtype=dtype_mod.convert_dtype(dtype) if dtype else None))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(x._data if isinstance(x, Tensor) else x,
                                fill_value,
                                dtype=dtype_mod.convert_dtype(dtype) if dtype else None))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step))
            else dtype_mod.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)),
                               base=_v(base), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns else None,
                          dtype=_dt(dtype)))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    arr = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    n = arr.shape[-1] + abs(offset)
    out = jnp.zeros(arr.shape[:-1] + (n, n), arr.dtype)
    idx = jnp.arange(arr.shape[-1])
    if offset >= 0:
        out = out.at[..., idx, idx + offset].set(arr)
    else:
        out = out.at[..., idx - offset, idx].set(arr)
    if (dim1, dim2) not in ((-2, -1), (out.ndim - 2, out.ndim - 1)):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return Tensor(out)


def assign(x, output=None):
    from .manipulation import assign as _assign

    return _assign(x, output)


def clone(x, name=None):
    return assign(x)


def tril_(x, diagonal=0):
    from .manipulation import tril

    return tril(x, diagonal)


def complex(real, imag, name=None):  # noqa: A001
    r = real._data if isinstance(real, Tensor) else real
    i = imag._data if isinstance(imag, Tensor) else imag
    return Tensor(jax_complex(r, i))


def jax_complex(r, i):
    return r + 1j * i.astype(jnp.result_type(i, jnp.complex64))


def as_complex(x, name=None):
    d = x._data if isinstance(x, Tensor) else x
    return Tensor(d[..., 0] + 1j * d[..., 1])


def as_real(x, name=None):
    d = x._data if isinstance(x, Tensor) else x
    return Tensor(jnp.stack([jnp.real(d), jnp.imag(d)], axis=-1))
