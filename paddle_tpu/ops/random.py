"""Random ops + global generator.

Reference: ``phi/core/generator.h`` (Generator with seed/offset state) and
``python/paddle/tensor/random.py``.  TPU-native: a stateful facade over jax
counter-based PRNG — ``paddle.seed`` resets the key; every sampling op
splits the key, so eager sampling is reproducible, and the distributed RNG
tracker (fleet/layers/mpu/random.py analog) can fork deterministic
per-mesh-axis streams.
"""
from __future__ import annotations

import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor


class Generator:
    """Stateful RNG facade over jax.random keys."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        # Key creation is deferred to first use: materializing it here
        # would initialize the jax backend at `import paddle_tpu` time,
        # breaking multi-host jobs that must call
        # jax.distributed.initialize first (env.init_parallel_env).
        self._seed = int(seed)
        self._key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    seed = initial_seed

    def _ensure_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
            self._fast_key = None

    def next_fast_key(self):
        """Key for mask-class randomness (dropout): on TPU uses the
        ``rbg`` generator (hardware PRNG; measured ~2x cheaper per
        [B,H,S,S] mask than threefry, which is generated THREE times
        per mask under remat).  Statistical quality is ample for
        dropout; user-facing sampling keeps the threefry stream, so
        paddle.seed reproducibility of tensors is unchanged."""
        with self._lock:
            self._ensure_key()
            if getattr(self, "_fast_key", None) is None:
                # concrete even when first touched inside a jit trace
                with jax.ensure_compile_time_eval():
                    try:
                        self._fast_key = jax.random.key(self._seed,
                                                        impl="rbg")
                    except Exception:  # backend without rbg support
                        self._fast_key = jax.random.key(self._seed)
            new_key, sub = jax.random.split(self._fast_key)
            if isinstance(new_key, jax.core.Tracer):
                with jax.ensure_compile_time_eval():
                    new_key, sub = jax.random.split(self._fast_key)
                if isinstance(new_key, jax.core.Tracer):
                    return jax.random.fold_in(self._fast_key, 0)
            self._fast_key = new_key
            return sub

    def next_key(self):
        with self._lock:
            self._ensure_key()
            new_key, sub = jax.random.split(self._key)
            if isinstance(new_key, jax.core.Tracer):
                # Under a jit trace, omnistaging stages the split and a
                # TRACER would be written back as generator state —
                # poisoning every later trace in the process
                # (UnexpectedTracerError on key<fry>).  Advance the
                # concrete state at trace time instead; the subkey is
                # baked into the trace as a constant (sampling inside a
                # compiled step is deterministic per compilation —
                # thread explicit keys for per-step variation).
                with jax.ensure_compile_time_eval():
                    new_key, sub = jax.random.split(self._key)
                if isinstance(new_key, jax.core.Tracer):
                    return sub  # give up on advancing; never store it
            self._key = new_key
            return sub

    def get_state(self):
        with self._lock:
            self._ensure_key()
            return jax.random.key_data(self._key)

    def set_state(self, state):
        # Same lock as next_key: an unlocked write here could be
        # overwritten by a concurrent next_key's split-writeback,
        # silently discarding the restored stream.  NB initial_seed()
        # keeps reporting the creation seed (the reference Generator's
        # seed/offset state behaves the same after SetState).
        with self._lock:
            self._key = jax.random.wrap_key_data(jnp.asarray(state))


default_generator = Generator(0)


def seed(value: int):
    """paddle.seed"""
    default_generator.manual_seed(int(value))
    return default_generator


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(state):
    default_generator.set_state(state[0] if isinstance(state, (list, tuple))
                                else state)


def _dt(dtype):
    if dtype is None:
        return dtype_mod.get_default_dtype()
    return dtype_mod.convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


_jit_normal = jax.jit(jax.random.normal, static_argnames=("shape", "dtype"))
_jit_uniform = jax.jit(jax.random.uniform,
                       static_argnames=("shape", "dtype"))
_jit_randint = jax.jit(jax.random.randint,
                       static_argnames=("shape", "dtype"))
_jit_bernoulli = jax.jit(lambda key, p: jax.random.bernoulli(key, p))


def randn(shape, dtype=None, name=None):
    return Tensor(_jit_normal(default_generator.next_key(), _shape(shape),
                              _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        z = _jit_normal(default_generator.next_key(), sh,
                        dtype_mod.get_default_dtype())
        return Tensor(m + s * z)
    z = randn(shape if shape is not None else [1])
    return Tensor(mean + std * z._data)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    z = _jit_normal(default_generator.next_key(), _shape(shape), _dt(dtype))
    return Tensor(mean + std * z)


def rand(shape, dtype=None, name=None):
    return Tensor(_jit_uniform(default_generator.next_key(), _shape(shape),
                               _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    u = _jit_uniform(default_generator.next_key(), _shape(shape), _dt(dtype))
    return Tensor(u * (max - min) + min)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = dtype_mod.convert_dtype(dtype) if dtype else jnp.dtype("int64")
    return Tensor(_jit_randint(default_generator.next_key(), _shape(shape),
                               int(low), int(high), d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or str(x.dtype))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(default_generator.next_key(),
                                         int(n)).astype(_dt(dtype)))


def shuffle(x, axis=0):
    d = x._data if isinstance(x, Tensor) else x
    return Tensor(jax.random.permutation(default_generator.next_key(), d,
                                         axis=axis, independent=False))


def bernoulli(x, name=None):
    p = x._data if isinstance(x, Tensor) else x
    return Tensor(_jit_bernoulli(default_generator.next_key(), p)
                  .astype(p.dtype))


def poisson(x, name=None):
    from ..ops import infermeta

    lam = x._data if isinstance(x, Tensor) else x
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("poisson", (lam,), {})
    return Tensor(jax.random.poisson(default_generator.next_key(), lam)
                  .astype(lam.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    from ..ops import infermeta

    p = x._data if isinstance(x, Tensor) else x
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("multinomial", (p,),
                       {"num_samples": int(num_samples),
                        "replacement": bool(replacement)})
    key = default_generator.next_key()
    if replacement:
        idx = jax.random.categorical(
            key, jnp.log(jnp.maximum(p, 1e-30)),
            shape=p.shape[:-1] + (num_samples,))
    else:
        # Gumbel top-k trick for sampling without replacement.
        g = jax.random.gumbel(key, p.shape)
        scores = jnp.log(jnp.maximum(p, 1e-30)) + g
        _, idx = jax.lax.top_k(scores, num_samples)
    return Tensor(idx.astype(jnp.int64))


def exponential_(x, lam=1.0, name=None):
    from ..ops import infermeta

    # in-place host path, so it never passes registry.apply's hook
    infermeta.validate("exponential_", (x._data,), {"lam": lam})
    u = jax.random.exponential(default_generator.next_key(),
                               jnp.shape(x._data)) / lam
    x.set_value(u.astype(x.dtype))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    z = _jit_normal(default_generator.next_key(), tuple(x.shape), x.dtype)
    x.set_value(mean + std * z)
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    u = _jit_uniform(default_generator.next_key(), tuple(x.shape), x.dtype)
    x.set_value(u * (max - min) + min)
    return x


def bernoulli_(x, p=0.5, name=None):
    """reference tensor/random.bernoulli_: in-place bernoulli fill."""
    u = jax.random.bernoulli(default_generator.next_key(), p,
                             jnp.shape(x._data))
    x.set_value(u.astype(x.dtype))
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    """reference tensor/random.cauchy_."""
    import math as _m

    from . import infermeta

    infermeta.validate("cauchy_", (x._data,),
                       {"loc": loc, "scale": scale})
    u = jax.random.uniform(default_generator.next_key(),
                           jnp.shape(x._data), jnp.float32,
                           1e-7, 1.0 - 1e-7)
    x.set_value((loc + scale * jnp.tan(_m.pi * (u - 0.5)))
                .astype(x.dtype))
    return x


def geometric_(x, probs, name=None):
    """reference tensor/random.geometric_ (counts trials, support
    1, 2, ...)."""
    from . import infermeta

    infermeta.validate("geometric_", (x._data,), {"probs": probs})
    u = jax.random.uniform(default_generator.next_key(),
                           jnp.shape(x._data), jnp.float32,
                           1e-7, 1.0 - 1e-7)
    x.set_value(jnp.ceil(jnp.log(u) / jnp.log1p(-probs))
                .astype(x.dtype))
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """reference tensor/random.log_normal_."""
    from ..ops import infermeta

    # in-place host path, so it never passes registry.apply's hook
    infermeta.validate("log_normal_", (x._data,),
                       {"mean": mean, "std": std})
    z = _jit_normal(default_generator.next_key(), tuple(x.shape),
                    jnp.float32)
    x.set_value(jnp.exp(mean + std * z).astype(x.dtype))
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """reference tensor/random.log_normal."""
    from ..core.tensor import Tensor

    if shape is None:
        shape = getattr(mean, "shape", ())
    z = _jit_normal(default_generator.next_key(),
                    tuple(int(d) for d in shape), jnp.float32)
    m = mean._data if hasattr(mean, "_data") else mean
    s = std._data if hasattr(std, "_data") else std
    return Tensor(jnp.exp(m + s * z))


def standard_gamma(alpha, name=None):
    """reference tensor/random.standard_gamma."""
    from ..core.tensor import Tensor

    a = alpha._data if hasattr(alpha, "_data") else jnp.asarray(alpha)
    out = jax.random.gamma(default_generator.next_key(), a)
    return Tensor(out)


def binomial(count, prob, name=None):
    """reference tensor/random.binomial (elementwise draws)."""
    from ..core.tensor import Tensor

    from ..ops import infermeta

    n = count._data if hasattr(count, "_data") else jnp.asarray(count)
    p = prob._data if hasattr(prob, "_data") else jnp.asarray(prob)
    # host path, so it never passes registry.apply's validator hook
    infermeta.validate("binomial", (n, p), {})
    # jax's binomial kernel compares against float literals of the
    # DEFAULT float dtype: forcing float32 operands under x64 trips a
    # lax.clamp dtype mismatch inside it
    dt = jnp.result_type(float)
    out = jax.random.binomial(default_generator.next_key(),
                              n.astype(dt), p.astype(dt))
    return Tensor(out.astype(jnp.int64))
