"""Elementwise / scalar math ops with hand-written backward pairings.

Reference op surface: ``python/paddle/tensor/math.py`` + kernel pairings in
``paddle/phi/ops/yaml/ops.yaml`` / ``backward.yaml`` (e.g. ``- op : add``
paired with ``add_grad``).  Each hot op here registers an explicit
(fwd, bwd) pair so eager dispatch stays on jitted, XLA-cached executables;
broadcasting grads reduce over the broadcast axes exactly like the
reference's ``ElementwiseGradKernel`` (phi/kernels/funcs/elementwise_base.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import apply, register_op


def unbroadcast(g, shape):
    """Sum ``g`` down to ``shape`` (reverse of numpy broadcasting)."""
    shape = tuple(shape)
    if g.shape == shape:
        return g
    # Sum leading extra dims.
    extra = g.ndim - len(shape)
    if extra > 0:
        g = jnp.sum(g, axis=tuple(range(extra)))
    # Sum dims that were 1 in the original shape.
    axes = tuple(i for i, s in enumerate(shape) if s == 1 and g.shape[i] != 1)
    if axes:
        g = jnp.sum(g, axis=axes, keepdims=True)
    return g.astype(jnp.result_type(g)) if g.shape == shape else jnp.reshape(g, shape)


def _shape_of(x):
    return jnp.shape(x)


# -- binary ops -------------------------------------------------------------

def _binary(name, fn, bwd):
    def fwd(x, y):
        return fn(x, y), (x, y)

    op = register_op(name, fn, fwd=fwd, bwd=bwd)

    def api(x, y, name=None):
        return apply(op, x, y)

    api.__name__ = name
    return api, op


def _add_bwd(saved, g):
    x, y = saved
    return unbroadcast(g, jnp.shape(x)), unbroadcast(g, jnp.shape(y))


def _sub_bwd(saved, g):
    x, y = saved
    return unbroadcast(g, jnp.shape(x)), unbroadcast(-g, jnp.shape(y))


def _mul_bwd(saved, g):
    x, y = saved
    return unbroadcast(g * y, jnp.shape(x)), unbroadcast(g * x, jnp.shape(y))


def _div_bwd(saved, g):
    x, y = saved
    gx = unbroadcast(g / y, jnp.shape(x))
    gy = unbroadcast(-g * x / (y * y), jnp.shape(y))
    return gx, gy


def _pow_bwd(saved, g):
    x, y = saved
    gx = unbroadcast(g * y * jnp.power(x, y - 1), jnp.shape(x))
    safe_x = jnp.where(x > 0, x, jnp.ones_like(x))
    gy = unbroadcast(g * jnp.power(x, y) * jnp.log(safe_x), jnp.shape(y))
    return gx, gy


def _max_bwd(saved, g):
    x, y = saved
    mask = (x >= y).astype(g.dtype)
    return (unbroadcast(g * mask, jnp.shape(x)),
            unbroadcast(g * (1 - mask), jnp.shape(y)))


def _min_bwd(saved, g):
    x, y = saved
    mask = (x <= y).astype(g.dtype)
    return (unbroadcast(g * mask, jnp.shape(x)),
            unbroadcast(g * (1 - mask), jnp.shape(y)))


add, add_op = _binary("add", jnp.add, _add_bwd)
subtract, subtract_op = _binary("subtract", jnp.subtract, _sub_bwd)
multiply, multiply_op = _binary("multiply", jnp.multiply, _mul_bwd)
divide, divide_op = _binary("divide", jnp.true_divide, _div_bwd)
pow_, pow_op = _binary("elementwise_pow", jnp.power, _pow_bwd)
maximum, maximum_op = _binary("maximum", jnp.maximum, _max_bwd)
minimum, minimum_op = _binary("minimum", jnp.minimum, _min_bwd)


def pow(x, y, name=None):  # noqa: A001 - paddle API name
    return apply(pow_op, x, y)


def _nodiff_binary(name, fn):
    op = register_op(name, fn)

    def api(x, y, name=None):
        return apply(op, x, y)

    api.__name__ = name
    return api


remainder = _nodiff_binary("remainder", jnp.remainder)
mod = remainder
floor_divide = _nodiff_binary("floor_divide", jnp.floor_divide)
floor_mod = remainder
fmax = _nodiff_binary("fmax", jnp.fmax)
fmin = _nodiff_binary("fmin", jnp.fmin)
logaddexp = _nodiff_binary("logaddexp", jnp.logaddexp)
atan2 = _nodiff_binary("atan2", jnp.arctan2)
gcd = _nodiff_binary("gcd", jnp.gcd)
lcm = _nodiff_binary("lcm", jnp.lcm)
bitwise_and = _nodiff_binary("bitwise_and", jnp.bitwise_and)
bitwise_or = _nodiff_binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = _nodiff_binary("bitwise_xor", jnp.bitwise_xor)
left_shift = _nodiff_binary("left_shift", jnp.left_shift)
right_shift = _nodiff_binary("right_shift", jnp.right_shift)


# -- unary ops --------------------------------------------------------------

def _unary(name, fn, grad_fn=None, save_out=False):
    """grad_fn(saved, g) where saved is input x (or output if save_out)."""
    if grad_fn is None:
        op = register_op(name, fn)
    else:
        def fwd(x):
            out = fn(x)
            return out, (out if save_out else x)

        def bwd(saved, g):
            return (grad_fn(saved, g),)

        op = register_op(name, fn, fwd=fwd, bwd=bwd)

    def api(x, name=None):
        return apply(op, x)

    api.__name__ = name
    return api


exp = _unary("exp", jnp.exp, lambda out, g: g * out, save_out=True)
expm1 = _unary("expm1", jnp.expm1, lambda x, g: g * jnp.exp(x))
log = _unary("log", jnp.log, lambda x, g: g / x)
log2 = _unary("log2", jnp.log2, lambda x, g: g / (x * jnp.log(2.0).astype(x.dtype)))
log10 = _unary("log10", jnp.log10,
               lambda x, g: g / (x * jnp.log(10.0).astype(x.dtype)))
log1p = _unary("log1p", jnp.log1p, lambda x, g: g / (1 + x))
sqrt = _unary("sqrt", jnp.sqrt, lambda out, g: g * 0.5 / out, save_out=True)
rsqrt = _unary("rsqrt", jax.lax.rsqrt,
               lambda x, g: g * (-0.5) * jax.lax.rsqrt(x) / x)
square = _unary("square", jnp.square, lambda x, g: g * 2 * x)
abs = _unary("abs", jnp.abs, lambda x, g: g * jnp.sign(x))  # noqa: A001
neg = _unary("neg", jnp.negative, lambda x, g: -g)
negative = neg
sign = _unary("sign", jnp.sign)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round_ = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x), lambda x, g: g)
reciprocal = _unary("reciprocal", jnp.reciprocal,
                    lambda x, g: -g / jnp.square(x))
sin = _unary("sin", jnp.sin, lambda x, g: g * jnp.cos(x))
cos = _unary("cos", jnp.cos, lambda x, g: -g * jnp.sin(x))
tan = _unary("tan", jnp.tan, lambda x, g: g / jnp.square(jnp.cos(x)))
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh, lambda x, g: g * jnp.cosh(x))
cosh = _unary("cosh", jnp.cosh, lambda x, g: g * jnp.sinh(x))
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf,
             lambda x, g: g * (2.0 / jnp.sqrt(jnp.pi)).astype(x.dtype)
             * jnp.exp(-jnp.square(x)))
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
bitwise_not = _unary("bitwise_not", jnp.bitwise_not)
isnan_ = _unary("isnan", jnp.isnan)
isinf_ = _unary("isinf", jnp.isinf)
isfinite_ = _unary("isfinite", jnp.isfinite)
logical_not = _unary("logical_not", jnp.logical_not)
i0 = _unary("i0", jax.scipy.special.i0)
rint = _unary("rint", jnp.rint)


def _logical_binary(name, fn):
    op = register_op(name, fn)

    def api(x, y, out=None, name=None):
        return apply(op, x, y)

    api.__name__ = name
    return api


logical_and = _logical_binary("logical_and", jnp.logical_and)
logical_or = _logical_binary("logical_or", jnp.logical_or)
logical_xor = _logical_binary("logical_xor", jnp.logical_xor)
equal = _logical_binary("equal", lambda x, y: jnp.equal(x, y))
not_equal = _logical_binary("not_equal", jnp.not_equal)
greater_than = _logical_binary("greater_than", jnp.greater)
greater_equal = _logical_binary("greater_equal", jnp.greater_equal)
less_than = _logical_binary("less_than", jnp.less)
less_equal = _logical_binary("less_equal", jnp.less_equal)


# -- clip / scale / lerp ----------------------------------------------------

def _clip_fwd(x, min=None, max=None):
    return jnp.clip(x, min, max), x


def _clip_bwd(x, g, min=None, max=None):
    mask = jnp.ones_like(x, dtype=bool)
    if min is not None:
        mask &= x >= min
    if max is not None:
        mask &= x <= max
    return (g * mask.astype(g.dtype),)


clip_op = register_op("clip", lambda x, min=None, max=None: jnp.clip(x, min, max),
                      fwd=_clip_fwd, bwd=_clip_bwd,
                      static_argnames=("min", "max"))


def clip(x, min=None, max=None, name=None):
    min = float(min) if min is not None and not hasattr(min, "ndim") else min
    max = float(max) if max is not None and not hasattr(max, "ndim") else max
    from ..core.tensor import Tensor

    if isinstance(min, Tensor):
        min = float(min.item())
    if isinstance(max, Tensor):
        max = float(max.item())
    return apply(clip_op, x, min=min, max=max)


def _scale_fn(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


scale_op = register_op(
    "scale", _scale_fn,
    fwd=lambda x, scale=1.0, bias=0.0, bias_after_scale=True: (
        _scale_fn(x, scale, bias, bias_after_scale), None),
    bwd=lambda saved, g, scale=1.0, bias=0.0, bias_after_scale=True: (
        g * scale,),
    static_argnames=("scale", "bias", "bias_after_scale"))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    from ..core.tensor import Tensor

    if isinstance(scale, Tensor):
        scale = float(scale.item())
    return apply(scale_op, x, scale=float(scale), bias=float(bias),
                 bias_after_scale=bool(bias_after_scale))


lerp_op = register_op(
    "lerp", lambda x, y, w: x + w * (y - x),
    fwd=lambda x, y, w: (x + w * (y - x), (x, y, w)),
    bwd=lambda saved, g: (
        unbroadcast(g * (1 - saved[2]), jnp.shape(saved[0])),
        unbroadcast(g * saved[2], jnp.shape(saved[1])),
        unbroadcast(g * (saved[1] - saved[0]), jnp.shape(saved[2]))))


def lerp(x, y, weight, name=None):
    return apply(lerp_op, x, y, weight)


stanh_op = register_op(
    "stanh",
    lambda x, scale_a=0.67, scale_b=1.7159: scale_b * jnp.tanh(x * scale_a),
    static_argnames=("scale_a", "scale_b"))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(stanh_op, x, scale_a=scale_a, scale_b=scale_b)


_nan_to_num_op = register_op(
    "nan_to_num",
    lambda x, nan=0.0, posinf=None, neginf=None: jnp.nan_to_num(
        x, nan=nan, posinf=posinf, neginf=neginf),
    static_argnames=("nan", "posinf", "neginf"))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(_nan_to_num_op, x, nan=nan, posinf=posinf, neginf=neginf)
