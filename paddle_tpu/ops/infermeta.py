"""Eager argument validation — the InferMeta layer.

Reference: ``paddle/phi/infermeta/`` (binary.cc MatmulInferMeta,
multiary.cc ConcatInferMeta, unary.cc ReshapeInferMeta, ...) — there,
every op validates shapes/dtypes BEFORE the kernel runs and raises
``InvalidArgument`` with an actionable message.  Without this layer a bad
call surfaces as a jnp broadcasting error deep inside dispatch.

TPU-native: validators run on the *metadata only* (shapes/dtypes — no
device work, no tracing interaction) for the high-traffic ops where
jnp's own message is worst.  Registered per op name; ``registry.apply``
consults the table when eager (tracers skip: XLA's shape checks own the
traced path, and validators must never force a concrete value).
"""
from __future__ import annotations

import numpy as np

from ..core.enforce import InvalidArgumentError

_VALIDATORS: dict = {}


def register_validator(name):
    def deco(fn):
        _VALIDATORS[name] = fn
        return fn

    return deco


def validate(op_name, datas, attrs):
    """Called from registry.apply (eager only).  ``datas`` are raw
    arrays/scalars — validators read only .shape/.dtype/.ndim."""
    fn = _VALIDATORS.get(op_name)
    if fn is not None:
        fn(datas, attrs)


def _shape(x):
    return tuple(getattr(x, "shape", ()))


def _ndim(x):
    return len(_shape(x))


def _fail(op, msg):
    raise InvalidArgumentError(
        f"(InvalidArgument) {msg} [operator < {op} > error]")


@register_validator("matmul")
def _matmul(datas, attrs):
    x, y = datas[0], datas[1]
    xs, ys = _shape(x), _shape(y)
    if not xs or not ys:
        _fail("matmul", f"matmul inputs must have rank >= 1, got "
                        f"x{list(xs)} @ y{list(ys)}")
    tx = bool(attrs.get("transpose_x", False))
    ty = bool(attrs.get("transpose_y", False))
    kx = xs[-2] if (tx and len(xs) > 1) else xs[-1]
    ky = (ys[-1] if ty else ys[-2]) if len(ys) > 1 else ys[0]
    if kx != ky:
        _fail("matmul",
              f"Input X's width should be equal to Y's height, but "
              f"received X'shape: {list(xs)}, Y'shape: {list(ys)} "
              f"(contracted dims {kx} vs {ky}, transpose_x={tx}, "
              f"transpose_y={ty})")
    # batch 13: MatmulInferMeta also broadcasts the batch dims (every
    # dim left of the matrix dims) — a mismatch otherwise surfaces as
    # a jnp dot_general error deep inside dispatch
    try:
        np.broadcast_shapes(xs[:-2], ys[:-2])
    except ValueError:
        _fail("matmul",
              f"The batch dimensions of Input(X) {list(xs)} and "
              f"Input(Y) {list(ys)} are not broadcast-compatible")


@register_validator("concat")
def _concat(datas, attrs):
    axis = int(attrs.get("axis", 0))
    shapes = [_shape(d) for d in datas]
    if not shapes:
        _fail("concat", "concat expects at least one input")
    base = shapes[0]
    nd = len(base)
    ax = axis + nd if axis < 0 else axis
    if not 0 <= ax < nd:
        _fail("concat", f"axis {axis} out of range for rank {nd}")
    for i, s in enumerate(shapes[1:], 1):
        if len(s) != nd:
            _fail("concat",
                  f"all inputs must share rank; input 0 has rank {nd}, "
                  f"input {i} has rank {len(s)}")
        for d in range(nd):
            if d != ax and s[d] != base[d]:
                _fail("concat",
                      f"The shape of input[0] and input[{i}] is "
                      f"expected to be equal except on axis {ax}, but "
                      f"received input[0]: {list(base)} vs input[{i}]: "
                      f"{list(s)}")


@register_validator("reshape")
def _reshape(datas, attrs):
    x = datas[0]
    shape = attrs.get("shape")
    if shape is None:
        return
    n = int(np.prod(_shape(x))) if _shape(x) else 1
    known = 1
    minus1 = 0
    for s in shape:
        if s == -1:
            minus1 += 1
        elif s == 0:
            continue  # reference: 0 copies the input dim
        else:
            known *= int(s)
    if minus1 > 1:
        _fail("reshape", f"only one dim may be -1, got shape {shape}")
    if minus1 == 0 and known != n and 0 not in shape:
        _fail("reshape",
              f"the number of elements ({n}) is not equal to the "
              f"target shape {list(shape)} ({known} elements)")
    if minus1 == 1 and known and n % known != 0:
        _fail("reshape",
              f"cannot infer -1: {n} elements not divisible by "
              f"{known} (target shape {list(shape)})")


@register_validator("conv2d")
def _conv2d(datas, attrs):
    x, w = datas[0], datas[1]
    xs, ws = _shape(x), _shape(w)
    if len(xs) != 4 or len(ws) != 4:
        _fail("conv2d",
              f"conv2d expects 4-D input and filter, got input "
              f"{list(xs)}, filter {list(ws)}")
    groups = int(attrs.get("groups", 1))
    fmt = attrs.get("data_format", "NCHW")
    in_ch = xs[1] if fmt == "NCHW" else xs[-1]
    if in_ch != ws[1] * groups:
        _fail("conv2d",
              f"The number of input's channels should be equal to "
              f"filter's channels * groups, but received input "
              f"channels {in_ch}, filter shape {list(ws)}, groups "
              f"{groups}")
    if ws[0] % groups != 0:
        _fail("conv2d",
              f"output channels {ws[0]} must be divisible by groups "
              f"{groups}")


@register_validator("embedding")
def _embedding(datas, attrs):
    # arg order matches the embedding op's signature — the call site
    # (nn/functional/__init__.py embedding) passes (weight, ids)
    table, ids = datas[0], datas[1]
    if _ndim(table) != 2:
        _fail("embedding",
              f"the weight must be 2-D [vocab, dim], got "
              f"{list(_shape(table))}")
    dt = getattr(ids, "dtype", None)
    if dt is not None and not np.issubdtype(np.dtype(str(dt)),
                                            np.integer):
        _fail("embedding",
              f"the input ids must be an integer dtype, got {dt}")


def _linear(datas, attrs):  # F.linear rides matmul; kept for custom use
    x, w = datas[0], datas[1]
    xs, ws = _shape(x), _shape(w)
    if len(ws) != 2:
        _fail("linear", f"weight must be 2-D [in, out], got {list(ws)}")
    if xs and xs[-1] != ws[0]:
        _fail("linear",
              f"Input's last dim ({xs[-1]}) should equal weight's "
              f"first dim ({ws[0]}); input {list(xs)}, weight "
              f"{list(ws)}")


@register_validator("where")
def _where(datas, attrs):
    if len(datas) < 3:
        return
    c, x, y = datas[0], datas[1], datas[2]
    try:
        np.broadcast_shapes(_shape(c), _shape(x), _shape(y))
    except ValueError:
        _fail("where",
              f"condition/x/y are not broadcast-compatible: "
              f"{list(_shape(c))}, {list(_shape(x))}, "
              f"{list(_shape(y))}")


@register_validator("softmax_with_cross_entropy")
def _cross_entropy(datas, attrs):
    logits, label = datas[0], datas[1]
    ls, ys = _shape(logits), _shape(label)
    if not ls:
        _fail("softmax_with_cross_entropy",
              "logits must be at least 1-D")
    if attrs.get("soft_label"):
        if ls != ys:
            _fail("cross_entropy",
                  f"soft labels must match logits shape {list(ls)}, "
                  f"got {list(ys)}")
        return
    if len(ys) == len(ls) and ys[-1] not in (1, ls[-1]):
        _fail("cross_entropy",
              f"hard label's last dim must be 1, got label "
              f"{list(ys)} for logits {list(ls)}")


def _int_dtype(x):
    dt = getattr(x, "dtype", None)
    return dt is None or np.issubdtype(np.dtype(str(dt)), np.integer)


def _axis_in(op, axis, nd, extra=0):
    """Normalize ``axis`` against rank ``nd`` (+``extra`` for ops that
    insert dims); fail with the reference-style message if out of range."""
    lo, hi = -(nd + extra), nd + extra
    if not lo <= axis < hi:
        _fail(op,
              f"The axis is expected to be in range of [{lo}, {hi}), "
              f"but got {axis}")
    return axis % hi if axis < 0 else axis


@register_validator("stack")
def _stack(datas, attrs):
    shapes = [_shape(d) for d in datas]
    if not shapes:
        _fail("stack", "stack expects at least one input")
    base = shapes[0]
    for i, s in enumerate(shapes[1:], 1):
        if s != base:
            _fail("stack",
                  f"inputs to stack must all have the same shape; "
                  f"input[0]: {list(base)} vs input[{i}]: {list(s)}")
    _axis_in("stack", int(attrs.get("axis", 0)), len(base), extra=1)


@register_validator("gather")
def _gather(datas, attrs):
    x, index = datas[0], datas[1]
    if not _int_dtype(index):
        _fail("gather",
              f"the index must be an integer dtype, got "
              f"{getattr(index, 'dtype', None)}")
    if _ndim(index) > 1:
        _fail("gather",
              f"the index should be a 0-D or 1-D tensor, got rank "
              f"{_ndim(index)}")
    _axis_in("gather", int(attrs.get("axis", 0)), max(_ndim(x), 1))


@register_validator("scatter")
def _scatter(datas, attrs):
    x, index, updates = datas[0], datas[1], datas[2]
    if not _int_dtype(index):
        _fail("scatter",
              f"the index must be an integer dtype, got "
              f"{getattr(index, 'dtype', None)}")
    xs, us = _shape(x), _shape(updates)
    if _ndim(index) == 1 and len(us) == len(xs) and len(xs) >= 1:
        if us[0] != _shape(index)[0]:
            _fail("scatter",
                  f"updates' first dim should equal index length "
                  f"({_shape(index)[0]}), but received updates "
                  f"{list(us)}")
        if us[1:] != xs[1:]:
            _fail("scatter",
                  f"updates' trailing dims should match input's "
                  f"({list(xs[1:])}), but received updates {list(us)}")


@register_validator("take_along_axis")
def _take_along_axis(datas, attrs):
    x, index = datas[0], datas[1]
    if not _int_dtype(index):
        _fail("take_along_axis",
              f"the indices must be an integer dtype, got "
              f"{getattr(index, 'dtype', None)}")
    if _ndim(index) != _ndim(x):
        _fail("take_along_axis",
              f"indices rank ({_ndim(index)}) must equal input rank "
              f"({_ndim(x)}); input {list(_shape(x))}, indices "
              f"{list(_shape(index))}")
    _axis_in("take_along_axis", int(attrs.get("axis", 0)),
             max(_ndim(x), 1))


@register_validator("squeeze")
def _squeeze(datas, attrs):
    x = datas[0]
    axis = attrs.get("axis")
    if axis is None:
        return
    nd = _ndim(x)
    for a in (axis if isinstance(axis, (list, tuple)) else (axis,)):
        _axis_in("squeeze", int(a), nd)


@register_validator("unsqueeze")
def _unsqueeze(datas, attrs):
    x = datas[0]
    axis = attrs.get("axis")
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    # rank grows by one per inserted dim; each axis addresses the
    # already-expanded rank (jnp.expand_dims semantics).
    nd = _ndim(x) + len(axes) - 1
    for a in axes:
        _axis_in("unsqueeze", int(a), nd, extra=1)


@register_validator("tile")
def _tile(datas, attrs):
    rt = attrs.get("repeat_times", ())
    for r in rt:
        if int(r) <= 0:
            _fail("tile",
                  f"every element of repeat_times must be a positive "
                  f"integer, got {list(rt)}")


@register_validator("pad")
def _pad(datas, attrs):
    pw = attrs.get("pad_width", ())
    for pair in pw:
        lo, hi = pair
        if int(lo) < 0 or int(hi) < 0:
            _fail("pad",
                  f"paddings must be non-negative, got "
                  f"{[list(p) for p in pw]}")


@register_validator("expand")
def _expand(datas, attrs):
    x = datas[0]
    shape = attrs.get("shape", ())
    xs = _shape(x)
    if len(shape) < len(xs):
        _fail("expand",
              f"the target shape's rank ({len(shape)}) must be >= the "
              f"input's rank ({len(xs)}); input {list(xs)}, target "
              f"{list(shape)}")
    for xd, td in zip(xs[::-1], tuple(shape)[::-1]):
        if xd != 1 and xd != td:
            _fail("expand",
                  f"input shape {list(xs)} cannot expand to "
                  f"{list(shape)}: dim {xd} is neither 1 nor {td}")


@register_validator("transpose")
def _transpose(datas, attrs):
    x = datas[0]
    perm = attrs.get("perm", ())
    nd = _ndim(x)
    if len(perm) != nd:
        _fail("transpose",
              f"perm's length ({len(perm)}) must equal input rank "
              f"({nd}); perm {list(perm)}")
    norm = [int(p) + nd if int(p) < 0 else int(p) for p in perm]
    if sorted(norm) != list(range(nd)):
        _fail("transpose",
              f"perm {list(perm)} is not a permutation of "
              f"[0, {nd})")


@register_validator("split")
def _split(datas, attrs):
    x = datas[0]
    num = attrs.get("num_or_sections")
    axis = int(attrs.get("axis", 0))
    xs = _shape(x)
    ax = axis + len(xs) if axis < 0 else axis
    if not 0 <= ax < len(xs):
        _fail("split", f"axis {axis} out of range for rank {len(xs)}")
    if isinstance(num, int):
        if num <= 0 or xs[ax] % num != 0:
            _fail("split",
                  f"The input's size along the split dimension must be "
                  f"evenly divisible by num ({num}), but received "
                  f"dim {ax} = {xs[ax]}")
    elif isinstance(num, (list, tuple)):
        fixed = sum(s for s in num if s != -1)
        n_infer = sum(1 for s in num if s == -1)
        if n_infer > 1:
            _fail("split", f"only one section may be -1, got {num}")
        if n_infer == 0 and fixed != xs[ax]:
            _fail("split",
                  f"sections {list(num)} must sum to dim {ax} = "
                  f"{xs[ax]}")
        if n_infer == 1 and fixed > xs[ax]:
            _fail("split",
                  f"sections {list(num)} exceed dim {ax} = {xs[ax]}")


@register_validator("cumsum")
def _cumsum(datas, attrs):
    x = datas[0]
    axis = attrs.get("axis")
    if axis is None:
        return  # reference: None flattens first
    _axis_in("cumsum", int(axis), max(_ndim(x), 1))


@register_validator("argsort")
def _argsort(datas, attrs):
    x = datas[0]
    _axis_in("argsort", int(attrs.get("axis", -1)), max(_ndim(x), 1))


@register_validator("topk")
def _topk(datas, attrs):
    x = datas[0]
    k = int(attrs.get("k", 1))
    xs = _shape(x)
    nd = max(len(xs), 1)
    ax = _axis_in("topk", int(attrs.get("axis", -1)), nd)
    if k < 1:
        _fail("topk",
              f"the attribute of k in the topk must be >= 1, but "
              f"received {k}")
    if xs and k > xs[ax]:
        _fail("topk",
              f"k ({k}) must be <= the input's size along axis {ax} "
              f"({xs[ax]}); input shape {list(xs)}")


@register_validator("clip")
def _clip(datas, attrs):
    lo, hi = attrs.get("min"), attrs.get("max")
    if lo is not None and hi is not None \
            and not hasattr(lo, "ndim") and not hasattr(hi, "ndim") \
            and float(lo) > float(hi):
        _fail("clip",
              f"max should be greater than or equal to min, but "
              f"received min = {lo}, max = {hi}")


@register_validator("one_hot")
def _one_hot(datas, attrs):
    x = datas[0]
    n = int(attrs.get("num_classes", 0))
    if n < 1:
        _fail("one_hot",
              f"num_classes should be a positive integer, but "
              f"received {n}")
    if not _int_dtype(x):
        _fail("one_hot",
              f"the input must be an integer dtype, got "
              f"{getattr(x, 'dtype', None)}")


@register_validator("flip")
def _flip(datas, attrs):
    x = datas[0]
    axis = attrs.get("axis")
    nd = max(_ndim(x), 1)
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    seen = set()
    for a in axes:
        n = _axis_in("flip", int(a), nd)
        if n in seen:
            _fail("flip", f"axis {list(axes)} has duplicate entries")
        seen.add(n)


@register_validator("roll")
def _roll(datas, attrs):
    x = datas[0]
    shifts = attrs.get("shifts")
    axis = attrs.get("axis")
    if axis is None:
        return  # reference: roll on the flattened tensor
    nd = max(_ndim(x), 1)
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    for a in axes:
        _axis_in("roll", int(a), nd)
    if isinstance(shifts, (list, tuple)) \
            and len(shifts) != len(tuple(axes)):
        _fail("roll",
              f"shifts ({list(shifts)}) and axis ({list(axes)}) must "
              f"have the same length")


@register_validator("diag")
def _diag(datas, attrs):
    nd = _ndim(datas[0])
    if nd not in (1, 2):
        _fail("diag",
              f"the input must be a 1-D or 2-D tensor, but received "
              f"rank {nd} (shape {list(_shape(datas[0]))})")


@register_validator("diagonal")
def _diagonal(datas, attrs):
    xs = _shape(datas[0])
    nd = len(xs)
    if nd < 2:
        _fail("diagonal",
              f"the input must have rank >= 2, but received rank {nd} "
              f"(shape {list(xs)})")
    a1 = _axis_in("diagonal", int(attrs.get("axis1", 0)), nd)
    a2 = _axis_in("diagonal", int(attrs.get("axis2", 1)), nd)
    if a1 == a2:
        _fail("diagonal",
              f"axis1 and axis2 must refer to different dimensions, "
              f"but both resolve to {a1}")


@register_validator("tril")
def _tril(datas, attrs):
    nd = _ndim(datas[0])
    if nd < 2:
        _fail("tril",
              f"the input must have rank >= 2, but received rank {nd} "
              f"(shape {list(_shape(datas[0]))})")


@register_validator("triu")
def _triu(datas, attrs):
    nd = _ndim(datas[0])
    if nd < 2:
        _fail("triu",
              f"the input must have rank >= 2, but received rank {nd} "
              f"(shape {list(_shape(datas[0]))})")


@register_validator("repeat_interleave")
def _repeat_interleave(datas, attrs):
    xs = _shape(datas[0])
    repeats = attrs.get("repeats")
    axis = attrs.get("axis")
    if axis is not None:
        ax = _axis_in("repeat_interleave", int(axis), max(len(xs), 1))
    if isinstance(repeats, (list, tuple)):
        if any(int(r) < 0 for r in repeats):
            _fail("repeat_interleave",
                  f"repeats must all be non-negative, got "
                  f"{list(repeats)}")
        size = (int(np.prod(xs)) if axis is None
                else (xs[ax] if xs else 1))
        if len(repeats) not in (1, size):
            _fail("repeat_interleave",
                  f"repeats has {len(repeats)} entries but the "
                  f"repeated dimension has size {size}")
    elif repeats is not None and int(repeats) < 0:
        _fail("repeat_interleave",
              f"repeats must be non-negative, got {repeats}")


@register_validator("cross")
def _cross(datas, attrs):
    xs, ys = _shape(datas[0]), _shape(datas[1])
    if xs != ys:
        _fail("cross",
              f"the inputs must have the same shape, but received "
              f"x{list(xs)} vs y{list(ys)}")
    ax = _axis_in("cross", int(attrs.get("axis", 0)), max(len(xs), 1))
    if xs and xs[ax] != 3:
        _fail("cross",
              f"the size along the cross axis must be 3, but "
              f"dimension {ax} of {list(xs)} is {xs[ax]}")


@register_validator("moveaxis")
def _moveaxis(datas, attrs):
    nd = max(_ndim(datas[0]), 1)
    src = attrs.get("source")
    dst = attrs.get("destination")
    srcs = src if isinstance(src, (list, tuple)) else (src,)
    dsts = dst if isinstance(dst, (list, tuple)) else (dst,)
    if len(srcs) != len(dsts):
        _fail("moveaxis",
              f"source ({list(srcs)}) and destination ({list(dsts)}) "
              f"must have the same number of axes")
    for name, axes in (("source", srcs), ("destination", dsts)):
        seen = set()
        for a in axes:
            n = _axis_in("moveaxis", int(a), nd)
            if n in seen:
                _fail("moveaxis",
                      f"{name} axes {list(axes)} have duplicates")
            seen.add(n)


@register_validator("meshgrid")
def _meshgrid(datas, attrs):
    # host-side op: the wrapper calls validate() directly
    if not datas:
        _fail("meshgrid", "meshgrid expects at least one input")
    for i, d in enumerate(datas):
        if _ndim(d) > 1:
            _fail("meshgrid",
                  f"each input must be 0-D or 1-D, but input {i} has "
                  f"shape {list(_shape(d))}")


@register_validator("sort")
def _sort(datas, attrs):
    _axis_in("sort", int(attrs.get("axis", -1)),
             max(_ndim(datas[0]), 1))


@register_validator("masked_fill")
def _masked_fill(datas, attrs):
    x, mask, value = datas[0], datas[1], datas[2]
    dt = getattr(mask, "dtype", None)
    if dt is not None and np.dtype(str(dt)) != np.bool_:
        _fail("masked_fill",
              f"the mask must be a bool tensor, got {dt}")
    try:
        np.broadcast_shapes(_shape(x), _shape(mask), _shape(value))
    except ValueError:
        _fail("masked_fill",
              f"the mask {list(_shape(mask))} / value "
              f"{list(_shape(value))} are not broadcast-compatible "
              f"with the input {list(_shape(x))}")


@register_validator("put_along_axis")
def _put_along_axis(datas, attrs):
    x, indices = datas[0], datas[1]
    if not _int_dtype(indices):
        _fail("put_along_axis",
              f"the indices must be an integer dtype, got "
              f"{getattr(indices, 'dtype', None)}")
    if _ndim(indices) != _ndim(x):
        _fail("put_along_axis",
              f"indices rank ({_ndim(indices)}) must equal input rank "
              f"({_ndim(x)}); input {list(_shape(x))}, indices "
              f"{list(_shape(indices))}")
    _axis_in("put_along_axis", int(attrs.get("axis", 0)),
             max(_ndim(x), 1))
    reduce = attrs.get("reduce", "assign")
    if reduce not in ("assign", "add", "mul", "multiply"):
        _fail("put_along_axis",
              f"the reduce should be one of 'assign', 'add', 'mul' / "
              f"'multiply', but received {reduce!r}")


@register_validator("nonzero")
def _nonzero(datas, attrs):
    # host-side op: the wrapper calls validate() directly
    if _ndim(datas[0]) < 1:
        _fail("nonzero",
              f"the input must have rank >= 1, but received rank "
              f"{_ndim(datas[0])}")


@register_validator("unique")
def _unique(datas, attrs):
    # host-side op: the wrapper calls validate() directly
    axis = attrs.get("axis")
    if axis is not None:
        _axis_in("unique", int(axis), max(_ndim(datas[0]), 1))


@register_validator("flatten")
def _flatten(datas, attrs):
    # host-side op (rides reshape): the wrapper calls validate() first
    nd = max(_ndim(datas[0]), 1)
    start = _axis_in("flatten", int(attrs.get("start_axis", 0)), nd)
    stop = _axis_in("flatten", int(attrs.get("stop_axis", -1)), nd)
    if start > stop:
        _fail("flatten",
              f"the start_axis ({attrs.get('start_axis')}) should be "
              f"no greater than stop_axis ({attrs.get('stop_axis')}) "
              f"for input rank {nd}")


@register_validator("unbind")
def _unbind(datas, attrs):
    # host-side op (split + squeeze): the wrapper calls validate() first
    _axis_in("unbind", int(attrs.get("axis", 0)),
             max(_ndim(datas[0]), 1))


@register_validator("bincount")
def _bincount(datas, attrs):
    # host-side op: the wrapper calls validate() directly
    x = datas[0]
    if _ndim(x) != 1:
        _fail("bincount",
              f"the input must be a 1-D tensor, but received shape "
              f"{list(_shape(x))}")
    if not _int_dtype(x):
        _fail("bincount",
              f"the input must be an integer dtype, got "
              f"{getattr(x, 'dtype', None)}")
    w = datas[1] if len(datas) > 1 else None
    if w is not None and _shape(w) != _shape(x):
        _fail("bincount",
              f"the weights {list(_shape(w))} must have the same shape "
              f"as the input {list(_shape(x))}")
    if int(attrs.get("minlength", 0)) < 0:
        _fail("bincount",
              f"minlength should be non-negative, but received "
              f"{attrs.get('minlength')}")


@register_validator("logsumexp")
def _logsumexp(datas, attrs):
    x = datas[0]
    axis = attrs.get("axis")
    if axis is None:
        return  # reference: None reduces over all dims
    nd = max(_ndim(x), 1)
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    seen = set()
    for a in axes:
        n = _axis_in("logsumexp", int(a), nd)
        if n in seen:
            _fail("logsumexp",
                  f"axis {list(axes)} has duplicate entries")
        seen.add(n)


@register_validator("cumprod")
def _cumprod(datas, attrs):
    dim = attrs.get("dim")
    if dim is None:
        return  # reference: None multiplies the flattened tensor
    _axis_in("cumprod", int(dim), max(_ndim(datas[0]), 1))


@register_validator("strided_slice")
def _strided_slice(datas, attrs):
    x = datas[0]
    axes = tuple(attrs.get("axes", ()))
    starts = tuple(attrs.get("starts", ()))
    ends = tuple(attrs.get("ends", ()))
    strides = tuple(attrs.get("strides", ()))
    if not (len(axes) == len(starts) == len(ends) == len(strides)):
        _fail("strided_slice",
              f"the lengths of axes ({len(axes)}), starts "
              f"({len(starts)}), ends ({len(ends)}) and strides "
              f"({len(strides)}) must be equal")
    nd = max(_ndim(x), 1)
    seen = set()
    for a in axes:
        n = _axis_in("strided_slice", int(a), nd)
        if n in seen:
            _fail("strided_slice",
                  f"axes {list(axes)} have duplicate entries")
        seen.add(n)
    for st in strides:
        if int(st) == 0:
            _fail("strided_slice",
                  f"stride must be non-zero, got strides "
                  f"{list(strides)}")


@register_validator("gather_nd")
def _gather_nd(datas, attrs):
    x, index = datas[0], datas[1]
    if not _int_dtype(index):
        _fail("gather_nd",
              f"the index must be an integer dtype, got "
              f"{getattr(index, 'dtype', None)}")
    xs, ixs = _shape(x), _shape(index)
    if not ixs:
        _fail("gather_nd",
              f"the index must have rank >= 1, but received rank 0")
    if ixs[-1] > len(xs):
        _fail("gather_nd",
              f"the last dimension of index ({ixs[-1]}) must be <= "
              f"the input's rank ({len(xs)}); input {list(xs)}, "
              f"index {list(ixs)}")


@register_validator("dot")
def _dot(datas, attrs):
    xs, ys = _shape(datas[0]), _shape(datas[1])
    if len(xs) not in (1, 2) or len(ys) not in (1, 2):
        _fail("dot",
              f"the inputs must be 1-D or 2-D tensors, but received "
              f"x{list(xs)} . y{list(ys)}")
    if xs != ys:
        _fail("dot",
              f"the inputs must have the same shape, but received "
              f"x{list(xs)} vs y{list(ys)}")


@register_validator("addmm")
def _addmm(datas, attrs):
    inp, x, y = datas[0], datas[1], datas[2]
    ins, xs, ys = _shape(inp), _shape(x), _shape(y)
    if len(xs) != 2 or len(ys) != 2:
        _fail("addmm",
              f"the tensors x and y must be 2-D, but received "
              f"x{list(xs)}, y{list(ys)}")
    if xs[1] != ys[0]:
        _fail("addmm",
              f"Input X's width should be equal to Y's height, but "
              f"received X'shape: {list(xs)}, Y'shape: {list(ys)}")
    out = (xs[0], ys[1])
    try:
        ok = np.broadcast_shapes(ins, out) == out
    except ValueError:
        ok = False
    if not ok:
        _fail("addmm",
              f"the input {list(ins)} is not broadcast-compatible "
              f"with the x @ y result shape {list(out)}")


@register_validator("searchsorted")
def _searchsorted(datas, attrs):
    ss = datas[0]
    if _ndim(ss) != 1:
        _fail("searchsorted",
              f"sorted_sequence must be a 1-D tensor here, but "
              f"received shape {list(_shape(ss))}")


@register_validator("index_add")
def _index_add(datas, attrs):
    # positional signature (x, index, axis, value) — ADVICE r3; axis
    # rides in datas unless the caller passed it by keyword.
    x, index = datas[0], datas[1]
    if "axis" in attrs:
        axis = int(attrs["axis"])
        value = datas[2] if len(datas) > 2 else None
    elif len(datas) > 3:
        axis, value = int(datas[2]), datas[3]
    else:
        return
    if not _int_dtype(index):
        _fail("index_add",
              f"the index must be an integer dtype, got "
              f"{getattr(index, 'dtype', None)}")
    if _ndim(index) > 1:
        _fail("index_add",
              f"the index should be a 0-D or 1-D tensor, got rank "
              f"{_ndim(index)}")
    nd = max(_ndim(x), 1)
    ax = _axis_in("index_add", axis, nd)
    xs, vs = _shape(x), _shape(value)
    if value is not None and len(vs) == len(xs) and xs:
        n_idx = _shape(index)[0] if _ndim(index) == 1 else 1
        expect = xs[:ax] + (n_idx,) + xs[ax + 1:]
        if vs != expect:
            _fail("index_add",
                  f"the value's shape {list(vs)} must match the "
                  f"input's except along axis {ax} where it must "
                  f"equal the index length ({n_idx}); expected "
                  f"{list(expect)}")


@register_validator("masked_select")
def _masked_select(datas, attrs):
    # host-side op: the wrapper calls validate() directly (it never
    # goes through registry.apply)
    x, mask = datas[0], datas[1]
    dt = getattr(mask, "dtype", None)
    if dt is not None and np.dtype(str(dt)) != np.bool_:
        _fail("masked_select",
              f"the mask must be a bool tensor, got {dt}")
    try:
        np.broadcast_shapes(_shape(x), _shape(mask))
    except ValueError:
        _fail("masked_select",
              f"the mask {list(_shape(mask))} is not broadcast-"
              f"compatible with the input {list(_shape(x))}")


# -- batch 7 (r14): math/selection tail toward the top-50 -------------------

@register_validator("trace")
def _trace(datas, attrs):
    # unary.cc TraceInferMeta
    x = datas[0]
    nd = _ndim(x)
    if nd < 2:
        _fail("trace",
              f"Input's dim is out of range (expected at least 2, but "
              f"got {nd})")
    a1 = _axis_in("trace", int(attrs.get("axis1", 0)), nd)
    a2 = _axis_in("trace", int(attrs.get("axis2", 1)), nd)
    if a1 == a2:
        _fail("trace",
              f"The dimensions should not be identical "
              f"{attrs.get('axis1', 0)} vs {attrs.get('axis2', 1)}")


@register_validator("kthvalue")
def _kthvalue(datas, attrs):
    # unary.cc KthvalueInferMeta
    x = datas[0]
    nd = max(_ndim(x), 1)
    ax = _axis_in("kthvalue", int(attrs.get("axis", -1)), nd)
    k = int(attrs.get("k", 1))
    if k < 1:
        _fail("kthvalue",
              f"the k in the kthvalue must >= 1, but received {k}")
    xs = _shape(x)
    if xs and k > xs[ax]:
        _fail("kthvalue",
              f"the k in the kthvalue must less equal than the size of "
              f"axis {ax} ({xs[ax]}), but received {k}")


@register_validator("mode")
def _mode(datas, attrs):
    # unary.cc ModeInferMeta
    x = datas[0]
    _axis_in("mode", int(attrs.get("axis", -1)), max(_ndim(x), 1))


@register_validator("index_sample")
def _index_sample(datas, attrs):
    # binary.cc IndexSampleInferMeta
    x, index = datas[0], datas[1]
    if _ndim(x) != 2:
        _fail("index_sample",
              f"Inputs(X) shape of IndexSample op should be 2-D, but "
              f"got X's shape = {list(_shape(x))}")
    if _ndim(index) != 2:
        _fail("index_sample",
              f"Inputs(Index) shape of IndexSample op should be 2-D, "
              f"but got Index's shape = {list(_shape(index))}")
    if not _int_dtype(index):
        _fail("index_sample",
              f"the index must be an integer dtype, got "
              f"{getattr(index, 'dtype', None)}")
    if _shape(x)[0] != _shape(index)[0]:
        _fail("index_sample",
              f"Inputs(X)'s value of dimension 0 must same with "
              f"Inputs(Index), but X's batch is {_shape(x)[0]} and "
              f"Index's batch is {_shape(index)[0]}")


@register_validator("renorm")
def _renorm(datas, attrs):
    # unary.cc RenormInferMeta (+ the p > 0 contract of the p-norm)
    x = datas[0]
    _axis_in("renorm", int(attrs.get("axis", -1)), max(_ndim(x), 1))
    p = float(attrs.get("p", 2.0))
    if p <= 0:
        _fail("renorm",
              f"the p of the renorm p-norm must be positive, but "
              f"received {p}")
    max_norm = float(attrs.get("max_norm", 0.0))
    if max_norm < 0:
        _fail("renorm",
              f"the max_norm must be non-negative, but received "
              f"{max_norm}")


@register_validator("cdist")
def _cdist(datas, attrs):
    # binary.cc CdistInferMeta
    x, y = datas[0], datas[1]
    if _ndim(x) < 2 or _ndim(y) < 2:
        _fail("cdist",
              f"the x and y must have at least 2 dimensions, got "
              f"x{list(_shape(x))} and y{list(_shape(y))}")
    if _shape(x)[-1] != _shape(y)[-1]:
        _fail("cdist",
              f"the x and y should have same value at dim -1, but got "
              f"{_shape(x)[-1]} and {_shape(y)[-1]}")
    p = float(attrs.get("p", 2.0))
    if p < 0:
        _fail("cdist",
              f"the p must be non-negative, but received {p}")


@register_validator("multinomial")
def _multinomial(datas, attrs):
    # unary.cc MultinomialInferMeta — host-side op: the wrapper calls
    # validate() directly (sampling never goes through registry.apply)
    x = datas[0]
    nd = _ndim(x)
    if nd < 1 or nd > 2:
        _fail("multinomial",
              f"The number of dimensions of the input probability "
              f"distribution should be > 0 and <= 2, but got {nd}")
    n = int(attrs.get("num_samples", 1))
    if n < 1:
        _fail("multinomial",
              f"The number of samples should be > 0, but got {n}")
    if not attrs.get("replacement", False):
        cats = _shape(x)[-1]
        if n > cats:
            _fail("multinomial",
                  f"When replacement is False, number of samples "
                  f"should be less than or equal to the number of "
                  f"categories ({cats}), but got {n}")


@register_validator("histogram")
def _histogram(datas, attrs):
    # unary.cc HistogramInferMeta — host-side op, wrapper-invoked
    bins = int(attrs.get("bins", 100))
    if bins < 1:
        _fail("histogram",
              f"the bins should be >= 1, but received {bins}")
    lo, hi = attrs.get("min", 0), attrs.get("max", 0)
    if float(hi) < float(lo):
        _fail("histogram",
              f"max must be larger or equal to min, but received "
              f"min {lo} and max {hi}")


def _reduce_axes(op, datas, attrs):
    """Shared unary-reduction check (unary.cc ReduceInferMetaBase):
    ``axis`` None / int / tuple, every entry in range, no duplicates."""
    axis = attrs.get("axis")
    if axis is None:
        return
    nd = max(_ndim(datas[0]), 1)
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    seen = set()
    for a in axes:
        n = _axis_in(op, int(a), nd)
        if n in seen:
            _fail(op, f"axis {list(axes)} has duplicate entries")
        seen.add(n)


@register_validator("reduce_prod")
def _reduce_prod(datas, attrs):
    _reduce_axes("reduce_prod", datas, attrs)


@register_validator("amax")
def _amax(datas, attrs):
    _reduce_axes("amax", datas, attrs)


@register_validator("amin")
def _amin(datas, attrs):
    _reduce_axes("amin", datas, attrs)


@register_validator("median")
def _median(datas, attrs):
    _reduce_axes("median", datas, attrs)


@register_validator("nanmedian")
def _nanmedian(datas, attrs):
    _reduce_axes("nanmedian", datas, attrs)


@register_validator("logcumsumexp")
def _logcumsumexp(datas, attrs):
    # unary.cc CumInferMeta; without this check the kernel's ``axis %
    # ndim`` silently WRAPS an out-of-range axis instead of failing.
    axis = attrs.get("axis")
    if axis is None:
        return
    _axis_in("logcumsumexp", int(axis), max(_ndim(datas[0]), 1))


# -- batch 9: lerp / dist / allclose / isclose / frexp / copysign ------------

def _is_float_dtype(dt):
    if dt is None:
        return True
    s = str(dt)
    if "float" in s:  # covers float16/32/64 AND bfloat16 (which numpy's
        return True   # issubdtype does not place under np.floating)
    try:
        return np.issubdtype(np.dtype(s), np.floating)
    except TypeError:
        return False


def _broadcast_pair(op, x, y, xname="X", yname="Y"):
    xs, ys = _shape(x), _shape(y)
    try:
        return np.broadcast_shapes(xs, ys)
    except ValueError:
        _fail(op,
              f"The shape of {xname} {list(xs)} and the shape of "
              f"{yname} {list(ys)} are not broadcast-compatible")


@register_validator("lerp")
def _lerp(datas, attrs):
    # binary.cc LerpInferMeta: x/y broadcast first, then the weight
    # against the pair (weight may be a python float — shape ())
    xy = _broadcast_pair("lerp", datas[0], datas[1])
    ws = _shape(datas[2])
    try:
        np.broadcast_shapes(xy, ws)
    except ValueError:
        _fail("lerp",
              f"The shape of Weight {list(ws)} is not broadcast-"
              f"compatible with the X/Y result shape {list(xy)}")


@register_validator("copysign")
def _copysign(datas, attrs):
    _broadcast_pair("copysign", datas[0], datas[1])


@register_validator("frexp")
def _frexp(datas, attrs):
    # unary.cc FrexpInferMeta: decomposition is only defined for
    # floating inputs
    dt = getattr(datas[0], "dtype", None)
    if not _is_float_dtype(dt):
        _fail("frexp",
              f"The input's data type must be floating point, but "
              f"received {dt}")


@register_validator("dist")
def _dist(datas, attrs):
    # binary.cc DistInferMeta — composite wrapper, validated manually
    # in linalg.dist (never passes registry.apply)
    _broadcast_pair("dist", datas[0], datas[1])


def _close_check(op, datas, attrs):
    # binary.cc ValueCompareInferMeta + the rtol/atol contract; host
    # path, wrapper-invoked
    _broadcast_pair(op, datas[0], datas[1],
                    xname="input X", yname="input Y")
    for key in ("rtol", "atol"):
        v = attrs.get(key)
        if v is not None and float(v) < 0:
            _fail(op, f"{key} must be non-negative, but received {v}")


@register_validator("allclose")
def _allclose(datas, attrs):
    _close_check("allclose", datas, attrs)


@register_validator("isclose")
def _isclose(datas, attrs):
    _close_check("isclose", datas, attrs)


# -- batch 10: linalg tail (kron / outer / householder_product / --------------
# -- matrix_power / slogdet / pinv) -------------------------------------------

def _square_matrix(op, x, name="X"):
    xs = _shape(x)
    if len(xs) < 2:
        _fail(op,
              f"The Input({name}) should have at least 2 dimensions, "
              f"but received a tensor of shape {list(xs)}")
    if xs[-1] != xs[-2]:
        _fail(op,
              f"The inner-most 2 dimensions of Input({name}) should "
              f"be equal (a square matrix or batches of square "
              f"matrices), but received shape {list(xs)}")
    return xs


@register_validator("kron")
def _kron(datas, attrs):
    # binary.cc KronInferMeta: both operands need rank >= 1 (the
    # output dim is the elementwise product of the right-aligned dims)
    for name, d in (("X", datas[0]), ("Y", datas[1])):
        if _ndim(d) < 1:
            _fail("kron",
                  f"the rank of Input({name}) should be no less than "
                  f"1, but received a 0-D tensor")


@register_validator("outer")
def _outer(datas, attrs):
    # linalg outer flattens both sides; only 0-D operands are rejected
    for name, d in (("X", datas[0]), ("Y", datas[1])):
        if _ndim(d) < 1:
            _fail("outer",
                  f"Input({name}) of outer should be a tensor with "
                  f"rank >= 1, but received a 0-D tensor")


@register_validator("householder_product")
def _householder_product(datas, attrs):
    # unary.cc HouseholderProductInferMeta: x is [*, m, n] reflectors,
    # tau is [*, k] with k <= n <= m and matching batch dims
    x, tau = datas[0], datas[1]
    xs, ts = _shape(x), _shape(tau)
    if len(xs) < 2:
        _fail("householder_product",
              f"The input matrix x must be at least 2-D, but received "
              f"shape {list(xs)}")
    if len(ts) != len(xs) - 1:
        _fail("householder_product",
              f"The input vector tau should have one dimension less "
              f"than x, but received x {list(xs)} and tau {list(ts)}")
    m, n = xs[-2], xs[-1]
    if m < n:
        _fail("householder_product",
              f"The rows of input matrix x must be greater than or "
              f"equal to its columns, but received shape {list(xs)}")
    if ts[-1] > n:
        _fail("householder_product",
              f"The last dim of tau ({ts[-1]}) must not exceed the "
              f"columns of x ({n}), received x {list(xs)} and tau "
              f"{list(ts)}")
    if xs[:-2] != ts[:-1]:
        _fail("householder_product",
              f"The batch dimensions of x and tau should match, but "
              f"received x {list(xs)} and tau {list(ts)}")


@register_validator("matrix_power")
def _matrix_power(datas, attrs):
    # unary.cc MatrixPowerInferMeta: square matrices only (a negative
    # exponent inverts, so squareness is the whole contract)
    _square_matrix("matrix_power", datas[0])


@register_validator("slogdet")
def _slogdet(datas, attrs):
    # unary.cc SlogDeterminantInferMeta
    _square_matrix("slogdet", datas[0], name="Input")


@register_validator("pinv")
def _pinv(datas, attrs):
    # unary.cc PInverseInferMeta — host-path wrapper, validated
    # manually in linalg.pinv (never passes registry.apply).  The
    # hermitian fast path additionally requires squareness.
    x = datas[0]
    xs = _shape(x)
    if len(xs) < 2:
        _fail("pinv",
              f"The input tensor x's dimension of PinvOp should be "
              f"no less than 2, but received shape {list(xs)}")
    if attrs.get("hermitian") and xs[-1] != xs[-2]:
        _fail("pinv",
              f"hermitian=True requires square matrices, but "
              f"received shape {list(xs)}")


# -- batch 11: linalg solves + factorizations (lu / lu_unpack / ---------------
# -- cholesky_solve / triangular_solve / matrix_rank / eigvalsh) --------------

def _batch_broadcast(op, xs, ys, xname="X", yname="Y"):
    """Batch dims (everything left of the matrix dims) must broadcast."""
    try:
        np.broadcast_shapes(xs[:-2], ys[:-2])
    except ValueError:
        _fail(op,
              f"The batch dimensions of Input({xname}) {list(xs)} and "
              f"Input({yname}) {list(ys)} are not broadcast-compatible")


@register_validator("lu")
def _lu(datas, attrs):
    # unary.cc LUInferMeta — host-path wrapper, validated manually in
    # linalg.lu (never passes registry.apply)
    xs = _shape(datas[0])
    if len(xs) < 2:
        _fail("lu",
              f"The rank of input must greater than 2, but received "
              f"input shape {list(xs)}")


@register_validator("lu_unpack")
def _lu_unpack(datas, attrs):
    # unary.cc LUUnpackInferMeta — host-path wrapper, validated
    # manually in linalg.lu_unpack.  Pivots carry one fewer dim than
    # the packed factor and their last dim is min(m, n).
    x, piv = datas[0], datas[1]
    xs, ps = _shape(x), _shape(piv)
    if len(xs) < 2:
        _fail("lu_unpack",
              f"The rank of input must greater than 2, but received "
              f"input shape {list(xs)}")
    if len(ps) != len(xs) - 1:
        _fail("lu_unpack",
              f"The rank of Pivots should be one less than the rank "
              f"of X, but received X {list(xs)} and Pivots {list(ps)}")
    k = min(xs[-2], xs[-1])
    if ps[-1] != k:
        _fail("lu_unpack",
              f"The last dim of Pivots should be min(rows, cols) = "
              f"{k} of X {list(xs)}, but received Pivots {list(ps)}")
    if xs[:-2] != ps[:-1]:
        _fail("lu_unpack",
              f"The batch dimensions of X and Pivots should match, "
              f"but received X {list(xs)} and Pivots {list(ps)}")


@register_validator("cholesky_solve")
def _cholesky_solve(datas, attrs):
    # binary.cc CholeskySolveInferMeta — host-path wrapper, validated
    # manually in linalg.cholesky_solve.  x is the RHS [*, M, K], y
    # the square Cholesky factor [*, M, M].
    x, y = datas[0], datas[1]
    xs = _shape(x)
    if len(xs) < 2:
        _fail("cholesky_solve",
              f"The rank of Input(X) should be no less than 2, but "
              f"received shape {list(xs)}")
    ys = _square_matrix("cholesky_solve", y, name="Y")
    if ys[-1] != xs[-2]:
        _fail("cholesky_solve",
              f"The rows of RHS X should match the order of the "
              f"factor Y, but received X {list(xs)} and Y {list(ys)}")
    _batch_broadcast("cholesky_solve", xs, ys)


@register_validator("triangular_solve")
def _triangular_solve(datas, attrs):
    # binary.cc TriangularSolveInferMeta: x is the square triangular
    # coefficient [*, M, M], y the RHS [*, M, K]
    x, y = datas[0], datas[1]
    xs = _square_matrix("triangular_solve", x)
    ys = _shape(y)
    if len(ys) < 2:
        _fail("triangular_solve",
              f"The rank of Input(Y) should be no less than 2, but "
              f"received shape {list(ys)}")
    if xs[-1] != ys[-2]:
        _fail("triangular_solve",
              f"The last dimension of X should be equal to the "
              f"second-to-last dimension of Y, but received X "
              f"{list(xs)} and Y {list(ys)}")
    _batch_broadcast("triangular_solve", xs, ys)


@register_validator("matrix_rank")
def _matrix_rank(datas, attrs):
    # unary.cc MatrixRankInferMeta — host-path wrapper, validated
    # manually in linalg.matrix_rank.  The hermitian fast path (eigh
    # under the hood) additionally requires squareness.
    xs = _shape(datas[0])
    if len(xs) < 2:
        _fail("matrix_rank",
              f"The dims of input must be greater than 2, but "
              f"received shape {list(xs)}")
    if attrs.get("hermitian") and xs[-1] != xs[-2]:
        _fail("matrix_rank",
              f"if hermitian == true, matrix should be n*n, but "
              f"received shape {list(xs)}")


@register_validator("eigvalsh")
def _eigvalsh(datas, attrs):
    # unary.cc EigvalshInferMeta — host-path wrapper, validated
    # manually in linalg.eigvalsh
    _square_matrix("eigvalsh", datas[0], name="Input")
    uplo = attrs.get("UPLO", "L")
    if uplo not in ("L", "U"):
        _fail("eigvalsh",
              f"UPLO must be 'L' or 'U', but received {uplo!r}")


@register_validator("cholesky")
def _cholesky(datas, attrs):
    # unary.cc CholeskyInferMeta — auto-wired through registry.apply
    _square_matrix("cholesky", datas[0], name="Input")


@register_validator("svd")
def _svd(datas, attrs):
    # unary.cc SvdInferMeta — host-path wrapper, validated manually in
    # linalg.svd
    xs = _shape(datas[0])
    if len(xs) < 2:
        _fail("svd",
              f"The rank of Input(X) should be greater equal than 2, "
              f"but received shape {list(xs)}")


@register_validator("qr")
def _qr(datas, attrs):
    # unary.cc QrInferMeta: rank >= 2 plus the mode grammar ('reduced'
    # and 'complete' return (Q, R); paddle's 'r' keeps R only)
    xs = _shape(datas[0])
    if len(xs) < 2:
        _fail("qr",
              f"The rank of Input(X) should be greater or equal to 2, "
              f"but received shape {list(xs)}")
    mode = attrs.get("mode", "reduced")
    if mode not in ("reduced", "complete", "r"):
        _fail("qr",
              f"QR received unrecognized mode {mode!r}; expected one "
              f"of 'reduced', 'complete', 'r'")


@register_validator("eig")
def _eig(datas, attrs):
    # unary.cc EigInferMeta — the general eigendecomposition needs a
    # square (batch of) matrix
    _square_matrix("eig", datas[0], name="Input")


@register_validator("eigh")
def _eigh(datas, attrs):
    # unary.cc EighInferMeta — square plus the UPLO grammar, the same
    # contract as eigvalsh
    _square_matrix("eigh", datas[0], name="Input")
    uplo = attrs.get("UPLO", "L")
    if uplo not in ("L", "U"):
        _fail("eigh",
              f"UPLO must be 'L' or 'U', but received {uplo!r}")


@register_validator("cond")
def _cond(datas, attrs):
    # unary.cc CondInferMeta: rank >= 2 always; the singular-value
    # norms (p None/2/-2) accept rectangles, every other order inverts
    # the matrix and needs squareness
    xs = _shape(datas[0])
    if len(xs) < 2:
        _fail("cond",
              f"The input of condition number must be a matrix or "
              f"batches of matrices, but received shape {list(xs)}")
    p = attrs.get("p")
    if p not in (None, 1, -1, 2, -2, float("inf"), float("-inf"),
                 "fro", "nuc"):
        _fail("cond",
              f"The p of condition number must be one of None, 1, "
              f"-1, 2, -2, inf, -inf, 'fro', 'nuc', but received "
              f"{p!r}")
    if p not in (None, 2, -2) and xs[-1] != xs[-2]:
        _fail("cond",
              f"The input matrix must be square when p is {p!r}, but "
              f"received shape {list(xs)}")


# -- batch 13: linalg systems + products (solve / lstsq / tensordot / ---------
# -- multi_dot) + matmul batch broadcasting (extends _matmul above) -----------

@register_validator("solve")
def _solve(datas, attrs):
    # binary.cc SolveInferMeta — auto-wired through registry.apply: x
    # is the square coefficient [*, M, M], y the RHS ([*, M, K] or an
    # [M] vector), batch dims broadcast
    x, y = datas[0], datas[1]
    xs = _square_matrix("solve", x)
    ys = _shape(y)
    if not ys:
        _fail("solve",
              f"The rank of Input(Y) should be no less than 1, but "
              f"received a 0-D tensor")
    rows = ys[-2] if len(ys) >= 2 else ys[0]
    if rows != xs[-1]:
        _fail("solve",
              f"The rows of the RHS Y should match the order of the "
              f"coefficient matrix X, but received X {list(xs)} and "
              f"Y {list(ys)}")
    if len(ys) >= 2:
        _batch_broadcast("solve", xs, ys)


@register_validator("lstsq")
def _lstsq(datas, attrs):
    # binary.cc LstsqInferMeta — host-path wrapper, validated manually
    # in linalg.lstsq: x [*, M, N] and y [*, M, K] share their rows
    # and batch dims; the driver grammar is the reference's
    x, y = datas[0], datas[1]
    xs, ys = _shape(x), _shape(y)
    for name, s in (("X", xs), ("Y", ys)):
        if len(s) < 2:
            _fail("lstsq",
                  f"The rank of Input({name}) should be no less than "
                  f"2, but received shape {list(s)}")
    if xs[-2] != ys[-2]:
        _fail("lstsq",
              f"The rows (second-to-last dimension) of X and Y should "
              f"be equal, but received X {list(xs)} and Y {list(ys)}")
    _batch_broadcast("lstsq", xs, ys)
    driver = attrs.get("driver")
    if driver not in (None, "gels", "gelsy", "gelsd", "gelss"):
        _fail("lstsq",
              f"The driver should be one of None, 'gels', 'gelsy', "
              f"'gelsd', 'gelss', but received {driver!r}")


@register_validator("tensordot")
def _tensordot(datas, attrs):
    # tensordot (math.py TensordotInferMeta shape grammar) — auto-wired
    # through registry.apply after the wrapper normalizes axes to an
    # int or a hashable pair
    x, y = datas[0], datas[1]
    xs, ys = _shape(x), _shape(y)
    axes = attrs.get("axes", 2)
    if isinstance(axes, int):
        if axes < 0:
            _fail("tensordot",
                  f"The number of contracted axes must be "
                  f"non-negative, but received {axes}")
        if axes > min(len(xs), len(ys)):
            _fail("tensordot",
                  f"The number of contracted axes ({axes}) must not "
                  f"exceed the rank of either operand, but received "
                  f"x {list(xs)} and y {list(ys)}")
        if axes and xs[len(xs) - axes:] != ys[:axes]:
            _fail("tensordot",
                  f"The contracted dimensions should be equal: the "
                  f"last {axes} dims of x {list(xs)} vs the first "
                  f"{axes} dims of y {list(ys)}")
        return
    if not (isinstance(axes, tuple) and len(axes) == 2):
        return  # unrecognized spelling: jnp's own checks apply
    ax, ay = axes
    ax = (ax,) if isinstance(ax, int) else tuple(ax)
    ay = (ay,) if isinstance(ay, int) else tuple(ay)
    if len(ax) != len(ay):
        _fail("tensordot",
              f"The axes lists for x and y should have the same "
              f"length, but received {list(ax)} and {list(ay)}")
    for a, b in zip(ax, ay):
        if not -len(xs) <= a < len(xs):
            _fail("tensordot",
                  f"The axis {a} is out of range for x of rank "
                  f"{len(xs)}")
        if not -len(ys) <= b < len(ys):
            _fail("tensordot",
                  f"The axis {b} is out of range for y of rank "
                  f"{len(ys)}")
        if xs[a] != ys[b]:
            _fail("tensordot",
                  f"The contracted dimensions should be equal, but "
                  f"x axis {a} has size {xs[a]} and y axis {b} has "
                  f"size {ys[b]}")


@register_validator("multi_dot")
def _multi_dot(datas, attrs):
    # multiary.cc MultiDotInferMeta — host-path wrapper, validated
    # manually in linalg.multi_dot: >= 2 operands, the ends may be
    # vectors, every middle operand must be a matrix, and the chain's
    # adjacent inner dimensions must agree
    shapes = [_shape(d) for d in datas]
    if len(shapes) < 2:
        _fail("multi_dot",
              f"The number of input tensors should be no less than 2, "
              f"but received {len(shapes)}")
    for name, s in (("first", shapes[0]), ("last", shapes[-1])):
        if len(s) not in (1, 2):
            _fail("multi_dot",
                  f"The {name} input tensor can be 1-D or 2-D, but "
                  f"received shape {list(s)}")
    for i, s in enumerate(shapes[1:-1], 1):
        if len(s) != 2:
            _fail("multi_dot",
                  f"The middle input tensors must be 2-D, but "
                  f"input[{i}] has shape {list(s)}")
    k = shapes[0][-1]
    for i, s in enumerate(shapes[1:], 1):
        if s[0] != k:
            _fail("multi_dot",
                  f"The inner dimensions of adjacent operands should "
                  f"be equal, but input[{i - 1}] ends with {k} and "
                  f"input[{i}] {list(s)} starts with {s[0]}")
        k = s[-1]


# -- batch 14: construction + statistics + in-place random fills --------------


def _float_dtype(x):
    dt = getattr(x, "dtype", None)
    if dt is None:
        return True
    try:
        return np.issubdtype(np.dtype(str(dt)), np.floating)
    except TypeError:
        return True     # extension dtypes (bfloat16): let jnp decide


@register_validator("block_diag")
def _block_diag(datas, attrs):
    # multiary.cc BlockDiagInferMeta — auto-wired: every input must be
    # at most 2-D (each block lands on the result diagonal)
    if not datas:
        _fail("block_diag", "block_diag expects at least one input")
    for i, d in enumerate(datas):
        if _ndim(d) > 2:
            _fail("block_diag",
                  f"Each input tensor can be 0-D, 1-D or 2-D, but "
                  f"input[{i}] has shape {list(_shape(d))}")


@register_validator("vander")
def _vander(datas, attrs):
    # unary.cc VanderInferMeta — auto-wired: 1-D input, non-negative
    # column count
    x = datas[0]
    if _ndim(x) != 1:
        _fail("vander",
              f"The input tensor must be 1-D, but received shape "
              f"{list(_shape(x))}")
    n = attrs.get("n")
    if n is not None and int(n) < 0:
        _fail("vander",
              f"The number of columns N should be non-negative, but "
              f"received {n}")


@register_validator("corrcoef")
def _corrcoef(datas, attrs):
    # unary.cc CorrcoefInferMeta — host-path wrapper, validated
    # manually in linalg.corrcoef: observations as a vector or matrix
    x = datas[0]
    if _ndim(x) > 2:
        _fail("corrcoef",
              f"The input tensor must be 1-D or 2-D, but received "
              f"shape {list(_shape(x))}")
    if not _float_dtype(x):
        _fail("corrcoef",
              f"The input must be a floating dtype, got "
              f"{getattr(x, 'dtype', None)}")


@register_validator("cov")
def _cov(datas, attrs):
    # multiary.cc CovInferMeta — host-path wrapper, validated manually
    # in linalg.cov: 1-D/2-D observations; each weights vector must be
    # 1-D with one entry per observation
    x = datas[0]
    xs = _shape(x)
    if len(xs) > 2:
        _fail("cov",
              f"The input tensor must be 1-D or 2-D, but received "
              f"shape {list(xs)}")
    rowvar = bool(attrs.get("rowvar", True))
    if len(xs) <= 1:
        nobs = xs[0] if xs else 1
    else:
        nobs = xs[1] if rowvar else xs[0]
    for name in ("fweights", "aweights"):
        w = attrs.get(name)
        if w is None:
            continue
        ws = _shape(w)
        if len(ws) != 1:
            _fail("cov",
                  f"The {name} tensor must be 1-D, but received shape "
                  f"{list(ws)}")
        if ws[0] != nobs:
            _fail("cov",
                  f"The length of {name} ({ws[0]}) should match the "
                  f"number of observations ({nobs})")


@register_validator("cauchy_")
def _cauchy_(datas, attrs):
    # unary.cc CauchyInferMeta — in-place fill, validated manually in
    # random.cauchy_: floating destination, positive scale
    x = datas[0]
    if not _float_dtype(x):
        _fail("cauchy_",
              f"The tensor to fill must be a floating dtype, got "
              f"{getattr(x, 'dtype', None)}")
    scale = attrs.get("scale", 1)
    if not float(scale) > 0:
        _fail("cauchy_",
              f"The scale parameter should be positive, but received "
              f"{scale}")


@register_validator("geometric_")
def _geometric_(datas, attrs):
    # unary.cc GeometricInferMeta — in-place fill, validated manually
    # in random.geometric_: floating destination, success probability
    # strictly inside (0, 1)
    x = datas[0]
    if not _float_dtype(x):
        _fail("geometric_",
              f"The tensor to fill must be a floating dtype, got "
              f"{getattr(x, 'dtype', None)}")
    probs = attrs.get("probs")
    if probs is not None and np.ndim(probs) == 0 \
            and not 0.0 < float(probs) < 1.0:
        _fail("geometric_",
              f"The probs parameter should be in the open interval "
              f"(0, 1), but received {probs}")


# -- batch 15: broadcast-shaping + dedup + distribution draws -----------------


@register_validator("expand_as")
def _expand_as(datas, attrs):
    # binary.cc ExpandAsInferMeta: the source rank must not exceed the
    # target's, and every source dim must equal the right-aligned
    # target dim or be 1 (otherwise the failure is a jnp broadcast
    # error deep inside expand's dispatch)
    xs = _shape(datas[0])
    ts = tuple(int(d) for d in attrs.get("target_shape", ()))
    if len(xs) > len(ts):
        _fail("expand_as",
              f"The rank of Input(X) {list(xs)} must not be greater "
              f"than the rank of Input(Y) {list(ts)}")
    for i in range(1, len(xs) + 1):
        if xs[-i] != ts[-i] and xs[-i] != 1:
            _fail("expand_as",
                  f"The value of the non-singleton dimension {len(ts) - i} "
                  f"of Input(X) ({xs[-i]}) must match Input(Y) "
                  f"({ts[-i]}); X'shape: {list(xs)}, Y'shape: {list(ts)}")


@register_validator("chunk")
def _chunk(datas, attrs):
    # unary.cc SplitWithNumInferMeta (chunk == split by count): a
    # positive chunk count, an in-range axis, and an axis extent the
    # count divides evenly
    xs = _shape(datas[0])
    chunks = int(attrs.get("chunks", 0))
    if chunks <= 0:
        _fail("chunk",
              f"Attr(chunks) should be greater than 0, but received "
              f"{chunks}")
    axis = _axis_in("chunk", int(attrs.get("axis", 0)), len(xs))
    if xs[axis] % chunks:
        _fail("chunk",
              f"The input's size along the split dimension must be "
              f"evenly divisible by Attr(chunks), but received "
              f"input shape {list(xs)}, axis {axis} and chunks {chunks}")


@register_validator("unique_consecutive")
def _unique_consecutive(datas, attrs):
    # unary.cc UniqueConsecutiveInferMeta: the index dtype attr is
    # int32/int64 only, and the axis (when given) must be in rank range
    xs = _shape(datas[0])
    dtype = str(attrs.get("dtype", "int64")).replace("paddle.", "")
    if dtype not in ("int32", "int64"):
        _fail("unique_consecutive",
              f"The dtype of attr(dtype) should be int32 or int64, "
              f"but got {dtype}")
    axis = attrs.get("axis")
    if axis is not None:
        _axis_in("unique_consecutive", int(axis), max(len(xs), 1))


@register_validator("poisson")
def _poisson(datas, attrs):
    # unary.cc PoissonInferMeta: the rate tensor must be floating
    if not _float_dtype(datas[0]):
        _fail("poisson",
              f"The rate tensor must be a floating dtype, got "
              f"{getattr(datas[0], 'dtype', None)}")


@register_validator("exponential_")
def _exponential_(datas, attrs):
    # unary.cc ExponentialInferMeta — in-place fill: floating
    # destination, strictly positive rate
    if not _float_dtype(datas[0]):
        _fail("exponential_",
              f"The tensor to fill must be a floating dtype, got "
              f"{getattr(datas[0], 'dtype', None)}")
    lam = attrs.get("lam", 1.0)
    if not float(lam) > 0:
        _fail("exponential_",
              f"The lam parameter should be positive, but received "
              f"{lam}")


@register_validator("log_normal_")
def _log_normal_(datas, attrs):
    # unary.cc LogNormalInferMeta — in-place fill: floating
    # destination, strictly positive std of the underlying normal
    if not _float_dtype(datas[0]):
        _fail("log_normal_",
              f"The tensor to fill must be a floating dtype, got "
              f"{getattr(datas[0], 'dtype', None)}")
    std = attrs.get("std", 2.0)
    if not float(std) > 0:
        _fail("log_normal_",
              f"The std parameter should be positive, but received "
              f"{std}")


@register_validator("binomial")
def _binomial(datas, attrs):
    # binary.cc BinomialInferMeta: count and prob are drawn
    # elementwise, so their shapes must match exactly
    cs, ps = _shape(datas[0]), _shape(datas[1])
    if cs != ps:
        _fail("binomial",
              f"Input(count) and Input(prob) should have the same "
              f"shape, but received count's shape {list(cs)} and "
              f"prob's shape {list(ps)}")
