"""Eager argument validation — the InferMeta layer.

Reference: ``paddle/phi/infermeta/`` (binary.cc MatmulInferMeta,
multiary.cc ConcatInferMeta, unary.cc ReshapeInferMeta, ...) — there,
every op validates shapes/dtypes BEFORE the kernel runs and raises
``InvalidArgument`` with an actionable message.  Without this layer a bad
call surfaces as a jnp broadcasting error deep inside dispatch.

TPU-native: validators run on the *metadata only* (shapes/dtypes — no
device work, no tracing interaction) for the high-traffic ops where
jnp's own message is worst.  Registered per op name; ``registry.apply``
consults the table when eager (tracers skip: XLA's shape checks own the
traced path, and validators must never force a concrete value).
"""
from __future__ import annotations

import numpy as np

from ..core.enforce import InvalidArgumentError

_VALIDATORS: dict = {}


def register_validator(name):
    def deco(fn):
        _VALIDATORS[name] = fn
        return fn

    return deco


def validate(op_name, datas, attrs):
    """Called from registry.apply (eager only).  ``datas`` are raw
    arrays/scalars — validators read only .shape/.dtype/.ndim."""
    fn = _VALIDATORS.get(op_name)
    if fn is not None:
        fn(datas, attrs)


def _shape(x):
    return tuple(getattr(x, "shape", ()))


def _ndim(x):
    return len(_shape(x))


def _fail(op, msg):
    raise InvalidArgumentError(
        f"(InvalidArgument) {msg} [operator < {op} > error]")


@register_validator("matmul")
def _matmul(datas, attrs):
    x, y = datas[0], datas[1]
    xs, ys = _shape(x), _shape(y)
    if not xs or not ys:
        _fail("matmul", f"matmul inputs must have rank >= 1, got "
                        f"x{list(xs)} @ y{list(ys)}")
    tx = bool(attrs.get("transpose_x", False))
    ty = bool(attrs.get("transpose_y", False))
    kx = xs[-2] if (tx and len(xs) > 1) else xs[-1]
    ky = (ys[-1] if ty else ys[-2]) if len(ys) > 1 else ys[0]
    if kx != ky:
        _fail("matmul",
              f"Input X's width should be equal to Y's height, but "
              f"received X'shape: {list(xs)}, Y'shape: {list(ys)} "
              f"(contracted dims {kx} vs {ky}, transpose_x={tx}, "
              f"transpose_y={ty})")


@register_validator("concat")
def _concat(datas, attrs):
    axis = int(attrs.get("axis", 0))
    shapes = [_shape(d) for d in datas]
    if not shapes:
        _fail("concat", "concat expects at least one input")
    base = shapes[0]
    nd = len(base)
    ax = axis + nd if axis < 0 else axis
    if not 0 <= ax < nd:
        _fail("concat", f"axis {axis} out of range for rank {nd}")
    for i, s in enumerate(shapes[1:], 1):
        if len(s) != nd:
            _fail("concat",
                  f"all inputs must share rank; input 0 has rank {nd}, "
                  f"input {i} has rank {len(s)}")
        for d in range(nd):
            if d != ax and s[d] != base[d]:
                _fail("concat",
                      f"The shape of input[0] and input[{i}] is "
                      f"expected to be equal except on axis {ax}, but "
                      f"received input[0]: {list(base)} vs input[{i}]: "
                      f"{list(s)}")


@register_validator("reshape")
def _reshape(datas, attrs):
    x = datas[0]
    shape = attrs.get("shape")
    if shape is None:
        return
    n = int(np.prod(_shape(x))) if _shape(x) else 1
    known = 1
    minus1 = 0
    for s in shape:
        if s == -1:
            minus1 += 1
        elif s == 0:
            continue  # reference: 0 copies the input dim
        else:
            known *= int(s)
    if minus1 > 1:
        _fail("reshape", f"only one dim may be -1, got shape {shape}")
    if minus1 == 0 and known != n and 0 not in shape:
        _fail("reshape",
              f"the number of elements ({n}) is not equal to the "
              f"target shape {list(shape)} ({known} elements)")
    if minus1 == 1 and known and n % known != 0:
        _fail("reshape",
              f"cannot infer -1: {n} elements not divisible by "
              f"{known} (target shape {list(shape)})")


@register_validator("conv2d")
def _conv2d(datas, attrs):
    x, w = datas[0], datas[1]
    xs, ws = _shape(x), _shape(w)
    if len(xs) != 4 or len(ws) != 4:
        _fail("conv2d",
              f"conv2d expects 4-D input and filter, got input "
              f"{list(xs)}, filter {list(ws)}")
    groups = int(attrs.get("groups", 1))
    fmt = attrs.get("data_format", "NCHW")
    in_ch = xs[1] if fmt == "NCHW" else xs[-1]
    if in_ch != ws[1] * groups:
        _fail("conv2d",
              f"The number of input's channels should be equal to "
              f"filter's channels * groups, but received input "
              f"channels {in_ch}, filter shape {list(ws)}, groups "
              f"{groups}")
    if ws[0] % groups != 0:
        _fail("conv2d",
              f"output channels {ws[0]} must be divisible by groups "
              f"{groups}")


@register_validator("embedding")
def _embedding(datas, attrs):
    # arg order matches the embedding op's signature — the call site
    # (nn/functional/__init__.py embedding) passes (weight, ids)
    table, ids = datas[0], datas[1]
    if _ndim(table) != 2:
        _fail("embedding",
              f"the weight must be 2-D [vocab, dim], got "
              f"{list(_shape(table))}")
    dt = getattr(ids, "dtype", None)
    if dt is not None and not np.issubdtype(np.dtype(str(dt)),
                                            np.integer):
        _fail("embedding",
              f"the input ids must be an integer dtype, got {dt}")


def _linear(datas, attrs):  # F.linear rides matmul; kept for custom use
    x, w = datas[0], datas[1]
    xs, ws = _shape(x), _shape(w)
    if len(ws) != 2:
        _fail("linear", f"weight must be 2-D [in, out], got {list(ws)}")
    if xs and xs[-1] != ws[0]:
        _fail("linear",
              f"Input's last dim ({xs[-1]}) should equal weight's "
              f"first dim ({ws[0]}); input {list(xs)}, weight "
              f"{list(ws)}")


@register_validator("where")
def _where(datas, attrs):
    if len(datas) < 3:
        return
    c, x, y = datas[0], datas[1], datas[2]
    try:
        np.broadcast_shapes(_shape(c), _shape(x), _shape(y))
    except ValueError:
        _fail("where",
              f"condition/x/y are not broadcast-compatible: "
              f"{list(_shape(c))}, {list(_shape(x))}, "
              f"{list(_shape(y))}")


@register_validator("softmax_with_cross_entropy")
def _cross_entropy(datas, attrs):
    logits, label = datas[0], datas[1]
    ls, ys = _shape(logits), _shape(label)
    if not ls:
        _fail("softmax_with_cross_entropy",
              "logits must be at least 1-D")
    if attrs.get("soft_label"):
        if ls != ys:
            _fail("cross_entropy",
                  f"soft labels must match logits shape {list(ls)}, "
                  f"got {list(ys)}")
        return
    if len(ys) == len(ls) and ys[-1] not in (1, ls[-1]):
        _fail("cross_entropy",
              f"hard label's last dim must be 1, got label "
              f"{list(ys)} for logits {list(ls)}")


@register_validator("split")
def _split(datas, attrs):
    x = datas[0]
    num = attrs.get("num_or_sections")
    axis = int(attrs.get("axis", 0))
    xs = _shape(x)
    ax = axis + len(xs) if axis < 0 else axis
    if not 0 <= ax < len(xs):
        _fail("split", f"axis {axis} out of range for rank {len(xs)}")
    if isinstance(num, int):
        if num <= 0 or xs[ax] % num != 0:
            _fail("split",
                  f"The input's size along the split dimension must be "
                  f"evenly divisible by num ({num}), but received "
                  f"dim {ax} = {xs[ax]}")
    elif isinstance(num, (list, tuple)):
        fixed = sum(s for s in num if s != -1)
        n_infer = sum(1 for s in num if s == -1)
        if n_infer > 1:
            _fail("split", f"only one section may be -1, got {num}")
        if n_infer == 0 and fixed != xs[ax]:
            _fail("split",
                  f"sections {list(num)} must sum to dim {ax} = "
                  f"{xs[ax]}")
        if n_infer == 1 and fixed > xs[ax]:
            _fail("split",
                  f"sections {list(num)} exceed dim {ax} = {xs[ax]}")
