"""Shape-keyed kernel autotune cache.

Reference analog: the exhaustive-search cudnn workspace the reference
wraps around conv (``paddle/phi/kernels/gpudnn/conv_kernel.cu``'s
``FLAGS_cudnn_exhaustive_search`` + cached AlgorithmsCache) — pick a
kernel configuration by measuring once per shape, then replay the
winner forever.

TPU-native: the tunables are Pallas tile/config choices (flash-attention
block sizes, long_attention block_q, rms_norm row-block, paged-decode
impl choice), the key is (device_kind, kernel, shape-key), and the cache
has three layers:

  1. process memory (dict — the hot path is one dict hit),
  2. a JSON file shared across processes (``PT_AUTOTUNE_CACHE``, default
     ``~/.cache/paddle_tpu/autotune.json``) so one measured run seeds
     every later run on the machine,
  3. a built-in seed table of winners proven in PERF.md (e.g. the
     512/1024 flash-attention tiles on v5e) so a fresh install starts
     from measured-good, not library defaults.

``lookup`` never measures (safe at trace time — it is pure host work);
``tune`` measures candidates via a caller-supplied thunk on a miss and
records the winner.  ``PT_AUTOTUNE=0`` disables both layers 2 and 3 and
makes ``lookup`` return its default (the escape hatch when a stale
cache entry is suspected).
"""
from __future__ import annotations

import json
import os
import time

import jax

# -- key / storage ------------------------------------------------------

_MEM: dict = {}

#: winners proven by measurement in PERF.md, keyed (device substring,
#: kernel).  Applies to every shape of that kernel on that device —
#: shape-specific measurements (layers 1/2) override.
_SEED = {
    # PERF.md r4: flash tiles 512/1024 beat the library's 128 default
    # on v5e at the llama/bert shapes (MXU stays busier per grid step).
    ("v5 lite", "fa_blocks"): (512, 1024),
    # PERF.md r4: long_attention fwd block_q=256 (bwd VMEM cap).
    ("v5 lite", "long_attention_block_q"): 256,
}


def enabled() -> bool:
    return os.environ.get("PT_AUTOTUNE", "1") != "0"


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:  # pragma: no cover - no backend at all
        return "unknown"


def cache_path() -> str:
    # shares the PT_CACHE_DIR root with the AOT compile cache — one
    # directory to ship/mount to pre-warm a fresh replica
    from ..core.aot import cache_root

    return os.environ.get(
        "PT_AUTOTUNE_CACHE",
        os.path.join(cache_root(), "autotune.json"))


def _key(kernel, shape_key) -> str:
    flat = "x".join(str(s) for s in tuple(shape_key)) or "-"
    return f"{device_kind()}|{kernel}|{flat}"


def _freeze(v):
    """JSON round-trips tuples as lists; winners are compared/unpacked
    as tuples."""
    return tuple(v) if isinstance(v, list) else v


def _load_disk() -> dict:
    try:
        with open(cache_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _store_disk(key: str, value) -> None:
    """Best-effort read-merge-write (atomic rename); losing a race just
    costs a re-measurement in some later process."""
    path = cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        disk = _load_disk()
        disk[key] = value
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(disk, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:  # pragma: no cover - read-only FS etc.
        pass


def clear_memory_cache() -> None:
    """Test hook: drop layer 1 so disk/seed layers are exercised."""
    _MEM.clear()


# -- query / record -----------------------------------------------------

def lookup(kernel, shape_key, default):
    """Cached winner for (device, kernel, shape) or ``default``.  Never
    measures — safe anywhere, including inside a trace."""
    key = _key(kernel, shape_key)
    if key in _MEM:
        return _MEM[key]
    if not enabled():
        return default
    disk = _load_disk()
    if key in disk:
        _MEM[key] = _freeze(disk[key])
        return _MEM[key]
    kind = device_kind().lower()
    for (dev_sub, kern), win in _SEED.items():
        if kern == kernel and dev_sub in kind:
            _MEM[key] = win
            return win
    return default


def record(kernel, shape_key, value) -> None:
    """Store a winner in memory (+ disk when enabled)."""
    key = _key(kernel, shape_key)
    _MEM[key] = _freeze(value)
    if enabled():
        _store_disk(key, list(value) if isinstance(value, tuple)
                    else value)


def tune(kernel, shape_key, candidates, measure, default=None):
    """Winner for (device, kernel, shape): cached if known, else each
    candidate is timed with ``measure(candidate) -> seconds`` and the
    fastest is recorded.  A candidate whose measurement raises is
    skipped (e.g. a tile the shape can't take); if every candidate
    fails, ``default`` is returned uncached.
    """
    hit = lookup(kernel, shape_key, None)
    if hit is not None:
        return hit
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            t = measure(cand)
        except Exception:
            continue
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        return default
    record(kernel, shape_key, best)
    return best


# -- measurement helper -------------------------------------------------

def measure_thunk(fn, iters=8):
    """Per-iteration seconds for ``fn`` under the axon-tunnel rules
    (PERF.md): time ``iters`` and ``2*iters`` loops, force a host
    transfer after each (block_until_ready is a silent no-op over the
    tunnel), and difference the two so the fetch round-trip and
    dispatch overhead cancel."""
    fn()  # compile + warm

    def timed(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        jax.device_get(jax.tree_util.tree_leaves(out)[0])
        return time.perf_counter() - t0

    t1 = timed(iters)
    t2 = timed(2 * iters)
    return max(t2 - t1, 1e-9) / iters
