"""Long-tail tensor ops (VERDICT r2 row 3: the manipulation/math tail).

Reference: ``python/paddle/tensor/{math,manipulation,linalg,stat}.py`` —
each function below names its reference counterpart.  All dispatch
through the registry (jit cache + vjp-fallback grads); implementations
are single fused jnp programs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import apply, register_op


def _axis_t(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _simple(name, fn, static=()):
    op = register_op(name, fn, static_argnames=static)

    def call(*args, **kwargs):
        return apply(op, *args, **kwargs)

    call.__name__ = name
    return call


# -- math tail ----------------------------------------------------------

kron = _simple("kron", jnp.kron)
trace = _simple(
    "trace",
    lambda x, offset=0, axis1=0, axis2=1: jnp.trace(
        x, offset=offset, axis1=axis1, axis2=axis2),
    static=("offset", "axis1", "axis2"))
heaviside = _simple("heaviside", jnp.heaviside)
copysign = _simple("copysign", jnp.copysign)
ldexp = _simple("ldexp", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
hypot = _simple("hypot", jnp.hypot)
deg2rad = _simple("deg2rad", jnp.deg2rad)
rad2deg = _simple("rad2deg", jnp.rad2deg)
positive = _simple("positive", jnp.positive)
diff = _simple(
    "diff",
    lambda x, n=1, axis=-1: jnp.diff(x, n=n, axis=axis),
    static=("n", "axis"))
trapezoid = _simple(
    "trapezoid",
    lambda y, x=None, dx=1.0, axis=-1: jnp.trapezoid(
        y, x=x, dx=dx, axis=axis),
    static=("dx", "axis"))
vander = _simple(
    "vander",
    lambda x, n=None, increasing=False: jnp.vander(
        x, N=n, increasing=increasing),
    static=("n", "increasing"))
logcumsumexp = _simple(
    "logcumsumexp",
    lambda x, axis=-1: jax.lax.cumlogsumexp(x, axis=axis % x.ndim),
    static=("axis",))
renorm = _simple(
    "renorm",
    lambda x, p, axis, max_norm: _renorm_impl(x, p, axis, max_norm),
    static=("p", "axis", "max_norm"))


def _renorm_impl(x, p, axis, max_norm):
    """tensor/math.py renorm: scale each sub-tensor along ``axis`` whose
    p-norm exceeds max_norm down to max_norm."""
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * scale


def _cdist_impl(x, y, p):
    d = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == 2.0:
        return jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 0.0))
    if p == float("inf"):
        return jnp.max(d, -1)
    return jnp.sum(d ** p, -1) ** (1.0 / p)


cdist = _simple("cdist",
                lambda x, y, p=2.0: _cdist_impl(x, y, p),
                static=("p",))
_tensordot_op = register_op(
    "tensordot",
    lambda x, y, axes=2: jnp.tensordot(x, y, axes=axes),
    static_argnames=("axes",))


def tensordot(x, y, axes=2, name=None):
    # normalize the documented list/nested-list forms to hashable tuples
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(int(i) for i in a)
                     if isinstance(a, (list, tuple)) else int(a)
                     for a in axes)
    else:
        axes = int(axes)
    return apply(_tensordot_op, x, y, axes=axes)


# -- search / stat tail -------------------------------------------------

bucketize = _simple(
    "bucketize",
    lambda x, sorted_sequence, out_int32=False, right=False:
        jnp.searchsorted(sorted_sequence, x,
                         side="right" if right else "left").astype(
            jnp.int32 if out_int32 else jnp.int64),
    static=("out_int32", "right"))
searchsorted = _simple(
    "searchsorted",
    lambda sorted_sequence, values, out_int32=False, right=False:
        jnp.searchsorted(sorted_sequence, values,
                         side="right" if right else "left").astype(
            jnp.int32 if out_int32 else jnp.int64),
    static=("out_int32", "right"))


def _nanmedian_impl(x, axis, keepdim):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


nanmedian = _simple(
    "nanmedian",
    lambda x, axis=None, keepdim=False: _nanmedian_impl(x, axis, keepdim),
    static=("axis", "keepdim"))

_mode_op = register_op(
    "mode",
    lambda x, axis: _mode_impl(x, axis),
    static_argnames=("axis",), n_outputs=2)


def _mode_impl(x, axis):
    """tensor/search.py mode: most frequent value (ties -> largest
    value, matching the reference's last-index convention on sorted
    data) + its index."""
    xm = jnp.moveaxis(x, axis, -1)
    n = xm.shape[-1]
    s = jnp.sort(xm, axis=-1)
    # run lengths in sorted order
    eq = jnp.concatenate(
        [jnp.ones(s.shape[:-1] + (1,), bool), s[..., 1:] == s[..., :-1]],
        axis=-1)
    run_id = jnp.cumsum(~eq, axis=-1)

    def counts_1d(rid):
        return jax.ops.segment_sum(jnp.ones_like(rid), rid,
                                   num_segments=n)

    flat = run_id.reshape(-1, n)
    cnt = jax.vmap(counts_1d)(flat)          # [B, n] counts per run id
    run_cnt = jnp.take_along_axis(cnt, flat, axis=1).reshape(run_id.shape)
    best = jnp.argmax(run_cnt + run_id * 1e-6, axis=-1)  # ties -> larger
    vals = jnp.take_along_axis(s, best[..., None], axis=-1)[..., 0]
    idx = jnp.argmax(xm == vals[..., None], axis=-1)
    return vals, idx.astype(jnp.int64)


def mode(x, axis=-1, keepdim=False, name=None):
    vals, idx = apply(_mode_op, x, axis=int(axis))
    if keepdim:
        from .manipulation import unsqueeze

        return unsqueeze(vals, axis), unsqueeze(idx, axis)
    return vals, idx


def _kthvalue_impl(x, k, axis):
    xm = jnp.moveaxis(x, axis, -1)
    return (jnp.sort(xm, axis=-1)[..., k - 1],
            jnp.argsort(xm, axis=-1)[..., k - 1].astype(jnp.int64))


_kthvalue_op = register_op(
    "kthvalue", _kthvalue_impl, static_argnames=("k", "axis"),
    n_outputs=2)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    vals, idx = apply(_kthvalue_op, x, k=int(k), axis=int(axis))
    if keepdim:
        from .manipulation import unsqueeze

        return unsqueeze(vals, axis), unsqueeze(idx, axis)
    return vals, idx


# -- manipulation tail --------------------------------------------------

_rot90_op = register_op(
    "rot90", lambda x, k=1, axes=(0, 1): jnp.rot90(x, k=k, axes=axes),
    static_argnames=("k", "axes"))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(_rot90_op, x, k=int(k),
                 axes=tuple(int(a) for a in axes))


def _take_impl(x, index, mode="raise"):
    flat = x.reshape(-1)
    if mode == "raise":
        # negatives index from the end (python convention) — normalize
        # BEFORE clipping or clip would send them to element 0.  In
        # explicit 'clip' mode the reference clips negatives to 0, so
        # no normalization there.
        index = jnp.where(index < 0, index + flat.shape[0], index)
    return jnp.take(flat, index,
                    mode="clip" if mode == "raise" else mode)


_take_op = register_op("take", _take_impl, static_argnames=("mode",))


def take(x, index, mode="raise", name=None):
    """tensor/math.py take.  mode='raise' checks bounds eagerly when the
    index is concrete; under tracing it degrades to 'clip' (XLA cannot
    raise data-dependently — documented divergence)."""
    if mode == "raise":
        import numpy as _np

        idx_data = getattr(index, "_data", index)
        if not isinstance(idx_data, jax.core.Tracer):
            size = 1
            for d in jnp.shape(getattr(x, "_data", x)):
                size *= d
            arr = _np.asarray(idx_data)
            if arr.size and (arr.min() < -size or arr.max() >= size):
                raise IndexError(
                    f"take: index out of range for tensor of {size} "
                    f"elements (got [{arr.min()}, {arr.max()}])")
    return apply(_take_op, x, index, mode=str(mode))
# Positional order matches the reference signatures
# index_add(x, index, axis, value) / index_fill(x, index, axis, value)
# (python/paddle/tensor/manipulation.py) — ADVICE r3.
index_add = _simple(
    "index_add",
    lambda x, index, axis, value: _index_put(x, index, value, axis,
                                             add=True),
    static=("axis",))
index_fill = _simple(
    "index_fill",
    lambda x, index, axis, value: _index_fill_impl(
        x, index, value, axis),
    static=("axis",))


def _index_put(x, index, value, axis, add):
    xm = jnp.moveaxis(x, axis, 0)
    vm = jnp.moveaxis(value, axis, 0)
    out = xm.at[index].add(vm) if add else xm.at[index].set(vm)
    return jnp.moveaxis(out, 0, axis)


def _index_fill_impl(x, index, fill_value, axis):
    xm = jnp.moveaxis(x, axis, 0)
    out = xm.at[index].set(jnp.asarray(fill_value, x.dtype))
    return jnp.moveaxis(out, 0, axis)


def _unfold_impl(x, axis, size, step):
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None, :]
    xm = jnp.moveaxis(x, axis, 0)
    seg = xm[idx]                       # [n, size, ...rest]
    seg = jnp.moveaxis(seg, (0, 1), (axis, x.ndim))
    return seg


_unfold_op = register_op(
    "tensor_unfold",
    lambda x, axis, size, step: _unfold_impl(x, axis, size, step),
    static_argnames=("axis", "size", "step"))


def unfold(x, axis, size, step, name=None):
    return apply(_unfold_op, x, axis=int(axis), size=int(size),
                 step=int(step))


_as_strided_op = register_op(
    "as_strided",
    lambda x, shape, stride, offset=0: _as_strided_impl(
        x, shape, stride, offset),
    static_argnames=("shape", "stride", "offset"))


def as_strided(x, shape, stride, offset=0, name=None):
    return apply(_as_strided_op, x, shape=tuple(int(s) for s in shape),
                 stride=tuple(int(s) for s in stride),
                 offset=int(offset))


def _as_strided_impl(x, shape, stride, offset):
    flat = x.reshape(-1)
    idx = jnp.full((), offset, jnp.int32)
    for dim, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(dim) * st
    return flat[idx.reshape(tuple(shape))]


_select_scatter_op = register_op(
    "select_scatter",
    lambda x, value, axis, index: jnp.moveaxis(
        jnp.moveaxis(x, axis, 0).at[index].set(value), 0, axis),
    static_argnames=("axis", "index"))


def select_scatter(x, value, axis, index, name=None):
    return apply(_select_scatter_op, x, value, axis=int(axis),
                 index=int(index))


_slice_scatter_op = register_op(
    "slice_scatter",
    lambda x, value, axes, starts, ends, strides: _slice_scatter_impl(
        x, value, axes, starts, ends, strides),
    static_argnames=("axes", "starts", "ends", "strides"))


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    return apply(_slice_scatter_op, x, value,
                 axes=tuple(int(a) for a in axes),
                 starts=tuple(int(s) for s in starts),
                 ends=tuple(int(e) for e in ends),
                 strides=tuple(int(s) for s in strides))


def _slice_scatter_impl(x, value, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x.at[tuple(idx)].set(value)


# -- stack / split family (python-level compositions) -------------------


def _t(x):
    from ..core.tensor import Tensor

    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def atleast_1d(*inputs):
    from .manipulation import reshape

    outs = [x if x.ndim >= 1 else reshape(x, [1]) for x in
            map(_t, inputs)]
    return outs if len(outs) > 1 else outs[0]


def atleast_2d(*inputs):
    from .manipulation import reshape

    outs = []
    for x in map(_t, inputs):
        if x.ndim == 0:
            outs.append(reshape(x, [1, 1]))
        elif x.ndim == 1:
            outs.append(reshape(x, [1, x.shape[0]]))
        else:
            outs.append(x)
    return outs if len(outs) > 1 else outs[0]


def atleast_3d(*inputs):
    from .manipulation import reshape

    outs = []
    for x in map(_t, inputs):
        if x.ndim == 0:
            outs.append(reshape(x, [1, 1, 1]))
        elif x.ndim == 1:
            outs.append(reshape(x, [1, x.shape[0], 1]))
        elif x.ndim == 2:
            outs.append(reshape(x, list(x.shape) + [1]))
        else:
            outs.append(x)
    return outs if len(outs) > 1 else outs[0]


def column_stack(x, name=None):
    from .manipulation import concat

    return concat([_col2d(c) for c in map(_t, x)], axis=1)


def _col2d(c):
    from .manipulation import reshape

    c = _t(c)
    return reshape(c, [c.shape[0], 1]) if c.ndim == 1 else c


def row_stack(x, name=None):
    from .manipulation import concat

    return concat([atleast_2d(c) for c in map(_t, x)], axis=0)


def dstack(x, name=None):
    from .manipulation import concat

    return concat([atleast_3d(c) for c in map(_t, x)], axis=2)


def tensor_split(x, num_or_indices, axis=0, name=None):
    from .manipulation import slice as _slice

    x = _t(x)
    axis = int(axis) % x.ndim
    n = x.shape[axis]
    if isinstance(num_or_indices, int):
        k = num_or_indices
        base, extra = divmod(n, k)
        sizes = [base + (1 if i < extra else 0) for i in range(k)]
        bounds = [0]
        for s in sizes:
            bounds.append(bounds[-1] + s)
    else:
        bounds = [0] + [int(i) for i in num_or_indices] + [n]
    return [_slice(x, [axis], [bounds[i]], [bounds[i + 1]])
            for i in range(len(bounds) - 1)]


def hsplit(x, num_or_indices, name=None):
    x = _t(x)
    return tensor_split(x, num_or_indices, axis=0 if x.ndim == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


_diagflat_op = register_op(
    "diagflat",
    lambda x, offset=0: jnp.diagflat(x, k=offset),
    static_argnames=("offset",))


def diagflat(x, offset=0, name=None):
    return apply(_diagflat_op, x, offset=int(offset))


def _index_put_impl(x, value, *indices, accumulate):
    idx = tuple(indices)
    if len(idx) == 1 and idx[0].dtype == jnp.bool_:
        # boolean-mask form: x[mask] = value.  Scalar values broadcast
        # over the mask; vector values assign value[i] to the i-th True
        # position (the reference kernel's contract).  The vector length
        # is static (an input shape) even though the True count is not.
        mask = idx[0]
        suffix = x.shape[mask.ndim:]
        if value.ndim > len(suffix) and value.shape[0] == 1:
            # length-1 leading dim broadcasts over every masked element
            # (reference semantics), not "first True position only"
            value = value.reshape(value.shape[1:])
        if value.ndim <= len(suffix):  # scalar-per-masked-element
            vb = jnp.broadcast_to(value, mask.shape + suffix)
            m = mask.reshape(mask.shape + (1,) * len(suffix))
            return jnp.where(m, x + vb if accumulate else vb, x)
        k = int(value.shape[0])
        flat_idx = jnp.nonzero(mask.reshape(-1), size=k,
                               fill_value=mask.size)[0]
        xf = x.reshape((-1,) + suffix)
        out = xf.at[flat_idx].add(value, mode="drop") if accumulate \
            else xf.at[flat_idx].set(value, mode="drop")
        return out.reshape(x.shape)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


_index_put_op = register_op(
    "index_put",
    lambda x, value, *indices, accumulate=False: _index_put_impl(
        x, value, *indices, accumulate=accumulate),
    static_argnames=("accumulate",))


def index_put(x, indices, value, accumulate=False, name=None):
    """x[indices] = value (functional).  Reference:
    python/paddle/tensor/manipulation.py:6610 (index_put_), :6659."""
    indices = tuple(indices)
    return apply(_index_put_op, x, value, *indices,
                 accumulate=bool(accumulate))


def index_put_(x, indices, value, accumulate=False, name=None):
    out = index_put(x, indices, value, accumulate)
    x.set_value(out)
    return x
