"""paddle.metric analog — reference: python/paddle/metric/metrics.py."""
from __future__ import annotations

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        from ..core.tensor import Tensor

        p = np.asarray(pred._data if isinstance(pred, Tensor) else pred)
        l = np.asarray(label._data if isinstance(label, Tensor) else label)
        if l.ndim == p.ndim:
            l = l.squeeze(-1)
        top = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = top == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        from ..core.tensor import Tensor

        c = np.asarray(correct._data if isinstance(correct, Tensor)
                       else correct)
        n = c.shape[0]
        res = []
        for i, k in enumerate(self.topk):
            acc = c[..., :k].sum() / n
            self.total[i] += c[..., :k].sum()
            self.count[i] += n
            res.append(acc)
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name=None):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds).round().astype(np.int32).ravel()
        l = np.asarray(labels).astype(np.int32).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds).round().astype(np.int32).ravel()
        l = np.asarray(labels).astype(np.int32).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(labels).ravel()
        idx = (p * self.num_thresholds).astype(np.int64)
        idx = np.clip(idx, 0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..core.tensor import Tensor

    m = Accuracy(topk=(k,))
    c = m.compute(input, label)
    acc = m.update(c)
    return Tensor(np.float32(acc))
