"""Small top-level API conveniences (reference python/paddle/framework/
+ tensor/attribute.py): iinfo/finfo, is_tensor/is_complex/
is_floating_point, rank, broadcast_tensors, version."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor


class _DtypeInfo:
    def __init__(self, info, bits):
        self.min = info.min
        self.max = info.max
        self.bits = bits
        self.dtype = str(np.dtype(info.dtype)) if hasattr(info, "dtype") \
            else None
        if hasattr(info, "eps"):
            self.eps = float(info.eps)
            self.tiny = float(info.tiny)
            self.smallest_normal = float(info.tiny)
            self.resolution = float(info.resolution)


def iinfo(dtype):
    from .core.dtype import convert_dtype

    d = np.dtype(str(convert_dtype(dtype)))
    return _DtypeInfo(np.iinfo(d), d.itemsize * 8)


def finfo(dtype):
    from .core.dtype import convert_dtype

    d = convert_dtype(dtype)
    if str(d) == "bfloat16":
        info = jnp.finfo(jnp.bfloat16)
        out = _DtypeInfo.__new__(_DtypeInfo)
        out.min = float(info.min)
        out.max = float(info.max)
        out.bits = 16
        out.eps = float(info.eps)
        out.tiny = float(info.tiny)
        out.smallest_normal = float(info.tiny)
        out.resolution = float(info.resolution)
        out.dtype = "bfloat16"
        return out
    d = np.dtype(str(d))
    return _DtypeInfo(np.finfo(d), d.itemsize * 8)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.floating)


def rank(x):
    return Tensor(jnp.asarray(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).ndim))


def broadcast_tensors(inputs, name=None):
    datas = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
             for x in inputs]
    shape = jnp.broadcast_shapes(*[d.shape for d in datas])
    return [Tensor(jnp.broadcast_to(d, shape)) for d in datas]
