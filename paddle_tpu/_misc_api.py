"""Small top-level API conveniences (reference python/paddle/framework/
+ tensor/attribute.py): iinfo/finfo, is_tensor/is_complex/
is_floating_point, rank, broadcast_tensors, version."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor


class _DtypeInfo:
    def __init__(self, info, bits):
        self.min = info.min
        self.max = info.max
        self.bits = bits
        self.dtype = str(np.dtype(info.dtype)) if hasattr(info, "dtype") \
            else None
        if hasattr(info, "eps"):
            self.eps = float(info.eps)
            self.tiny = float(info.tiny)
            self.smallest_normal = float(info.tiny)
            self.resolution = float(info.resolution)


def iinfo(dtype):
    from .core.dtype import convert_dtype

    d = np.dtype(str(convert_dtype(dtype)))
    return _DtypeInfo(np.iinfo(d), d.itemsize * 8)


def finfo(dtype):
    from .core.dtype import convert_dtype

    d = convert_dtype(dtype)
    if str(d) == "bfloat16":
        info = jnp.finfo(jnp.bfloat16)
        out = _DtypeInfo.__new__(_DtypeInfo)
        out.min = float(info.min)
        out.max = float(info.max)
        out.bits = 16
        out.eps = float(info.eps)
        out.tiny = float(info.tiny)
        out.smallest_normal = float(info.tiny)
        out.resolution = float(info.resolution)
        out.dtype = "bfloat16"
        return out
    d = np.dtype(str(d))
    return _DtypeInfo(np.finfo(d), d.itemsize * 8)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.floating)


def rank(x):
    return Tensor(jnp.asarray(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).ndim))


def broadcast_tensors(inputs, name=None):
    datas = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
             for x in inputs]
    shape = jnp.broadcast_shapes(*[d.shape for d in datas])
    return [Tensor(jnp.broadcast_to(d, shape)) for d in datas]


# -- round-4 top-level tail (closing the reference __all__ gap) -------------

def tolist(x):
    """reference tensor.tolist."""
    import numpy as _np

    return _np.asarray(getattr(x, "_data", x)).tolist()


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference tensor/creation.create_parameter: a free-standing
    trainable Tensor (parameter outside a Layer)."""
    import numpy as _np

    import jax.numpy as _jnp

    from .core import dtype as _dt
    from .core.tensor import Tensor

    dt = _dt.convert_dtype(dtype)
    if default_initializer is not None:
        t = Tensor(_jnp.zeros(tuple(int(s) for s in shape), dt))
        default_initializer(t)
    else:
        fan_in = int(_np.prod(shape[:-1])) if len(shape) > 1 else 1
        bound = float(_np.sqrt(6.0 / max(fan_in + int(shape[-1]), 1))) \
            if not is_bias else 0.0
        from .ops.random import default_generator

        import jax as _jax

        if bound > 0:
            val = _jax.random.uniform(
                default_generator.next_key(),
                tuple(int(s) for s in shape), _jnp.float32,
                -bound, bound).astype(dt)
        else:
            val = _jnp.zeros(tuple(int(s) for s in shape), dt)
        t = Tensor(val)
    t.stop_gradient = False
    return t


def batch(reader, batch_size, drop_last=False):
    """reference paddle.batch: wrap a sample reader into a batch
    reader."""
    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


class LazyGuard:
    """reference paddle.LazyGuard: delay parameter materialization
    inside the guard.  Layers here already initialize lazily per-call
    cost-free (jax arrays are cheap until used), so the guard is a
    scoping no-op kept for API compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def disable_signal_handler():
    """reference paddle.disable_signal_handler: the C++ runtime's
    signal interception doesn't exist here — nothing to disable."""


def check_shape(shape):
    """reference paddle.check_shape (shape sanity for static ops)."""
    if shape is None:
        raise ValueError("shape must not be None")
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        if not isinstance(s, int) and s is not None:
            raise TypeError(f"shape entries must be int, got {type(s)}")
    return True


def get_cuda_rng_state():
    """CUDA-compat alias of the device RNG state (reference
    get_cuda_rng_state; one key stream serves all devices here)."""
    from .ops.random import get_rng_state

    return get_rng_state()


def set_cuda_rng_state(state):
    from .ops.random import set_rng_state

    set_rng_state(state)
