"""paddle.summary — layer-by-layer model summary.

Reference: ``python/paddle/hapi/model_summary.py`` — prints a table of
(layer, output shape, params) via forward hooks on a dry run and
returns {'total_params': N, 'trainable_params': N}.
"""
from __future__ import annotations

import numpy as np

from ..nn.layers import Layer


def summary(net, input_size=None, dtypes=None, input=None):
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from .. import autograd

    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = [input_size] if isinstance(input_size[0], int) \
            else list(input_size)
        dts = dtypes or ["float32"] * len(sizes)
        input = [Tensor(jnp.zeros([d if d and d > 0 else 1
                                   for d in s], dt))
                 for s, dt in zip(sizes, dts)]
    elif not isinstance(input, (list, tuple)):
        input = [input]

    rows = []
    handles = []

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) \
                else outputs
            shape = list(out.shape) if hasattr(out, "shape") else []
            n_params = int(sum(np.prod(p.shape)
                               for p in lyr.parameters(
                                   include_sublayers=False)))
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}",
                         name, shape, n_params))

        return hook

    for name, layer in net.named_sublayers():
        if isinstance(layer, Layer):
            handles.append(layer.register_forward_post_hook(
                make_hook(name, layer)))

    was_training = net.training
    net.eval()
    try:
        with autograd.no_grad():
            net(*input)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total = int(sum(np.prod(p.shape) for _, p in net.named_parameters()))
    trainable = int(sum(np.prod(p.shape)
                        for _, p in net.named_parameters()
                        if not p.stop_gradient))

    w_name = max([len(r[0]) for r in rows] + [12])
    w_shape = max([len(str(r[2])) for r in rows] + [14])
    line = "-" * (w_name + w_shape + 30)
    print(line)
    print(f"{'Layer (type)':<{w_name}}  {'Output Shape':<{w_shape}}  "
          f"{'Param #':>12}")
    print("=" * (w_name + w_shape + 30))
    for label, _, shape, n in rows:
        print(f"{label:<{w_name}}  {str(shape):<{w_shape}}  {n:>12,}")
    print("=" * (w_name + w_shape + 30))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
