"""hapi callbacks.

Reference: ``python/paddle/hapi/callbacks.py`` — Callback base, ProgBarLogger,
ModelCheckpoint, EarlyStopping, LRScheduler callback, VisualDL.
"""
from __future__ import annotations

import os
import sys
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in logs.items())
            total = self.steps or "?"
            print(f"step {step + 1}/{total} - {items}", file=sys.stdout)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"Epoch {epoch + 1} done in {dt:.1f}s: "
                  + " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in (logs or {}).items()))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def _save(self, path):
        from ..testing import faults

        faults.fire("hapi.save", "before", path=path)
        self.model.save(path)
        faults.fire("hapi.save", "after", path=path)

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self._save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self._save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode

    def on_eval_end(self, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            value = value[0]
        better = (self.best is None
                  or (self.mode == "min" and value < self.best - self.min_delta)
                  or (self.mode == "max" and value > self.best + self.min_delta))
        if better:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()
