"""FLOPs counting by forward hooks.

Reference: ``python/paddle/hapi/dynamic_flops.py`` — per-layer-type
count functions registered as forward post-hooks, summed over a dry
run.  Convention (matching the reference): one multiply-add = 2 FLOPs is
NOT used — the reference counts MACs-style "flops" per its table
(conv: Cin/g * K * K * out_numel, linear: in*out, ...); we reproduce
that so numbers are comparable.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor


def _numel(t):
    return int(np.prod(t.shape)) if hasattr(t, "shape") else 0


def _count_conv2d(layer, x, y):
    cin = layer.weight.shape[1]  # [out, in/g, kh, kw]
    kh, kw = layer.weight.shape[2], layer.weight.shape[3]
    out_numel = _numel(y)
    fl = cin * kh * kw * out_numel
    if getattr(layer, "bias", None) is not None:
        fl += out_numel
    return fl


def _count_linear(layer, x, y):
    in_f, out_f = layer.weight.shape[0], layer.weight.shape[1]
    batch = _numel(y) // max(out_f, 1)
    fl = batch * in_f * out_f
    if getattr(layer, "bias", None) is not None:
        fl += _numel(y)
    return fl


def _count_norm(layer, x, y):
    return 2 * _numel(y)


def _count_act(layer, x, y):
    return _numel(y)


def _count_pool(layer, x, y):
    return _numel(y)


_DEFAULT = []


def _default_table():
    global _DEFAULT
    if _DEFAULT:
        return _DEFAULT
    table = [
        (nn.Conv2D, _count_conv2d),
        (nn.Linear, _count_linear),
        (nn.BatchNorm2D, _count_norm),
        (nn.LayerNorm, _count_norm),
        (nn.ReLU, _count_act),
        (nn.GELU, _count_act),
        (nn.Sigmoid, _count_act),
        (nn.MaxPool2D, _count_pool),
        (nn.AvgPool2D, _count_pool),
    ]
    for name in ("BatchNorm1D", "BatchNorm", "RMSNorm", "Tanh",
                 "Softmax", "AdaptiveAvgPool2D"):
        cls = getattr(nn, name, None)
        if cls is not None:
            fn = _count_norm if "Norm" in name else (
                _count_pool if "Pool" in name else _count_act)
            table.append((cls, fn))
    _DEFAULT = table
    return table


def dynamic_flops(net, input_size, custom_ops=None, print_detail=False):
    custom_ops = custom_ops or {}
    table = list(custom_ops.items()) + _default_table()
    total = [0]
    rows = []
    handles = []

    def make_hook(layer, fn):
        def hook(lyr, inputs, output):
            fl = int(fn(lyr, inputs, output))
            total[0] += fl
            rows.append((type(lyr).__name__, fl))

        return hook

    def attach(layer):
        for child in layer._sub_layers.values():
            attach(child)
        for cls, fn in table:
            if type(layer) is cls:
                handles.append(layer.register_forward_post_hook(
                    make_hook(layer, fn)))
                break

    attach(net)
    training = net.training
    try:
        import jax.numpy as jnp

        x = Tensor(jnp.zeros(tuple(input_size), jnp.float32))
        net.eval()
        from ..autograd import engine as _engine

        with _engine.no_grad():
            net(x)
    finally:
        # Restore mode even when the dry-run forward raises — leaving
        # the model in eval() would silently freeze BN/Dropout for the
        # caller's subsequent training steps.
        if training:
            net.train()
        for h in handles:
            h.remove()

    if print_detail:
        for name, fl in rows:
            print(f"{name:>20}: {fl:,}")
        print(f"{'Total':>20}: {total[0]:,}")
    return total[0]
