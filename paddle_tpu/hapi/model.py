"""High-level Model API (Keras-style fit/evaluate/predict).

Reference: ``python/paddle/hapi/model.py:1082`` (Model), ``:2010`` (fit),
``:2264`` (evaluate), ``:2394`` (predict).  The reference dispatches to a
DynamicGraphAdapter/StaticGraphAdapter pair; here there is one dygraph
train/eval path over the jax-backed eager engine, with AMP via
``paddle.amp`` and metrics via ``paddle.metric``.
"""
from __future__ import annotations

import math
import os

import numpy as np

from .. import obs
from ..core.tensor import Tensor
from ..metric import Metric
from ..nn.layers import Layer
from .callbacks import Callback, CallbackList, ModelCheckpoint, ProgBarLogger


def _timed_batches(loader, timer=None):
    """Iterate ``loader``, timing each ``next()`` under a
    ``train.data_wait`` span when telemetry is on — input starvation
    becomes visible as wide data-wait slices in the trace.  ``timer``
    (an ``obs.perf.StepTimer``) additionally accumulates the wait into
    the step's ``data_wait`` phase."""
    it = iter(loader)
    while True:
        h = obs.handle()
        try:
            ph = (timer.phase("data_wait") if timer is not None
                  else obs.NULL_SPAN)
            with ph:
                if h is not None:
                    with h.tracer.span("train.data_wait", cat="train"):
                        batch = next(it)
                else:
                    batch = next(it)
        except StopIteration:
            return
        yield batch


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


class Model:
    """Network wrapper with training/inference loops.

    model = paddle.Model(network)
    model.prepare(optimizer, loss, metrics)
    model.fit(train_dataset, eval_dataset, epochs=2, batch_size=32)
    """

    def __init__(self, network, inputs=None, labels=None):
        if not isinstance(network, Layer):
            raise TypeError("Model expects a paddle.nn.Layer, got "
                            f"{type(network)}")
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._scaler = None
        self._amp_level = "O0"
        self._amp_dtype = "bfloat16"
        self.stop_training = False

    # -- setup -------------------------------------------------------------

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer)
                                     or callable(loss)):
            raise TypeError("loss must be a Layer or a callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            self._amp_level = amp_configs.get("level", "O1")
            self._amp_dtype = amp_configs.get("dtype", "bfloat16")
            if self._amp_level not in ("O0", "O1", "O2"):
                raise ValueError(f"bad amp level {self._amp_level}")
            if self._amp_level != "O0":
                from .. import amp

                use_scaler = amp_configs.get(
                    "use_loss_scaling", self._amp_dtype == "float16")
                self._scaler = amp.GradScaler(enable=use_scaler)
                if self._amp_level == "O2":
                    amp.decorate(self.network, level="O2",
                                 dtype=self._amp_dtype)

    # -- single-batch paths (reference model.py train_batch/eval_batch) ----

    def _forward(self, inputs):
        return self.network(*inputs)

    def _compute_loss(self, outputs, labels):
        outs, labs = _to_list(outputs), _to_list(labels)
        if isinstance(self._loss, Layer) or callable(self._loss):
            return self._loss(*(outs + labs))
        raise RuntimeError("loss not set; call prepare(loss=...)")

    def train_batch(self, inputs, labels=None, update=True):
        if self._optimizer is None:
            raise RuntimeError("optimizer not set; call prepare() first")
        self.network.train()
        inputs = [_to_tensor(t) for t in _to_list(inputs)]
        labels = [_to_tensor(t) for t in _to_list(labels)]

        if self._amp_level != "O0":
            from .. import amp

            with amp.auto_cast(level=self._amp_level,
                               dtype=self._amp_dtype):
                outputs = self._forward(inputs)
                loss = self._compute_loss(outputs, labels)
        else:
            outputs = self._forward(inputs)
            loss = self._compute_loss(outputs, labels)

        scaled = self._scaler.scale(loss) if self._scaler else loss
        scaled.backward()
        if update:
            self._apply_update()

        metrics = self._update_metrics(outputs, labels)
        return (float(np.asarray(loss.numpy())), metrics)

    def _apply_update(self, found_inf=False):
        """Apply (or, with ``found_inf``, skip with GradScaler found_inf
        semantics) the pending optimizer update and clear grads."""
        if self._scaler:
            if found_inf:
                self._scaler.mark_found_inf()
            self._scaler.step(self._optimizer)
            self._scaler.update()
        elif not found_inf:
            self._optimizer.step()
        self._optimizer.clear_grad()

    def _global_grad_norm(self):
        """Global L2 norm over all parameter grads (guardian monitor;
        eager path — the loop is host-synchronous anyway)."""
        tot = 0.0
        for p in self._optimizer._parameter_list():
            if p.grad is not None:
                g = np.asarray(p.grad._data, np.float64)
                tot += float((g * g).sum())
        return float(np.sqrt(tot))

    def _guarded_train_batch(self, guardian, inputs, labels):
        """One fit-loop step under the training guardian: forward +
        backward, poll the guard.* value-fault points, classify, then
        apply / skip (found_inf semantics) / roll back per the
        escalation policy."""
        from ..testing import faults
        from ..training.guardian import Decision

        loss, metrics = self.train_batch(inputs, labels, update=False)
        if faults.poll("guard.nan_loss") is not None:
            loss = float("nan")
        else:
            spike = faults.poll("guard.loss_spike")
            if spike is not None:
                loss = loss + (1e6 if spike is True else float(spike))
        gnorm = None
        if guardian.policy.check_grad_norm:
            gnorm = self._global_grad_norm()
            if faults.poll("guard.nan_grad") is not None:
                gnorm = float("nan")
        decision = guardian.observe(loss, gnorm)
        if decision is Decision.OK:
            self._apply_update()
            guardian.maybe_commit(guardian.steps_seen)
        elif decision is Decision.SKIP:
            self._apply_update(found_inf=True)
        else:  # ROLLBACK — restore last committed state, drop grads
            guardian.rollback()
            self._optimizer.clear_grad()
        return loss, metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..autograd import engine as _engine

        inputs = [_to_tensor(t) for t in _to_list(inputs)]
        labels = [_to_tensor(t) for t in _to_list(labels)]
        with _engine.no_grad():
            outputs = self._forward(inputs)
            loss = (self._compute_loss(outputs, labels)
                    if self._loss is not None else None)
        metrics = self._update_metrics(outputs, labels)
        lv = float(np.asarray(loss.numpy())) if loss is not None else None
        return (lv, metrics)

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import engine as _engine

        inputs = [_to_tensor(t) for t in _to_list(inputs)]
        with _engine.no_grad():
            outputs = self._forward(inputs)
        return [np.asarray(o.numpy()) for o in _to_list(outputs)]

    def _update_metrics(self, outputs, labels):
        res = {}
        outs, labs = _to_list(outputs), _to_list(labels)
        for m in self._metrics:
            state = m.compute(*(outs + labs))
            m.update(*_to_list(state))
            res[m.name()] = m.accumulate()
        return res

    # -- loops --------------------------------------------------------------

    def _make_loader(self, data, batch_size, shuffle, num_workers,
                     drop_last):
        from ..io import DataLoader, Dataset

        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")

    def _split_batch(self, batch):
        """A loader batch is (input..., label...); with a loss configured the
        last element feeds the loss, otherwise everything is input."""
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if self._loss is None or len(batch) == 1:
            return batch, []
        n_lab = len(self._labels) if self._labels else 1
        return batch[:-n_lab], batch[-n_lab:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            guardian=None):
        """``guardian``: a ``paddle.training.TrainingGuardian`` (e.g.
        from ``training.guardian.guardian_for_model``) — each train
        step is then monitored (NaN/Inf loss, grad norm, loss spike)
        and anomalies escalate skip -> rollback-to-last-committed ->
        ``GuardianAbort`` per its policy."""
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        cbks = _to_list(callbacks)
        if not any(isinstance(c, ProgBarLogger) for c in cbks):
            cbks.append(ProgBarLogger(log_freq, verbose=verbose))
        if save_dir and not any(isinstance(c, ModelCheckpoint)
                                for c in cbks):
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbk = CallbackList(cbks)
        cbk.set_model(self)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbk.set_params({"epochs": epochs, "steps": steps,
                        "verbose": verbose,
                        "metrics": ["loss"] + [m.name()
                                               for m in self._metrics]})
        self.stop_training = False
        cbk.on_train_begin()
        if guardian is not None and guardian.manager is not None \
                and guardian.manager.latest_step() is None:
            # Rollback must always have a committed source.
            guardian.commit(0)
        logs = {}
        timer = obs.perf.StepTimer("train.step")
        # health plane: guardian-anomaly SLO + "train" heartbeat,
        # evaluated once per fit step when telemetry is on
        health_eng = None
        if obs.handle() is not None:
            from ..obs import health as _health

            health_eng = _health.SLOEngine(
                _health.default_train_slos(), source="train")
            obs.handle().statusz["train"] = \
                lambda: {"phase_seconds": timer.phase_seconds()}
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbk.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(_timed_batches(loader, timer)):
                cbk.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                h = obs.handle()
                sp = (h.tracer.span("train.fit_step", cat="train",
                                    epoch=epoch, step=step)
                      if h is not None else obs.NULL_SPAN)
                with sp:
                    with timer.phase("compute"):
                        if guardian is not None:
                            loss, metrics = self._guarded_train_batch(
                                guardian, ins, labs)
                        else:
                            loss, metrics = self.train_batch(ins, labs)
                    sp.set(loss=float(loss))
                logs = {"loss": loss, **metrics}
                # Callback flush (progress bars, metric sinks) is the
                # loop's own telemetry cost — the "obs" phase.
                with timer.phase("obs"):
                    cbk.on_train_batch_end(step, logs)
                timer.end_step()
                if health_eng is not None:
                    health_eng.evaluate(step=step)
                    obs.beat("train")
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                          log_freq=log_freq, verbose=0,
                                          num_workers=num_workers,
                                          callbacks=cbk)
                logs.update({f"eval_{k}" if not k.startswith("eval_")
                             else k: v for k, v in eval_logs.items()})
            # Epoch-boundary callbacks carry the ModelCheckpoint save.
            with timer.phase("checkpoint"):
                cbk.on_epoch_end(epoch, logs)
            timer.end_step()
        cbk.on_train_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False,
                                   num_workers, False)
        own_cbk = not isinstance(callbacks, CallbackList)
        if own_cbk:
            cbks = _to_list(callbacks)
            if verbose and not any(isinstance(c, ProgBarLogger)
                                   for c in cbks):
                cbks.append(ProgBarLogger(log_freq, verbose=verbose))
            cbk = CallbackList(cbks)
            cbk.set_model(self)
            cbk.set_params({"verbose": verbose,
                            "metrics": ["loss"] + [m.name()
                                                   for m in self._metrics]})
        else:
            cbk = callbacks
        for m in self._metrics:
            m.reset()
        cbk.on_eval_begin()
        logs, losses = {}, []
        for step, batch in enumerate(loader):
            cbk.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            loss, metrics = self.eval_batch(ins, labs)
            if loss is not None:
                losses.append(loss)
            logs = dict(metrics)
            if losses:
                logs["loss"] = float(np.mean(losses))
            cbk.on_eval_batch_end(step, logs)
        cbk.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False,
                                   num_workers, False)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outs = self.predict_batch(ins)
            outputs.append(outs if len(outs) > 1 else outs[0])
        if stack_outputs and outputs:
            if isinstance(outputs[0], list):
                outputs = [np.concatenate([o[i] for o in outputs])
                           for i in range(len(outputs[0]))]
            else:
                outputs = np.concatenate(outputs)
        return outputs

    # -- persistence ---------------------------------------------------------

    def save(self, path, training=True):
        """path is a prefix: writes <path>.pdparams (+ .pdopt when
        training=True), matching the reference's save layout."""
        from .. import framework_io

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework_io.save(self._optimizer.state_dict(),
                              path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework_io

        state = framework_io.load(path + ".pdparams")
        if skip_mismatch:
            own = self.network.state_dict()
            state = {k: v for k, v in state.items()
                     if k in own and list(np.asarray(v).shape)
                     == list(own[k].shape)}
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(framework_io.load(opt_path))

    # -- introspection -------------------------------------------------------

    def parameters(self, include_sublayers=True):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        """Parameter-count summary (reference hapi/model_summary.py)."""
        rows, total = [], 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            rows.append((name, list(p.shape), n))
        width = max((len(r[0]) for r in rows), default=10) + 2
        lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Params':>12}"]
        lines += [f"{n:<{width}}{str(s):<20}{c:>12,}" for n, s, c in rows]
        lines.append(f"Total params: {total:,}")
        out = "\n".join(lines)
        print(out)
        return {"total_params": total, "trainable_params": total}
