"""paddle.geometric — graph message passing + segment reductions.

Reference: ``python/paddle/geometric/`` — ``math.py`` (segment_sum:23,
segment_mean:80, segment_min:139, segment_max:197) and
``message_passing/send_recv.py`` (send_u_recv:36, send_ue_recv:186,
send_uv:389).

TPU-native: all of these are jax segment ops / gathers — XLA lowers
them to sorted-scatter kernels; everything dispatches through the op
registry so gradients flow to the node/edge features (the reference's
kernels are likewise differentiable w.r.t. x/y, not the index tensors).
``out_size`` (static) pins the output row count for jit-ability.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry

_op = _registry.cached_apply


def _out_size(out_size, x):
    """Destination-node count: an explicit out_size (0 is a valid empty
    graph) wins over the source-node count."""
    if out_size is not None:
        return int(out_size)
    if not hasattr(x, "shape"):
        raise ValueError("out_size is required when x has no .shape")
    return int(x.shape[0])


def _nseg(segment_ids, out_size=None):
    if out_size is not None:
        return int(out_size)
    ids = np.asarray(segment_ids._data if isinstance(segment_ids, Tensor)
                     else segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


def _reduce(gathered, dst, n, pool_type):
    """Single segment-reduce used by both the segment_* ops and the
    message-passing ops.  Empty segments yield 0 — detected via a
    segment count, so legitimate +/-inf data values survive min/max."""
    if pool_type == "sum":
        return jax.ops.segment_sum(gathered, dst, num_segments=n)
    # Count in fp32 (exact for any realistic segment size), but keep the
    # result in the data's dtype so bf16/fp16 pipelines stay low-precision.
    cnt = jax.ops.segment_sum(
        jnp.ones(gathered.shape[:1], jnp.float32), dst, num_segments=n)
    cnt = cnt[(...,) + (None,) * (gathered.ndim - 1)]
    if pool_type == "mean":
        s = jax.ops.segment_sum(gathered, dst, num_segments=n)
        out = s.astype(jnp.float32) / jnp.maximum(cnt, 1.0)
        dt = gathered.dtype
        return out.astype(dt if jnp.issubdtype(dt, jnp.floating)
                          else jnp.float32)
    red = jax.ops.segment_max if pool_type == "max" else jax.ops.segment_min
    out = red(gathered, dst, num_segments=n)
    return jnp.where(cnt > 0, out, jnp.zeros_like(out))


def _segment_op(pool, data, segment_ids):
    n = _nseg(segment_ids)
    return _op(f"segment_{pool}",
               lambda d, i, n, pool: _reduce(d, i, n, pool),
               data, segment_ids, n=n, pool=pool)


def segment_sum(data, segment_ids, name=None):
    return _segment_op("sum", data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    return _segment_op("mean", data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment_op("min", data, segment_ids)


def segment_max(data, segment_ids, name=None):
    return _segment_op("max", data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] along edges, reduce at dst (send_recv.py:36)."""
    reduce_op = reduce_op.lower()
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    n = _out_size(out_size, x)

    def fn(x, src, dst, n, pool):
        return _reduce(x[src], dst, n, pool)

    return _op("send_u_recv", fn, x, src_index, dst_index, n=int(n),
               pool=reduce_op)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine x[src] with edge features y, reduce at dst
    (send_recv.py:186)."""
    message_op = message_op.lower()
    reduce_op = reduce_op.lower()
    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"unsupported message_op {message_op!r}")
    n = _out_size(out_size, x)

    def fn(x, y, src, dst, n, msg, pool):
        g = x[src]
        if msg == "add":
            g = g + y
        elif msg == "sub":
            g = g - y
        elif msg == "mul":
            g = g * y
        else:
            g = g / y
        return _reduce(g, dst, n, pool)

    return _op("send_ue_recv", fn, x, y, src_index, dst_index,
               n=int(n), msg=message_op, pool=reduce_op)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from x[src] and y[dst] (send_recv.py:389)."""
    message_op = message_op.lower()

    def fn(x, y, src, dst, msg):
        a, b = x[src], y[dst]
        if msg == "add":
            return a + b
        if msg == "sub":
            return a - b
        if msg == "mul":
            return a * b
        return a / b

    return _op("send_uv", fn, x, y, src_index, dst_index,
               msg=message_op)


# --- sampling + reindex (reference python/paddle/geometric/
# {sampling/neighbors.py:30,221, reindex.py:42}; incubate/operators/
# graph_{sample_neighbors,reindex,khop_sampler}.py re-export these).
# Host-side numpy: graph sampling is input-pipeline work, like the
# reference's CPU kernels. ------------------------------------------------

def _np1(t):
    import numpy as _n

    a = _n.asarray(t._data if hasattr(t, "_data") else t)
    return a.reshape(-1)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniformly sample up to ``sample_size`` in-neighbors per input
    node from a CSC graph (reference sampling/neighbors.py:30)."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp

    rowv = _np1(row)
    cp = _np1(colptr)
    nodes = _np1(input_nodes)
    ev = None if eids is None else _np1(eids)
    out_n, out_cnt, out_e = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        neigh = rowv[beg:end]
        eid = np.arange(beg, end)
        if sample_size != -1 and neigh.size > sample_size:
            pick = np.random.choice(neigh.size, sample_size,
                                    replace=False)
            neigh, eid = neigh[pick], eid[pick]
        out_n.append(neigh)
        out_e.append(eid if ev is None else ev[eid])
        out_cnt.append(neigh.size)
    out_neighbors = Tensor(_jnp.asarray(np.concatenate(out_n).astype(
        rowv.dtype) if out_n else np.zeros(0, rowv.dtype)))
    out_count = Tensor(_jnp.asarray(np.asarray(out_cnt, np.int32)))
    if return_eids:
        return out_neighbors, out_count, Tensor(_jnp.asarray(
            np.concatenate(out_e).astype(np.int64) if out_e
            else np.zeros(0, np.int64)))
    return out_neighbors, out_count


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-proportional sampling without replacement (reference
    sampling/neighbors.py:221)."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp

    rowv = _np1(row)
    cp = _np1(colptr)
    wts = _np1(edge_weight).astype(np.float64)
    nodes = _np1(input_nodes)
    ev = None if eids is None else _np1(eids)
    out_n, out_cnt, out_e = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        neigh = rowv[beg:end]
        eid = np.arange(beg, end)
        w = wts[beg:end]
        if sample_size != -1 and neigh.size > sample_size:
            p = w / w.sum()
            pick = np.random.choice(neigh.size, sample_size,
                                    replace=False, p=p)
            neigh, eid = neigh[pick], eid[pick]
        out_n.append(neigh)
        out_e.append(eid if ev is None else ev[eid])
        out_cnt.append(neigh.size)
    out_neighbors = Tensor(_jnp.asarray(np.concatenate(out_n).astype(
        rowv.dtype) if out_n else np.zeros(0, rowv.dtype)))
    out_count = Tensor(_jnp.asarray(np.asarray(out_cnt, np.int32)))
    if return_eids:
        return out_neighbors, out_count, Tensor(_jnp.asarray(
            np.concatenate(out_e).astype(np.int64) if out_e
            else np.zeros(0, np.int64)))
    return out_neighbors, out_count


def _reindex(x, neighbors, count):
    xv = _np1(x)
    nb = _np1(neighbors)
    cnt = _np1(count)
    mapping = {}
    out_nodes = []
    for n in xv.tolist():
        if n not in mapping:
            mapping[n] = len(out_nodes)
            out_nodes.append(n)
    for n in nb.tolist():
        if n not in mapping:
            mapping[n] = len(out_nodes)
            out_nodes.append(n)
    src = np.asarray([mapping[n] for n in nb.tolist()], np.int64)
    dst = np.repeat(np.arange(xv.size), cnt).astype(np.int64)
    return src, dst, np.asarray(out_nodes, xv.dtype)


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """Compact node ids to [0, n) with inputs first (reference
    geometric/reindex.py:42; example contract in its docstring)."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp

    src, dst, out_nodes = _reindex(x, neighbors, count)
    return (Tensor(_jnp.asarray(src)), Tensor(_jnp.asarray(dst)),
            Tensor(_jnp.asarray(out_nodes)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: per-edge-type neighbor/count lists share
    one id space (reference geometric/reindex.py:170)."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp

    xv = _np1(x)
    per_type = [(_np1(n), _np1(c)) for n, c in zip(neighbors, count)]
    mapping = {}
    out_nodes = []
    for n in xv.tolist():
        if n not in mapping:
            mapping[n] = len(out_nodes)
            out_nodes.append(n)
    srcs, dsts = [], []
    for nbt, cntt in per_type:
        for v in nbt.tolist():
            if v not in mapping:
                mapping[v] = len(out_nodes)
                out_nodes.append(v)
        srcs.append(np.asarray([mapping[v] for v in nbt.tolist()],
                               np.int64))
        dsts.append(np.repeat(np.arange(xv.size), cntt).astype(np.int64))
    from ..core.tensor import Tensor as _T

    return (_T(_jnp.asarray(np.concatenate(srcs))),
            _T(_jnp.asarray(np.concatenate(dsts))),
            _T(_jnp.asarray(np.asarray(out_nodes, xv.dtype))))
