"""Paged KV cache + decode attention for serving.

Reference: ``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``
(block/paged KV cache with a block table per sequence) and
``masked_multihead_attention`` (single-token decode attention against a
length-masked cache), the two kernels behind the reference Predictor's
continuous-batching serving path.

TPU-native: the page pool is a static [n_kv, num_pages, page_size, d]
array per layer (XLA-friendly fixed shape — page capacity plays the
role of the reference's pre-allocated block pool), the block table is a
host-side free-list (allocation is control plane, not compute), decode
attention runs the Pallas ``paged_attention`` TPU kernel over the page
pool (dense gather fallback off-TPU), and prefill writes whole pages
with one scatter.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry
from ..testing import faults as _faults

_op = _registry.cached_apply


def _on_tpu():
    return jax.default_backend() == "tpu"


# -- decode attention ops ----------------------------------------------


def masked_multihead_attention(q, k_cache, v_cache, lengths, name=None):
    """Single-token decode attention against a dense cache (reference
    masked_multihead_attention_kernel).

    q: [B, H, D]; k_cache/v_cache: [B, KV, T, D]; lengths: [B] valid
    token counts.  Returns [B, H, D].  Supports GQA (H % KV == 0).
    """

    def fn(q, kc, vc, lens):
        B, H, D = q.shape
        KV, T = kc.shape[1], kc.shape[2]
        g = H // KV
        qg = q.reshape(B, KV, g, D)
        logits = jnp.einsum("bkgd,bktd->bkgt", qg.astype(jnp.float32),
                            kc.astype(jnp.float32)) / np.sqrt(D)
        mask = jnp.arange(T)[None, None, None, :] < \
            lens[:, None, None, None]
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bkgt,bktd->bkgd", p, vc.astype(jnp.float32))
        return out.reshape(B, H, D).astype(q.dtype)

    wrap = isinstance(q, Tensor)
    out = _op("masked_multihead_attention", fn,
              q if wrap else Tensor(jnp.asarray(q)),
              Tensor(jnp.asarray(k_cache._data if isinstance(k_cache, Tensor)
                                 else k_cache)),
              Tensor(jnp.asarray(v_cache._data if isinstance(v_cache, Tensor)
                                 else v_cache)),
              Tensor(jnp.asarray(lengths._data if isinstance(lengths, Tensor)
                                 else lengths)))
    return out if wrap else out._data


def _dense_paged_attention(q, k_pages, v_pages, lengths, page_indices):
    """Reference semantics of the Pallas kernel, in plain XLA ops —
    the off-TPU fallback and the parity oracle for tests.

    q [B, H, D]; k/v_pages [KV, P, ps, D]; page_indices [B, pages_per_seq].
    """
    B, H, D = q.shape
    KV, _, ps, _ = k_pages.shape
    pages_per_seq = page_indices.shape[1]
    T = pages_per_seq * ps
    # gather each sequence's pages -> dense [B, KV, T, D]
    kc = jnp.swapaxes(k_pages[:, page_indices], 0, 1)  # [B, KV, pps, ps, D]
    vc = jnp.swapaxes(v_pages[:, page_indices], 0, 1)
    kc = kc.reshape(B, KV, T, D)
    vc = vc.reshape(B, KV, T, D)
    g = H // KV
    qg = q.reshape(B, KV, g, D)
    logits = jnp.einsum("bkgd,bktd->bkgt", qg.astype(jnp.float32),
                        kc.astype(jnp.float32)) / np.sqrt(D)
    mask = jnp.arange(T)[None, None, None, :] < \
        lengths[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", p, vc.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


def _dense_paged_attention_q(q, k_pages, v_pages, lengths, page_indices,
                             k_scales, v_scales):
    """Int8-page analog of ``_dense_paged_attention`` — dequantize the
    GATHERED window (never the whole pool) with the per-page scales,
    then the same f32 einsum/softmax/einsum.  The off-TPU fallback and
    the parity oracle for the quant kernel."""
    B, H, D = q.shape
    KV, _, ps, _ = k_pages.shape
    pages_per_seq = page_indices.shape[1]
    T = pages_per_seq * ps
    kc = jnp.swapaxes(k_pages[:, page_indices], 0, 1)  # [B, KV, pps, ps, D]
    vc = jnp.swapaxes(v_pages[:, page_indices], 0, 1)
    ksc = jnp.swapaxes(k_scales[:, page_indices], 0, 1)  # [B, KV, pps]
    vsc = jnp.swapaxes(v_scales[:, page_indices], 0, 1)
    kc = kc.astype(jnp.float32) * ksc[..., None, None]
    vc = vc.astype(jnp.float32) * vsc[..., None, None]
    kc = kc.reshape(B, KV, T, D)
    vc = vc.reshape(B, KV, T, D)
    g = H // KV
    qg = q.reshape(B, KV, g, D)
    logits = jnp.einsum("bkgd,bktd->bkgt", qg.astype(jnp.float32),
                        kc) / np.sqrt(D)
    mask = jnp.arange(T)[None, None, None, :] < \
        lengths[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", p, vc)
    return out.reshape(B, H, D).astype(q.dtype)


def _select_impl(head_dim, page_size):
    """Resolve the decode-attention implementation.

    ``PT_PAGED_IMPL`` ∈ {auto, pallas, stock, dense} forces a path
    (the A/B lever bench.py uses); ``auto`` prefers the self-authored
    fused kernel when its shape gate passes, then the stock flash-style
    kernel, then the dense jnp gather.  The gate is load-bearing: over
    the async tunnel a Mosaic lowering error surfaces as a compile
    HANG, not a raise, so an incompatible shape must never reach a
    compiled kernel."""
    import os

    from ..ops.pallas_kernels import paged_decode as _fused

    impl = os.environ.get("PT_PAGED_IMPL", "auto").lower()
    if impl not in ("auto", "pallas", "stock", "dense"):
        raise ValueError(
            f"PT_PAGED_IMPL={impl!r}: expected auto|pallas|stock|dense")
    if impl != "auto":
        return impl
    from ..ops import autotune as _autotune

    if _fused.supported(head_dim, page_size, _on_tpu()):
        # measured choice between the two compiled kernels, cached per
        # (device, shape); defaults to the fused kernel untuned
        return _autotune.lookup(
            "paged_decode_impl", (head_dim, page_size),
            default="pallas")
    if _on_tpu() and head_dim % 128 == 0:
        return "stock"
    return "dense"


def paged_decode_attention(q, k_pages, v_pages, lengths, page_indices,
                           pages_per_compute_block=4,
                           k_scales=None, v_scales=None):
    """Decode attention over the page pool.  On TPU this is the
    self-authored fused kernel (``ops/pallas_kernels/paged_decode.py``:
    per-sequence DMA page gather + whole decode attention in VMEM) or
    the stock flash-style ``paged_attention`` kernel; elsewhere the
    dense-gather fallback jit-cached through the op registry.  Routing
    is overridable via ``PT_PAGED_IMPL`` (see ``_select_impl``).
    Returns a Tensor iff ``q`` is a Tensor.

    ``k_scales``/``v_scales`` [KV, P] select the int8-page path
    (``PT_QUANT=int8``): the fused quant kernel when its (stricter)
    shape gate passes, else the dense dequantize-the-gather fallback —
    the stock kernel has no scale inlet, so quant never routes there.
    """
    wrap = isinstance(q, Tensor)
    q = q._data if wrap else jnp.asarray(q)
    lengths = jnp.asarray(lengths, jnp.int32)
    page_indices = jnp.asarray(page_indices, jnp.int32)

    if k_scales is not None:
        from ..ops.pallas_kernels import paged_decode as _fused

        impl = _select_impl(q.shape[-1], k_pages.shape[2])
        if impl == "pallas" and (
                _fused.supported_quant(q.shape[-1], k_pages.shape[2],
                                       _on_tpu())
                or not _on_tpu()):
            out = _fused.handle_quant()(
                Tensor(q), Tensor(jnp.asarray(k_pages)),
                Tensor(jnp.asarray(v_pages)), Tensor(lengths),
                Tensor(page_indices),
                Tensor(jnp.asarray(k_scales, jnp.float32)),
                Tensor(jnp.asarray(v_scales, jnp.float32)))
        else:
            out = _op("paged_decode_attention_q",
                      _dense_paged_attention_q,
                      Tensor(q), Tensor(jnp.asarray(k_pages)),
                      Tensor(jnp.asarray(v_pages)), Tensor(lengths),
                      Tensor(page_indices),
                      Tensor(jnp.asarray(k_scales, jnp.float32)),
                      Tensor(jnp.asarray(v_scales, jnp.float32)))
        return out if wrap else out._data

    impl = _select_impl(q.shape[-1], k_pages.shape[2])

    if impl == "pallas":
        from ..ops.pallas_kernels import paged_decode as _fused

        out = _fused.handle()(
            Tensor(q), Tensor(jnp.asarray(k_pages)),
            Tensor(jnp.asarray(v_pages)), Tensor(lengths),
            Tensor(page_indices))
        return out if wrap else out._data
    if impl == "dense":
        out = _op("paged_decode_attention", _dense_paged_attention,
                  Tensor(q), Tensor(jnp.asarray(k_pages)),
                  Tensor(jnp.asarray(v_pages)), Tensor(lengths),
                  Tensor(page_indices))
        return out if wrap else out._data
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention,
    )

    blk = min(pages_per_compute_block, page_indices.shape[1])
    while page_indices.shape[1] % blk:
        blk -= 1
    # The stock kernel mixes int32/int64 under global x64 mode — trace
    # it x64-off (same guard as the flash-attention wrappers).  It also
    # applies NO logits scaling: pre-scale q by 1/sqrt(D).
    q = q / np.sqrt(q.shape[-1])
    with jax.enable_x64(False):
        out = paged_attention(
            jnp.asarray(q), jnp.asarray(k_pages),
            jnp.asarray(v_pages), jnp.asarray(lengths, jnp.int32),
            jnp.asarray(page_indices, jnp.int32),
            pages_per_compute_block=blk)
    return Tensor(out) if wrap else out


# -- block-table cache manager ------------------------------------------


class PagedKVCache:
    """Block-table KV cache (reference block_multi_head_attention's
    pre-allocated block pool + per-sequence block table).

    The pools are [L, KV, num_pages, page_size, D] device arrays; page
    allocation is a host-side free list (control plane).  Sequences are
    dense slots 0..max_seqs-1 with a fixed-size page table row each —
    static shapes end-to-end, so every compute step is one cached XLA
    program.

    Pages are REFCOUNTED (prefix-cache sharing, r11): a page is either
    on the free list (refcount 0) or held by one or more owners — slot
    page-table rows and/or the radix prefix index.  A page with
    refcount > 1 is read-only; every in-place write path goes through
    :meth:`make_writable`, which copy-on-writes a shared page into a
    fresh exclusively-owned one.  ``free()`` decrements instead of
    returning pages to the pool, so shared prefix pages survive the
    sequences that used them.  With no prefix cache attached every
    refcount is 0/1 and the behavior is bit-identical to the r10 code.
    """

    def __init__(self, n_layers, n_kv_heads, head_dim, num_pages,
                 page_size=16, max_seqs=8, dtype=jnp.bfloat16,
                 max_pages_per_seq=None, quant=None):
        from ..ops import quant as _quant

        self.n_layers = n_layers
        self.page_size = page_size
        self.num_pages = num_pages
        # Per-seq budget decoupled from the pool size: a serving pool is
        # deliberately OVERSUBSCRIBED (num_pages < max_seqs * budget) so
        # admission pressure is real and preemption has something to do.
        self.max_pages_per_seq = (num_pages // max_seqs
                                  if max_pages_per_seq is None
                                  else int(max_pages_per_seq))
        self.max_seqs = max_seqs
        #: what consumers compute in — the pool storage dtype in the
        #: plain mode, the requested float dtype when the pool is int8.
        self.compute_dtype = dtype
        self.quant = _quant.quant_mode(quant)
        shape = (n_layers, n_kv_heads, num_pages, page_size, head_dim)
        if self.quant == "int8":
            # int8 pages + one f32 scale per (layer, kv-head, page),
            # kept alongside the page table: a page's scale moves,
            # copies, and frees with the page.
            self.k_pages = jnp.zeros(shape, jnp.int8)
            self.v_pages = jnp.zeros(shape, jnp.int8)
            self.k_scales = jnp.zeros((n_layers, n_kv_heads, num_pages),
                                      jnp.float32)
            self.v_scales = jnp.zeros((n_layers, n_kv_heads, num_pages),
                                      jnp.float32)
        else:
            self.k_pages = jnp.zeros(shape, dtype)
            self.v_pages = jnp.zeros(shape, dtype)
            self.k_scales = None
            self.v_scales = None
        self._free = list(range(num_pages - 1, -1, -1))
        # page table: [max_seqs, max_pages_per_seq] int32; -1 = unset
        # (page id 0 is valid, so 0 cannot double as the sentinel)
        self.page_table = np.full((max_seqs, self.max_pages_per_seq),
                                  -1, np.int32)
        self.lengths = np.zeros((max_seqs,), np.int32)
        self._active = [False] * max_seqs
        # per-page owner count: slots referencing it + the prefix index
        self.page_refs = np.zeros((num_pages,), np.int32)
        self.cow_count = 0         # copy-on-write page copies performed
        # optional callable(shortfall_pages) that tries to free pages
        # (the prefix cache's LRU eviction); consulted before any
        # "pool exhausted" raise
        self.reclaimer = None

    # -- control plane (host) ------------------------------------------

    def allocate(self) -> int:
        """Claim a sequence slot."""
        for s in range(self.max_seqs):
            if not self._active[s]:
                self._active[s] = True
                self.lengths[s] = 0
                return s
        raise RuntimeError("no free sequence slots (continuous batching "
                           "is full) — free() a finished sequence first")

    def free(self, seq: int) -> None:
        """Release a sequence's pages — every ASSIGNED slot, not just
        length-covered ones, so reserved-but-unwritten pages (e.g. from
        a failed batch step) are recovered too.  A page returns to the
        free list only when its LAST owner lets go: pages shared with
        the prefix index (refcount > 1) merely drop a reference."""
        for pid in self.page_table[seq]:
            if pid >= 0:
                self._deref(int(pid))
        self.page_table[seq] = -1
        self.lengths[seq] = 0
        self._active[seq] = False

    # -- refcounted page pool --------------------------------------------

    def _pop_page(self) -> int:
        pid = self._free.pop()
        self.page_refs[pid] = 1
        return pid

    def _deref(self, pid: int) -> None:
        self.page_refs[pid] -= 1
        if self.page_refs[pid] == 0:
            self._free.append(pid)
        elif self.page_refs[pid] < 0:
            raise AssertionError(
                f"page {pid} refcount went negative (double free)")

    def _reclaim(self, shortfall: int) -> None:
        """Ask the attached prefix cache (if any) to LRU-evict enough
        zero-refcount pages to cover ``shortfall`` — tried before any
        pool-exhausted raise, so eviction replaces preempt-and-recompute
        whenever cold cache entries are holding the pages."""
        if self.reclaimer is not None and shortfall > 0:
            self.reclaimer(shortfall)

    def attach(self, seq: int, page_ids, n_tokens: int) -> None:
        """Attach already-written pages BY REFERENCE (prefix-cache hit):
        the slot's first ``len(page_ids)`` table rows point at shared
        pages and the sequence length starts at ``n_tokens`` — prefill
        then begins at the first divergent token.  The final page may be
        partially covered (``n_tokens`` not page-aligned); the first
        write to it copy-on-writes."""
        n_pages = len(page_ids)
        if n_tokens > n_pages * self.page_size:
            raise ValueError(
                f"attach: {n_tokens} tokens exceed {n_pages} pages "
                f"x {self.page_size}")
        if n_pages > self.max_pages_per_seq:
            raise RuntimeError(
                f"sequence {seq} needs {n_pages} pages > per-seq "
                f"budget {self.max_pages_per_seq}")
        for i, pid in enumerate(page_ids):
            if self.page_table[seq, i] >= 0:
                raise AssertionError(
                    f"attach over an assigned slot {i} of seq {seq}")
            self.page_table[seq, i] = int(pid)
            self.page_refs[int(pid)] += 1
        self.lengths[seq] = int(n_tokens)

    def make_writable(self, seq: int, start: int, end: int) -> None:
        """Copy-on-write guard: every page-table slot overlapping token
        positions [start, end) must be exclusively owned before an
        in-place write.  Shared pages (refcount > 1) get a fresh page
        with the prefix-resident contents copied; unshared pages are
        untouched, so with no prefix cache this is a no-op."""
        if end <= start:
            return
        ps = self.page_size
        for slot in range(start // ps, -(-end // ps)):
            pid = int(self.page_table[seq, slot])
            if pid >= 0 and self.page_refs[pid] > 1:
                self._cow(seq, slot)

    def _cow(self, seq: int, slot: int) -> None:
        _faults.fire("prefix.cow", "before")
        if not self._free:
            self._reclaim(1)
        if not self._free:
            raise RuntimeError("KV page pool exhausted (copy-on-write "
                               "of a shared prefix page)")
        old = int(self.page_table[seq, slot])
        new = self._pop_page()
        # the prefix-resident slice lives below the write offset; the
        # whole-page copy is a superset (bytes past it are overwritten
        # or masked by the length)
        self.k_pages = self.k_pages.at[:, :, new].set(
            self.k_pages[:, :, old])
        self.v_pages = self.v_pages.at[:, :, new].set(
            self.v_pages[:, :, old])
        if self.k_scales is not None:
            # a quantized page is meaningless without its scale — the
            # copy must carry both or the COW'd page dequantizes wrong
            self.k_scales = self.k_scales.at[:, :, new].set(
                self.k_scales[:, :, old])
            self.v_scales = self.v_scales.at[:, :, new].set(
                self.v_scales[:, :, old])
        self.page_table[seq, slot] = new
        self.page_refs[old] -= 1
        self.cow_count += 1
        from .. import obs as _obs

        h = _obs.handle()
        if h is not None:
            h.recorder.record("kv.cow", seq=seq, slot=slot,
                              old_page=old, new_page=new)
            h.registry.counter(
                "kv_cow_copies_total",
                "Copy-on-write duplications of shared KV pages").inc()
        _faults.fire("prefix.cow", "after")

    def _plan_missing(self, seq: int, new_len: int):
        """Slot-aware plan (-1 = unset): the list of page-table slots
        that still need a page for ``seq`` to hold ``new_len`` tokens.
        Idempotent across retries — already-assigned slots are never
        re-popped."""
        need = -(-new_len // self.page_size)
        if need > self.max_pages_per_seq:
            raise RuntimeError(
                f"sequence {seq} needs {need} pages > per-seq budget "
                f"{self.max_pages_per_seq}")
        return [i for i in range(need) if self.page_table[seq, i] < 0]

    def _ensure_capacity(self, seq: int, new_len: int) -> None:
        missing = self._plan_missing(seq, new_len)
        if len(missing) > len(self._free):
            self._reclaim(len(missing) - len(self._free))
        if len(missing) > len(self._free):
            raise RuntimeError("KV page pool exhausted")
        for i in missing:
            self.page_table[seq, i] = self._pop_page()

    def reserve(self, seqs, extra_tokens=1) -> None:
        """Batch-atomic capacity reservation: plan every sequence's
        missing slots first, commit only if the WHOLE batch fits (a
        per-sequence loop would leak the earlier sequences' pages on a
        mid-batch failure).  Prefix-cache eviction is tried before
        giving up, so cold cached pages yield to live sequences.

        ``extra_tokens`` is one int for the whole batch or a per-seq
        sequence aligned with ``seqs`` (speculative decode reserves a
        clamped lookahead per sequence)."""
        seqs = list(seqs)
        extras = (list(extra_tokens)
                  if isinstance(extra_tokens, (list, tuple, np.ndarray))
                  else [extra_tokens] * len(seqs))
        plans = [(s, self._plan_missing(
            s, int(self.lengths[s]) + int(e)))
            for s, e in zip(seqs, extras)]
        need = sum(len(m) for _, m in plans)
        if need > len(self._free):
            self._reclaim(need - len(self._free))
        if need > len(self._free):
            raise RuntimeError("KV page pool exhausted")
        for s, missing in plans:
            for i in missing:
                self.page_table[s, i] = self._pop_page()

    def trim(self, seq: int) -> int:
        """Release every assigned page-table slot past the page cover of
        the sequence's CURRENT length (the rollback half of speculative
        decode: pages reserved for a draft window whose tail was
        rejected go back to the pool/refcount pool).  Returns the number
        of slots released.  Refcount-safe: a shared page merely drops
        this slot's reference."""
        keep = -(-int(self.lengths[seq]) // self.page_size)
        freed = 0
        for slot in range(keep, self.max_pages_per_seq):
            pid = int(self.page_table[seq, slot])
            if pid >= 0:
                self._deref(pid)
                self.page_table[seq, slot] = -1
                freed += 1
        return freed

    # -- data plane (device) -------------------------------------------

    def prefill(self, seq: int, k, v) -> None:
        """Write a prompt's KV: k/v [L, KV, T, D]."""
        self.write_at(seq, k, v, 0)

    def write_at(self, seq: int, k, v, start: int) -> None:
        """Write a token span's KV at position ``start`` (chunked
        prefill): k/v [L, KV, T, D] covering positions
        ``start..start+T-1``.  Pages are allocated as needed; the
        sequence length becomes ``start + T``.  On an int8 pool the
        span is quantized on write (``ops.quant.kv_write``:
        scatter-max the touched pages' scales, requantize residents,
        write the new cells)."""
        T = int(np.shape(k)[2])
        self._ensure_capacity(seq, start + T)
        # shared pages in the write window are read-only: COW them
        # first (no-op when nothing is shared, i.e. no prefix cache)
        self.make_writable(seq, start, start + T)
        ps = self.page_size
        if self.quant == "int8":
            from ..ops import quant as _quant

            row = self.page_table[seq]
            pids = jnp.asarray([int(row[(start + t) // ps])
                                for t in range(T)], jnp.int32)
            offs = jnp.asarray([(start + t) % ps for t in range(T)],
                               jnp.int32)
            _faults.fire("quant.kv_write", "before")
            self.k_pages, self.k_scales = _quant.kv_write(
                self.k_pages, self.k_scales, pids, offs,
                jnp.asarray(k))
            self.v_pages, self.v_scales = _quant.kv_write(
                self.v_pages, self.v_scales, pids, offs,
                jnp.asarray(v))
            _faults.fire("quant.kv_write", "after")
            self.lengths[seq] = start + T
            return
        k = jnp.asarray(k, self.k_pages.dtype)
        v = jnp.asarray(v, self.v_pages.dtype)
        t = 0
        while t < T:
            pos = start + t
            page, off = pos // ps, pos % ps
            n = min(ps - off, T - t)  # span within this page
            pid = int(self.page_table[seq, page])
            self.k_pages = self.k_pages.at[:, :, pid, off:off + n].set(
                k[:, :, t:t + n])
            self.v_pages = self.v_pages.at[:, :, pid, off:off + n].set(
                v[:, :, t:t + n])
            t += n
        self.lengths[seq] = start + T

    def write_sharded(self, seq: int, k, v, start: int,
                      n_ranks: int) -> int:
        """Write one sequence-parallel prefill chunk's KV as
        ``n_ranks`` contiguous per-rank ranges (serve.prefill_sp):
        rank r owns positions ``start + r*(T/n) .. start +
        (r+1)*(T/n) - 1`` — the same stripes the ring-gathered
        attention computed.  Ranges land in ascending rank order, so
        the final sequence length is exactly ``start + T`` like one
        dense :meth:`write_at`; every range write is bracketed by the
        ``sp.shard`` fault point, and a raise there fails ONLY the
        bracketed request (the scheduler's serve.request isolation),
        never the pool.  Returns the number of ranges written."""
        T = int(np.shape(k)[2])
        if n_ranks < 1 or T % n_ranks:
            raise ValueError(
                f"sp chunk of {T} tokens does not split into "
                f"{n_ranks} equal per-rank ranges")
        cl = T // n_ranks
        for r in range(n_ranks):
            _faults.fire("sp.shard", "before")
            self.write_at(seq, k[:, :, r * cl:(r + 1) * cl],
                          v[:, :, r * cl:(r + 1) * cl], start + r * cl)
            _faults.fire("sp.shard", "after")
        return n_ranks

    def gather_shards(self, seq: int) -> int:
        """One-shot page all-gather at the prefill->decode transition
        of a sequence-parallel prefill: after it, every rank holds the
        sequence's full page set and decode runs byte-identical to the
        single-device path.  On this single-host pool the page arrays
        are already globally addressable, so the data movement itself
        is a no-op — what this models (and meters: the ``sp.gather``
        fault point plus ``sp_gather_pages_total``) is the one
        ``all_gather`` of pages a range-sharded multi-host pool pays
        HERE, once, instead of every decode step gathering across the
        mesh.  Returns the number of pages covered."""
        _faults.fire("sp.gather", "before")
        pages = -(-int(self.lengths[seq]) // self.page_size)
        from .. import obs as _obs

        h = _obs.handle()
        if h is not None:
            h.registry.counter(
                "sp_gather_pages_total",
                "KV pages all-gathered at sequence-parallel "
                "prefill->decode transitions",
            ).inc(pages)
        _faults.fire("sp.gather", "after")
        return pages

    def gather_dense(self, seq: int, length=None):
        """Gather a sequence's pages into dense [L, KV, P, D] arrays
        (P = page-multiple cover of ``length``) — the past-KV operand of
        the chunked-prefill forward.  Positions >= length are garbage
        and must be masked by the consumer."""
        L = int(self.lengths[seq]) if length is None else int(length)
        n = -(-L // self.page_size)
        row = self.page_table[seq, :n]
        if (row < 0).any():
            # an unset (-1) slot inside the requested length used to be
            # clipped to page 0 — silently serving another sequence's
            # KV.  That is always a caller bug: fail loudly instead.
            bad = int(np.argmax(row < 0))
            raise RuntimeError(
                f"gather_dense: sequence {seq} page slot {bad} is "
                f"unset inside the requested length {L} "
                f"({n} pages) — refusing to read garbage from page 0")
        pids = jnp.asarray(row)
        k = self.k_pages[:, :, pids]          # [L, KV, n, ps, D]
        v = self.v_pages[:, :, pids]
        if self.quant == "int8":
            from ..ops import quant as _quant

            _faults.fire("quant.dequant", "before")
            k = _quant.kv_dequant(k, self.k_scales[:, :, pids],
                                  self.compute_dtype)
            v = _quant.kv_dequant(v, self.v_scales[:, :, pids],
                                  self.compute_dtype)
            _faults.fire("quant.dequant", "after")
        sh = (k.shape[0], k.shape[1], n * self.page_size, k.shape[4])
        return k.reshape(sh), v.reshape(sh)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def free_slots(self) -> int:
        return self._active.count(False)

    def append(self, seqs, k, v) -> None:
        """Decode-step write: one new token per listed sequence.
        k/v: [L, KV, B, D] for B = len(seqs).

        Two-phase so a capacity failure mutates NOTHING: plan every
        sequence's allocation first, commit only if the whole batch
        fits (otherwise an earlier seq would record a length whose
        page slot never got written)."""
        ps = self.page_size
        self.reserve(seqs, extra_tokens=1)  # batch-atomic
        for s in seqs:
            pos = int(self.lengths[s])
            self.make_writable(s, pos, pos + 1)
        pids, offs = [], []
        for s in seqs:
            pos = int(self.lengths[s])
            pids.append(int(self.page_table[s, pos // ps]))
            offs.append(pos % ps)
            self.lengths[s] = pos + 1
        pids = jnp.asarray(pids)
        offs = jnp.asarray(offs)
        if self.quant == "int8":
            from ..ops import quant as _quant

            _faults.fire("quant.kv_write", "before")
            self.k_pages, self.k_scales = _quant.kv_write(
                self.k_pages, self.k_scales, pids, offs, jnp.asarray(k))
            self.v_pages, self.v_scales = _quant.kv_write(
                self.v_pages, self.v_scales, pids, offs, jnp.asarray(v))
            _faults.fire("quant.kv_write", "after")
            return
        k = jnp.asarray(k, self.k_pages.dtype)
        v = jnp.asarray(v, self.v_pages.dtype)
        # advanced indexing: [L, KV, B, D] written at (page, offset)[B]
        self.k_pages = self.k_pages.at[:, :, pids, offs].set(k)
        self.v_pages = self.v_pages.at[:, :, pids, offs].set(v)

    def attend(self, layer: int, q, seqs,
               pages_per_compute_block=4):
        """Decode attention for one layer: q [B, H, D] over the listed
        sequences' pages."""
        # clip -1 sentinels (unassigned slots beyond each length) to a
        # valid page id — the length mask excludes them from attention,
        # but gathers/kernel prefetch must stay in range
        table = jnp.asarray(np.maximum(self.page_table[seqs], 0))
        lens = jnp.asarray(self.lengths[seqs])
        return paged_decode_attention(
            q, self.k_pages[layer], self.v_pages[layer], lens, table,
            pages_per_compute_block=pages_per_compute_block,
            k_scales=(None if self.k_scales is None
                      else self.k_scales[layer]),
            v_scales=(None if self.v_scales is None
                      else self.v_scales[layer]))
