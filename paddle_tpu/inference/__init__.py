"""Inference predictor.

Reference: ``paddle/fluid/inference/api/paddle_inference_api.h:81``
(Predictor), ``analysis_predictor.h:105`` (AnalysisPredictor: load program,
run IR pass pipeline, execute with zero-copy handles), Python surface
``paddle.inference.Config`` / ``create_predictor``.

TPU-native: the "analysis + executor" pipeline is XLA — a Predictor wraps
either a live Layer or a ``paddle_tpu.jit.save``d program prefix, compiles
the forward once with ``jax.jit`` over the parameter pytree, and serves
``run()`` as an executable-cache hit.  Zero-copy handles are jax device
arrays.
"""
from __future__ import annotations

import numpy as np


class Config:
    """Reference: paddle.inference.Config(prog_file, params_file)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        if params_path is not None and params_path != model_path:
            # jit.save writes program + weights into one <prefix>.pdparams;
            # a separate params file would be silently ignored otherwise.
            raise NotImplementedError(
                "paddle_tpu saves program and weights in a single "
                f"'<prefix>.pdparams' file; pass that prefix as model_path "
                f"(got params_path={params_path!r})")
        self._device = None

    def enable_use_gpu(self, *a, **k):  # compat no-op: device is jax's
        pass

    def disable_gpu(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class Predictor:
    """predictor = create_predictor(config)  # or Predictor(layer)
    out = predictor.run([np_array, ...])  -> [np_array, ...]
    """

    def __init__(self, source, model_builder=None):
        from ..nn.layers import Layer

        if isinstance(source, Config):
            from .. import jit as pjit

            translated = pjit.load(source.model_path)
            if model_builder is not None:
                layer = model_builder()
                layer.set_state_dict(translated.state_dict())
                self.layer = layer
            elif translated.has_program():
                # Artifact-only inference: execute the saved program
                # directly — no python model code (reference
                # analysis_predictor.h:105 ability).
                self.layer = translated
            else:
                raise ValueError(
                    "this artifact carries no executable program (saved "
                    "without input_spec) — pass model_builder: a callable "
                    "returning the Layer to load the saved weights into")
        elif isinstance(source, Layer):
            self.layer = source
        else:
            raise TypeError(f"Predictor expects Config or Layer, got "
                            f"{type(source)}")
        self.layer.eval()
        self._jitted = None

    def _build(self):
        import jax

        from ..jit.functional import functional_call, param_tree

        layer = self.layer
        self._params = param_tree(layer, trainable_only=False)

        def fwd(params, *inputs):
            return functional_call(layer, params, *inputs)

        self._jitted = jax.jit(fwd)

    def get_input_names(self):
        import inspect

        sig = inspect.signature(self.layer.forward)
        return [p for p in sig.parameters if p != "self"]

    def run(self, inputs):
        """inputs: list of np arrays / Tensors -> list of np arrays."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        if self._jitted is None:
            self._build()
        ins = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        out = self._jitted(self._params, *ins)
        outs = out if isinstance(out, (tuple, list)) else [out]
        return [np.asarray(o) for o in outs]


def create_predictor(config, model_builder=None):
    return Predictor(config, model_builder=model_builder)


from .paged import (  # noqa: F401,E402
    PagedKVCache, masked_multihead_attention, paged_decode_attention,
)
from .serving import PagedLlamaEngine  # noqa: F401,E402
