"""Inference predictor.

Reference: ``paddle/fluid/inference/api/paddle_inference_api.h:81``
(Predictor), ``analysis_predictor.h:105`` (AnalysisPredictor: load program,
run IR pass pipeline, execute with zero-copy handles), Python surface
``paddle.inference.Config`` / ``create_predictor``.

TPU-native: the "analysis + executor" pipeline is XLA — a Predictor wraps
either a live Layer or a ``paddle_tpu.jit.save``d program prefix, compiles
the forward once with ``jax.jit`` over the parameter pytree, and serves
``run()`` as an executable-cache hit.  Zero-copy handles are jax device
arrays.
"""
from __future__ import annotations

import numpy as np


class Config:
    """Reference: paddle.inference.Config(prog_file, params_file)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        if params_path is not None and params_path != model_path:
            # jit.save writes program + weights into one <prefix>.pdparams;
            # a separate params file would be silently ignored otherwise.
            raise NotImplementedError(
                "paddle_tpu saves program and weights in a single "
                f"'<prefix>.pdparams' file; pass that prefix as model_path "
                f"(got params_path={params_path!r})")
        self._device = None

    def enable_use_gpu(self, memory_pool_init_size_mb=100,
                       device_id=0, *a, **k):
        """Device binding (reference Config::EnableUseGpu).  Maps onto
        the accelerator jax exposes; device_id selects among local
        devices."""
        self._device = ("accel", int(device_id))

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = int(n)

    # -- analysis/optimization toggles (analysis_predictor.h:105) ------
    # XLA always runs its own pass pipeline; these record the
    # reference's knobs and steer the pieces that exist here.

    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def enable_memory_optim(self, flag=True):
        """Reference memory-optim pass -> jax buffer donation on run()
        inputs (the analog: reuse input buffers for activations)."""
        self._memory_optim = bool(flag)

    def memory_optim_enabled(self):
        return getattr(self, "_memory_optim", False)

    def enable_mkldnn(self):
        pass  # x86-only backend knob; XLA:CPU handles vectorization

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT is a CUDA engine; the XLA pipeline is always on "
            "— precision is controlled via enable_low_precision()")

    def enable_low_precision(self, dtype="bfloat16"):
        """Serve in low precision (the EnableTensorRtEngine precision
        analog on TPU): weights+compute cast at load."""
        self._low_precision = str(dtype)

    def switch_use_feed_fetch_ops(self, flag=False):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    def set_model(self, model_path, params_path=None):
        self.model_path = model_path

    def model_dir(self):
        return self.model_path

    def summary(self):
        rows = [("model_path", str(self.model_path)),
                ("device", str(getattr(self, "_device", None))),
                ("ir_optim", str(getattr(self, "_ir_optim", True))),
                ("memory_optim",
                 str(getattr(self, "_memory_optim", False))),
                ("low_precision",
                 str(getattr(self, "_low_precision", None)))]
        w = max(len(k) for k, _ in rows) + 2
        return "\n".join(f"{k:<{w}}{v}" for k, v in rows)


class Predictor:
    """predictor = create_predictor(config)  # or Predictor(layer)
    out = predictor.run([np_array, ...])  -> [np_array, ...]
    """

    def __init__(self, source, model_builder=None):
        from ..nn.layers import Layer

        if isinstance(source, Config):
            from .. import jit as pjit

            translated = pjit.load(source.model_path)
            if model_builder is not None:
                layer = model_builder()
                layer.set_state_dict(translated.state_dict())
                self.layer = layer
            elif translated.has_program():
                # Artifact-only inference: execute the saved program
                # directly — no python model code (reference
                # analysis_predictor.h:105 ability).
                self.layer = translated
            else:
                raise ValueError(
                    "this artifact carries no executable program (saved "
                    "without input_spec) — pass model_builder: a callable "
                    "returning the Layer to load the saved weights into")
        elif isinstance(source, Layer):
            self.layer = source
        else:
            raise TypeError(f"Predictor expects Config or Layer, got "
                            f"{type(source)}")
        self._config = source if isinstance(source, Config) else None
        self.layer.eval()
        self._jitted = None

    def _build(self):
        import jax

        from ..jit.functional import functional_call, param_tree

        layer = self.layer
        self._params = param_tree(layer, trainable_only=False)
        cfg = self._config
        if cfg is not None and getattr(cfg, "_low_precision", None):
            import jax.numpy as jnp

            from ..core import dtype as _dt

            lp = _dt.convert_dtype(cfg._low_precision)
            self._params = {
                k: (v.astype(lp)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in self._params.items()}
        if cfg is not None and getattr(cfg, "_device", None):
            kind, idx = cfg._device
            devs = (jax.devices("cpu") if kind == "cpu"
                    else jax.devices())
            dev = devs[min(idx, len(devs) - 1)]
            self._params = jax.device_put(self._params, dev)

        def fwd(params, *inputs):
            return functional_call(layer, params, *inputs)

        self._jitted = jax.jit(fwd)

    def get_input_names(self):
        import inspect

        sig = inspect.signature(self.layer.forward)
        return [p for p in sig.parameters if p != "self"]

    def run(self, inputs):
        """inputs: list of np arrays / Tensors -> list of np arrays."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        if self._jitted is None:
            self._build()
        ins = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        out = self._jitted(self._params, *ins)
        outs = out if isinstance(out, (tuple, list)) else [out]
        return [np.asarray(o) for o in outs]


def create_predictor(config, model_builder=None):
    return Predictor(config, model_builder=model_builder)


from .paged import (  # noqa: F401,E402
    PagedKVCache, masked_multihead_attention, paged_decode_attention,
)
from .serving import PagedLlamaEngine  # noqa: F401,E402
