"""Inference predictor.

Reference: ``paddle/fluid/inference/api/paddle_inference_api.h:81``
(Predictor), ``analysis_predictor.h:105`` (AnalysisPredictor: load program,
run IR pass pipeline, execute with zero-copy handles), Python surface
``paddle.inference.Config`` / ``create_predictor``.

TPU-native: the "analysis + executor" pipeline is XLA — a Predictor wraps
either a live Layer or a ``paddle_tpu.jit.save``d program prefix, compiles
the forward once with ``jax.jit`` over the parameter pytree, and serves
``run()`` as an executable-cache hit.  Zero-copy handles are jax device
arrays.
"""
from __future__ import annotations

import numpy as np


class Config:
    """Reference: paddle.inference.Config(prog_file, params_file)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        if params_path is not None and params_path != model_path:
            # jit.save writes program + weights into one <prefix>.pdparams;
            # a separate params file would be silently ignored otherwise.
            raise NotImplementedError(
                "paddle_tpu saves program and weights in a single "
                f"'<prefix>.pdparams' file; pass that prefix as model_path "
                f"(got params_path={params_path!r})")
        self._device = None

    def enable_use_gpu(self, memory_pool_init_size_mb=100,
                       device_id=0, *a, **k):
        """Device binding (reference Config::EnableUseGpu).  Maps onto
        the accelerator jax exposes; device_id selects among local
        devices."""
        self._device = ("accel", int(device_id))

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = int(n)

    # -- analysis/optimization toggles (analysis_predictor.h:105) ------
    # XLA always runs its own pass pipeline; these record the
    # reference's knobs and steer the pieces that exist here.

    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def enable_memory_optim(self, flag=True):
        """Reference memory-optim pass -> jax buffer donation on run()
        inputs (the analog: reuse input buffers for activations)."""
        self._memory_optim = bool(flag)

    def memory_optim_enabled(self):
        return getattr(self, "_memory_optim", False)

    def enable_mkldnn(self):
        pass  # x86-only backend knob; XLA:CPU handles vectorization

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT is a CUDA engine; the XLA pipeline is always on "
            "— precision is controlled via enable_low_precision()")

    def enable_low_precision(self, dtype="bfloat16"):
        """Serve in low precision (the EnableTensorRtEngine precision
        analog on TPU): weights+compute cast at load."""
        self._low_precision = str(dtype)

    def switch_use_feed_fetch_ops(self, flag=False):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    def set_optim_cache_dir(self, path):
        """Reference Config::SetOptimCacheDir — persists optimized
        programs.  TPU analog: the jax persistent compilation cache (the
        compiled XLA executable IS the optimized program)."""
        import jax

        jax.config.update("jax_compilation_cache_dir", str(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        self._optim_cache_dir = str(path)

    def use_gpu(self):
        d = getattr(self, "_device", None)
        return bool(d) and d[0] == "accel"

    def gpu_device_id(self):
        d = getattr(self, "_device", None)
        return d[1] if d else 0

    def disable_glog_info(self):
        import logging

        logging.getLogger("jax").setLevel(logging.ERROR)
        self._glog_disabled = True

    def glog_info_disabled(self):
        return getattr(self, "_glog_disabled", False)

    def enable_profile(self):
        self._profile = True

    def pass_builder(self):
        """XLA owns the pass pipeline; expose a no-op recorder so tooling
        that deletes passes keeps working."""
        cfg = self

        class _PassBuilder:
            def all_passes(self):
                return []

            def delete_pass(self, name):
                cfg._deleted_passes = getattr(cfg, "_deleted_passes",
                                              set()) | {name}

        return _PassBuilder()

    def exp_disable_tensorrt_ops(self, ops):
        pass  # no TensorRT on TPU

    def set_model(self, model_path, params_path=None):
        self.model_path = model_path

    def model_dir(self):
        return self.model_path

    def summary(self):
        rows = [("model_path", str(self.model_path)),
                ("device", str(getattr(self, "_device", None))),
                ("ir_optim", str(getattr(self, "_ir_optim", True))),
                ("memory_optim",
                 str(getattr(self, "_memory_optim", False))),
                ("low_precision",
                 str(getattr(self, "_low_precision", None)))]
        w = max(len(k) for k, _ in rows) + 2
        return "\n".join(f"{k:<{w}}{v}" for k, v in rows)


class DataType:
    """Reference paddle_infer.DataType (paddle_inference_api.h)."""

    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


class PrecisionType:
    """Reference paddle_infer.PrecisionType."""

    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    """Reference paddle_infer.PlaceType."""

    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    CUSTOM = "custom"
    UNK = "unk"


class Tensor:
    """Inference tensor handle (reference paddle_infer.Tensor /
    wrapper.py:45 tensor_copy_from_cpu): the zero-copy feed/fetch slot of
    the handle-based run workflow."""

    def __init__(self, name=""):
        self.name = name
        self._data = None

    def copy_from_cpu(self, data):
        import jax.numpy as jnp

        self._data = jnp.asarray(np.asarray(data))

    def share_external_data(self, data):
        """wrapper.py:59 — adopt the buffer without a copy (device arrays
        pass through)."""
        from ..core.tensor import Tensor as _T

        self._data = data._data if isinstance(data, _T) else data

    def copy_to_cpu(self):
        return np.asarray(self._data)

    def reshape(self, shape):
        if self._data is not None:
            self._data = self._data.reshape(tuple(shape))

    def shape(self):
        return list(self._data.shape) if self._data is not None else []

    def type(self):
        return str(self._data.dtype) if self._data is not None else None


class Predictor:
    """predictor = create_predictor(config)  # or Predictor(layer)
    out = predictor.run([np_array, ...])  -> [np_array, ...]

    Also serves the reference's handle workflow
    (paddle_inference_api.h:81):
        h = predictor.get_input_handle(name); h.copy_from_cpu(x)
        predictor.run()
        out = predictor.get_output_handle(
            predictor.get_output_names()[0]).copy_to_cpu()
    """

    def __init__(self, source, model_builder=None):
        from ..nn.layers import Layer

        if isinstance(source, Config):
            from .. import jit as pjit

            translated = pjit.load(source.model_path)
            if model_builder is not None:
                layer = model_builder()
                layer.set_state_dict(translated.state_dict())
                self.layer = layer
            elif translated.has_program():
                # Artifact-only inference: execute the saved program
                # directly — no python model code (reference
                # analysis_predictor.h:105 ability).
                self.layer = translated
            else:
                raise ValueError(
                    "this artifact carries no executable program (saved "
                    "without input_spec) — pass model_builder: a callable "
                    "returning the Layer to load the saved weights into")
        elif isinstance(source, Layer):
            self.layer = source
        else:
            raise TypeError(f"Predictor expects Config or Layer, got "
                            f"{type(source)}")
        self._config = source if isinstance(source, Config) else None
        self.layer.eval()
        self._jitted = None
        self._input_handles = {}
        self._output_handles = {}
        self._output_names = []

    def _build(self):
        import jax

        from ..jit.functional import functional_call, param_tree

        layer = self.layer
        self._params = param_tree(layer, trainable_only=False)
        cfg = self._config
        if cfg is not None and getattr(cfg, "_low_precision", None):
            import jax.numpy as jnp

            from ..core import dtype as _dt

            lp = _dt.convert_dtype(cfg._low_precision)
            self._params = {
                k: (v.astype(lp)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in self._params.items()}
        if cfg is not None and getattr(cfg, "_device", None):
            kind, idx = cfg._device
            devs = (jax.devices("cpu") if kind == "cpu"
                    else jax.devices())
            dev = devs[min(idx, len(devs) - 1)]
            self._params = jax.device_put(self._params, dev)

        def fwd(params, *inputs):
            return functional_call(layer, params, *inputs)

        if cfg is not None and cfg.memory_optim_enabled():
            # memory-optim pass analog: donate input buffers so XLA can
            # reuse them for activations (per-arity jit cache — donation
            # positions depend on how many inputs arrive).  Only buffers
            # the predictor itself created are donatable; caller-owned
            # arrays (handles, live Tensors) must survive run().
            cache = {}
            plain = jax.jit(fwd)

            def jitted(params, *ins, _donate=False):
                if not _donate:
                    return plain(params, *ins)
                fn = cache.get(len(ins))
                if fn is None:
                    fn = jax.jit(
                        fwd, donate_argnums=tuple(range(1, len(ins) + 1)))
                    cache[len(ins)] = fn
                return fn(params, *ins)

            self._jitted = jitted
            self._can_donate = True
        else:
            self._jitted = jax.jit(fwd)
            self._can_donate = False

    def get_input_names(self):
        import inspect

        sig = inspect.signature(self.layer.forward)
        return [p for p in sig.parameters if p != "self"]

    # -- handle workflow (reference get_input_handle / get_output_handle) --

    def get_input_handle(self, name):
        return self._input_handles.setdefault(name, Tensor(name))

    def get_output_names(self):
        if not self._output_names:
            # one generic slot per output; populated after the first run
            return ["output_0"]
        return list(self._output_names)

    def get_output_handle(self, name):
        return self._output_handles.setdefault(name, Tensor(name))

    def _run_handles(self):
        names = self.get_input_names()
        ins = []
        for n in names:
            h = self._input_handles.get(n)
            if h is None or h._data is None:
                raise ValueError(
                    f"input handle {n!r} not fed — call "
                    "get_input_handle(name).copy_from_cpu(data) first")
            ins.append(h._data)
        outs = self._execute(ins)
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        for i, o in enumerate(outs):
            self.get_output_handle(self._output_names[i])._data = o
        return True

    def _execute(self, ins, donatable=False):
        if self._jitted is None:
            self._build()
        if donatable and self._can_donate:
            out = self._jitted(self._params, *ins, _donate=True)
        else:
            out = self._jitted(self._params, *ins)
        return list(out) if isinstance(out, (tuple, list)) else [out]

    def run(self, inputs=None):
        """List style: run([np, ...]) -> [np, ...].  Handle style (the
        reference's primary workflow): feed via get_input_handle, call
        run() with no args, fetch via get_output_handle."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor as _T

        if inputs is None:
            return self._run_handles()
        # Donation is only safe for buffers created here from host data —
        # a live user Tensor must survive run().
        donatable = all(not isinstance(i, _T) and not hasattr(i, "devices")
                        for i in inputs)
        ins = [i._data if isinstance(i, _T) else jnp.asarray(i)
               for i in inputs]
        return [np.asarray(o)
                for o in self._execute(ins, donatable=donatable)]


def create_predictor(config, model_builder=None):
    return Predictor(config, model_builder=model_builder)


class PredictorPool:
    """Reference paddle_infer.PredictorPool(config, size): a pool of
    predictors sharing one loaded program (XLA executables are shared via
    the jit cache; parameters are shared by reference)."""

    def __init__(self, config, size=1, model_builder=None):
        self._predictors = [create_predictor(config, model_builder)
                            for _ in range(int(size))]

    def retrieve(self, idx):
        return self._predictors[idx]


class XpuConfig:
    """Signature-parity config for XPU device binding (no XPU backend in
    a TPU build; attributes are recorded)."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0
        self.conv_autotune_level = 0


def get_version():
    from .. import __version__

    return f"paddle_tpu {__version__} (XLA inference)"


def get_num_bytes_of_data_type(dtype):
    import jax.numpy as jnp

    from ..core import dtype as _dt

    return jnp.dtype(_dt.convert_dtype(dtype)).itemsize


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT in a TPU build


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """Reference maps fluid op names to phi kernel names; the registry IS
    the kernel table here."""
    return op_name


def convert_to_mixed_precision(model_file, params_file=None,
                               mixed_model_file=None,
                               mixed_params_file=None,
                               mixed_precision="bfloat16", backend=None,
                               keep_io_types=True, black_list=None,
                               model_builder=None, **kwargs):
    """Reference wrapper.py:79 — rewrite a saved artifact with float
    weights cast to the mixed precision (fp16/bf16).

    The saved program (StableHLO export) bakes weights in as constants, so
    a program-carrying artifact needs ``model_builder`` (a callable
    returning the Layer) to re-lower at the new precision — the analog of
    the reference's program-proto rewrite pass.  Weights-only artifacts
    are cast in place."""
    import pickle

    import jax.numpy as jnp

    from ..core import dtype as _dt

    lp = _dt.convert_dtype(
        mixed_precision if isinstance(mixed_precision, str)
        else str(mixed_precision))
    black = set(black_list or [])
    with open(model_file + ".pdparams", "rb") as f:
        payload = pickle.load(f)
    state = {}
    for k, v in payload["state_dict"].items():
        arr = jnp.asarray(v)
        if k not in black and jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(lp)
        state[k] = np.asarray(arr)
    if "exported" in payload or "stablehlo" in payload:
        if model_builder is None:
            raise ValueError(
                "this artifact carries a lowered program whose weights "
                "are baked into the StableHLO — pass model_builder to "
                "re-lower it at the mixed precision")
        from .. import jit as pjit
        from ..core.tensor import Tensor as _T
        from ..jit import InputSpec
        from jax import export as _export

        layer = model_builder()
        layer.set_state_dict({k: _T(jnp.asarray(v))
                              for k, v in state.items()})
        exp = _export.deserialize(payload["exported"])
        specs = []
        for aval in exp.in_avals:
            dt = aval.dtype
            if not keep_io_types and jnp.issubdtype(dt, jnp.floating):
                dt = lp
            specs.append(InputSpec(shape=aval.shape, dtype=dt))
        pjit.save(layer, mixed_model_file, input_spec=specs)
        return mixed_model_file
    payload["state_dict"] = state
    with open(mixed_model_file + ".pdparams", "wb") as f:
        pickle.dump(payload, f)
    return mixed_model_file


from .paged import (  # noqa: F401,E402
    PagedKVCache, masked_multihead_attention, paged_decode_attention,
)
from .serving import PagedLlamaEngine  # noqa: F401,E402
from .server import (  # noqa: F401,E402
    PagedExecutor, RequestHandle, RequestState, ServingEngine,
)
