"""PagedExecutor — the model-execution backend of the serving stack.

This is the data plane the continuous-batching scheduler drives: it owns
the stacked model parameters, the jitted prefill/decode programs and the
:class:`~paddle_tpu.inference.paged.PagedKVCache` page pool.  It knows
NOTHING about queues, priorities or deadlines — those live in
``scheduler.py`` — it only exposes slot-granular operations:

  * ``prefill(sid, ids)``          whole-prompt prefill, one program
  * ``prefill_chunk(sid, ids, t0)``chunked prefill: attend past pages,
                                   write the chunk's KV at offset t0
  * ``decode(sids)``               one greedy token for an explicit
                                   batch of slots
  * ``decode_n(sids, n)``          n greedy tokens, feedback on device

The legacy :class:`~paddle_tpu.inference.serving.PagedLlamaEngine`
manual API is a thin shim over this class, so the hand-driven and the
scheduled paths execute byte-identical programs.
"""
from __future__ import annotations

import os
import warnings

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as _P

from ... import obs
from ...analysis import CountedJit, ProgramContract, register_program
from ...ops import quant as _quant
from ...ops.nn_ops import _rms_norm_plain, _rope_plain
from ..paged import PagedKVCache, paged_decode_attention


def _sp_prefill_enabled() -> bool:
    """PT_SP_PREFILL={off,on} — sequence-parallel prefill of long
    prompts over a mesh (serve.prefill_sp).  Off is bit-exact r22."""
    mode = os.environ.get("PT_SP_PREFILL", "off").lower()
    if mode not in ("off", "on"):
        raise ValueError(f"PT_SP_PREFILL={mode!r}: expected off|on")
    return mode == "on"


def _sp_min_tokens_default() -> int:
    """PT_SP_PREFILL_MIN_TOKENS — raw prompt-length threshold above
    which prefill is planned sequence-parallel (floor-quantized onto
    the AOT bucket ladder when one is armed)."""
    return int(os.environ.get("PT_SP_PREFILL_MIN_TOKENS", "64"))


def _mm(x, w):
    """Weight matmul that dispatches on the weight's pytree form at
    TRACE time: a plain array keeps the exact pre-quant jaxpr
    (PT_QUANT=none stays bit-exact by construction), a QuantizedLinear
    dict routes through the fused-dequant path."""
    if _quant.is_quantized(w):
        return _quant.qmatmul(x, w)
    return x @ w


#: the stacked decoder weights quantized under PT_QUANT=int8 — the
#: seven per-layer projection matmuls.  Embedding, norms, RoPE tables
#: and the LM head stay in the checkpoint dtype (small, and the head
#: dominates logit drift).
_QUANT_LAYER_WEIGHTS = (
    "self_attn.q_proj.weight", "self_attn.k_proj.weight",
    "self_attn.v_proj.weight", "self_attn.o_proj.weight",
    "mlp.gate_proj.weight", "mlp.up_proj.weight",
    "mlp.down_proj.weight",
)


class _PendingDecode:
    """Unrealized device output of one async decode dispatch.

    ``wait()`` is the commit fence: ONE host transfer (the in-graph
    argmax already reduced logits to an int32 [B] row), then the
    last-token bookkeeping the sync path does inline.  Idempotent, so
    a fault-interrupted commit can be re-driven safely."""

    __slots__ = ("_ex", "sids", "_dev", "_out")

    def __init__(self, ex, sids, dev):
        self._ex = ex
        self.sids = sids
        self._dev = dev
        self._out = None

    def wait(self) -> dict:
        if self._out is None:
            toks = np.asarray(self._dev)      # the single device_get
            out = {}
            for i, s in enumerate(self.sids):
                tok = int(toks[i])
                self._ex.last_token[s] = tok
                out[s] = tok
            self._out = out
            self._dev = None
        return self._out


class _PendingVerify:
    """Unrealized device outputs of one async speculative-verify
    dispatch: the sort-packed token block and per-seq counts stay on
    device until ``wait()``, which also applies the length/last-token
    bookkeeping the sync :meth:`PagedExecutor.verify` does inline."""

    __slots__ = ("_ex", "sids", "_packed", "_emit_n", "_out")

    def __init__(self, ex, sids, packed, emit_n):
        self._ex = ex
        self.sids = sids
        self._packed = packed
        self._emit_n = emit_n
        self._out = None

    def wait(self):
        if self._out is None:
            cache = self._ex.cache
            packed = np.asarray(self._packed)
            counts = np.asarray(self._emit_n)
            out, accepted = {}, {}
            off = 0
            for i, s in enumerate(self.sids):
                n = int(counts[i])
                toks = [int(t) for t in packed[off:off + n]]
                off += n
                cache.lengths[s] += n
                self._ex.last_token[s] = toks[-1]
                out[s] = toks
                accepted[s] = n - 1
            self._out = (out, accepted)
            self._packed = self._emit_n = None
        return self._out


class PagedExecutor:
    """Execution backend over the paged KV cache.

    ``num_pages=None`` sizes the pool so every slot can reach
    ``max_len`` (the legacy engine's sizing).  A serving deployment
    passes a smaller pool to oversubscribe: the per-seq page budget
    stays ``max_len // page_size`` but the POOL can run dry, which is
    what makes admission control and preemption meaningful.
    """

    def __init__(self, model, max_seqs=4, page_size=16, max_len=256,
                 dtype=jnp.float32, num_pages=None, quant=None,
                 sp_mesh=None, sp_prefill=None, sp_min_tokens=None,
                 sp_axis=None):
        from ...models.generation import _stack_layer_params
        from ...models.llama import _rope_tables

        cfg = model.config
        self.config = cfg
        self.max_len = int(max_len)
        # PT_QUANT gate (ops/quant.py): validated here so a bogus value
        # fails the engine build, not the first decode step
        self.quant = _quant.quant_mode(quant)
        state = {k: v._data for k, v in model.state_dict().items()}
        self.layers = _stack_layer_params(state, cfg.num_hidden_layers)
        if self.quant == "int8":
            # stacked [L, in, out] projections -> QuantizedLinear dicts
            # ({qweight int8 [L, in, out], scale f32 [L, 1, out]});
            # lax.scan slices the dict leaves per layer like any other
            # stacked param, so the forwards only change at _mm()
            for name in _QUANT_LAYER_WEIGHTS:
                self.layers[name] = _quant.quantize_linear(
                    self.layers[name])
        embed = jnp.asarray(state["llama.embed_tokens.weight"])
        cos, sin = _rope_tables(cfg)
        # non-layer weights travel as jit ARGUMENTS: closed-over arrays
        # are baked into the HLO as literals, and multi-MB constants
        # (embed/head at vocab 32k) choke the remote AOT compiler — the
        # r5 root cause of the serving prefill "hang"
        # tied embeddings: alias the SAME buffer and transpose in-graph
        # (embed.T here would materialize a duplicate vocab x hidden
        # array in HBM); _head() applies the orientation.
        self._tied = bool(cfg.tie_word_embeddings)
        self.tops = {
            "embed": embed,
            "norm_w": jnp.asarray(state["llama.norm.weight"]),
            "head_w": (embed if self._tied
                       else jnp.asarray(state["lm_head.weight"])),
            "cos": jnp.asarray(cos),
            "sin": jnp.asarray(sin),
        }

        pages_per_seq = -(-max_len // page_size)
        self.cache = PagedKVCache(
            n_layers=cfg.num_hidden_layers,
            n_kv_heads=cfg.num_key_value_heads, head_dim=cfg.head_dim,
            num_pages=(max_seqs * pages_per_seq if num_pages is None
                       else int(num_pages)),
            page_size=page_size, max_seqs=max_seqs, dtype=dtype,
            max_pages_per_seq=pages_per_seq, quant=self.quant)
        h = obs.handle()
        if h is not None:
            h.registry.gauge(
                "kv_pool_dtype",
                "KV page pool storage dtype (value 1 marks the active "
                "dtype)", labels=("dtype",)).labels(
                dtype=str(np.dtype(self.cache.k_pages.dtype))).set(1)
            h.registry.gauge(
                "quant_mode",
                "Serving quantization mode (PT_QUANT; value 1 marks "
                "the active mode)", labels=("mode",)).labels(
                mode=self.quant).set(1)
        self.last_token = {}
        # (sid, n_tokens) per prefill dispatch — the audit trail the
        # prefix-cache tests use to assert prefill FLOPs covered only
        # the novel suffix of a warm request
        self.prefill_events = []
        # every program is a CountedJit (analysis/audit.py): trace and
        # dispatch counters come with the jit wrapper, and the unjitted
        # fn doubles as the lint registration target below
        self._jit_prefill = CountedJit(self._prefill_fwd,
                                       name="serve.prefill")
        # donate the pools (and the chunk's dense past-KV gather, which
        # is a fresh copy the caller never reuses): the call sites
        # immediately replace them with the outputs, so XLA updates in
        # place instead of copying GBs of KV — the donation-miss lint
        # check flagged the chunk program's past_k/past_v
        self._jit_chunk = CountedJit(self._chunk_fwd,
                                     name="serve.prefill_chunk",
                                     donate_argnums=(4, 5))
        self._jit_decode = CountedJit(self._decode_fwd,
                                      name="serve.decode",
                                      donate_argnums=(4, 5))
        # async twin of serve.decode with the greedy argmax folded
        # in-graph: the only transferable output is an int32 [B] token
        # row, so the double-buffered scheduler's commit fence moves
        # one small vector instead of [B, V] logits
        self._jit_decode_async = CountedJit(self._decode_tok_fwd,
                                            name="serve.decode_async",
                                            donate_argnums=(4, 5))
        self._jit_decode_n = CountedJit(self._decode_n_fwd,
                                        name="serve.decode_n",
                                        static_argnames=("n",),
                                        donate_argnums=(4, 5))
        self._jit_verify = CountedJit(self._verify_fwd,
                                      name="serve.verify",
                                      donate_argnums=(3, 4))
        # -- sequence-parallel prefill (serve.prefill_sp) -------------
        # param forces on/off, None follows PT_SP_PREFILL; off (the
        # default) builds no program and changes nothing — bit-exact
        # r22.  Armed, long-prompt chunks stripe across the mesh's sp
        # axis: each rank ring-gathers the chunk K/V into canonical
        # order and runs the UNMODIFIED dense mask/softmax on its row
        # stripe, so the output is bit-identical to _chunk_fwd.
        sp_on = (_sp_prefill_enabled() if sp_prefill is None
                 else bool(sp_prefill))
        self._sp_mesh = None
        self._sp_jmesh = None
        self._sp_axis = None
        self._sp_n = 1
        self._jit_chunk_sp = None
        if sp_on:
            mesh, axis = self._resolve_sp_mesh(sp_mesh, sp_axis)
            if mesh is not None and mesh.get_dim_size(axis) > 1:
                self._sp_mesh = mesh
                self._sp_jmesh = mesh.jax_mesh
                self._sp_axis = axis
                self._sp_n = int(mesh.get_dim_size(axis))
                self._jit_chunk_sp = CountedJit(
                    self._sp_chunk_fwd, name="serve.prefill_sp",
                    donate_argnums=(4, 5))
        self._sp_min_tokens = (int(sp_min_tokens)
                               if sp_min_tokens is not None
                               else _sp_min_tokens_default())
        # slots holding range-sharded pages from an sp chunk: the
        # prefill->decode gather must fire for these even when the
        # (small) FINAL chunk itself routed to the dense program
        self._sp_written = set()
        self.sp_prefill_tokens = 0
        self.rollback_pages = 0
        # AOT plane state (core/aot.py): a non-None ladder switches the
        # executor into bucketed-shape mode — the scheduler quantizes
        # prefill chunks onto the rungs and prefill_chunk pads the past
        # cover onto page buckets.  None (PT_AOT=off) is bit-exact r17.
        self.aot_ladder = None
        self._aot_page_buckets = None
        self._aot_sealed = False
        self._aot_config = None
        self._register_contracts()

    @property
    def programs(self) -> dict:
        """The jitted programs, by contract name suffix (prefill_sp
        only when the sequence-parallel plane is armed)."""
        progs = {"prefill": self._jit_prefill,
                 "prefill_chunk": self._jit_chunk,
                 "decode": self._jit_decode,
                 "decode_async": self._jit_decode_async,
                 "decode_n": self._jit_decode_n,
                 "verify": self._jit_verify}
        if self._jit_chunk_sp is not None:
            progs["prefill_sp"] = self._jit_chunk_sp
        return progs

    # -- sequence-parallel plane ----------------------------------------

    @staticmethod
    def _resolve_sp_mesh(sp_mesh, sp_axis):
        """(1-D ProcessMesh, axis name) for sequence-parallel prefill.

        ``sp_mesh=None`` builds a 1-D mesh over every local device.  A
        multi-dim mesh (the dp x sep hybrid a training job hands over)
        is reduced to the 1-D submesh along ``sp_axis`` — auto-detected
        as ``sp`` then ``sep``, else the largest dim — by fixing every
        other dim at index 0: prefill shards the SEQUENCE, so exactly
        one mesh axis participates.  Returns (None, None) when no
        multi-device axis exists (the caller disarms)."""
        from ...distributed.auto_parallel import ProcessMesh

        if sp_mesh is None:
            n = jax.device_count()
            if n < 2:
                return None, None
            return (ProcessMesh(list(range(n)), dim_names=["sp"]),
                    sp_axis or "sp")
        mesh = sp_mesh
        if sp_axis is None:
            for cand in ("sp", "sep"):
                if cand in mesh.dim_names:
                    sp_axis = cand
                    break
            else:
                sp_axis = max(mesh.dim_names, key=mesh.get_dim_size)
        for d in list(mesh.dim_names):
            if d != sp_axis and mesh.ndim > 1:
                mesh = mesh.get_mesh_with_dim(d, 0)
        return mesh, sp_axis

    @property
    def sp_degree(self) -> int:
        """Ranks a sequence-parallel chunk stripes across (1 = the
        plane is off and every prompt takes the single-device path)."""
        return self._sp_n if self._jit_chunk_sp is not None else 1

    def sp_min_tokens_effective(self) -> int:
        """The sequence-parallel length threshold the scheduler plans
        with: the raw PT_SP_PREFILL_MIN_TOKENS, floor-quantized onto
        the armed bucket ladder so the threshold sits ON a warmed rung
        — AOT warmup covers every (prefill_sp x rung) pair at or above
        it and a sealed engine never misses.  Below the lowest rung the
        lowest rung is the floor."""
        raw = self._sp_min_tokens
        ladder = self.aot_ladder
        if ladder is None:
            return raw
        rung = ladder.floor(raw)
        return int(rung) if rung is not None else int(min(ladder.rungs))

    # speculative-decode audit counters, kept as properties over the
    # CountedJit wrapper: traces counts how many times _verify_fwd was
    # TRACED (re-traces mean shape churn), dispatches how many verify
    # steps ran — the no-host-loop test asserts dispatches >> traces
    # while tokens >> dispatches
    @property
    def verify_traces(self) -> int:
        return self._jit_verify.traces

    @property
    def verify_dispatches(self) -> int:
        return self._jit_verify.dispatches

    def _pools(self):
        """The jit-argument form of the KV pools: the bare page arrays
        in the plain mode (byte-identical signatures to r18), or
        ``(pages, scales)`` tuples on an int8 pool — jit flattens the
        tuple, donation covers every leaf, and the forwards branch on
        the pytree form at trace time."""
        c = self.cache
        if self.quant == "int8":
            return (c.k_pages, c.k_scales), (c.v_pages, c.v_scales)
        return c.k_pages, c.v_pages

    def _set_pools(self, kps, vps):
        """Store a program's updated pool outputs back on the cache."""
        c = self.cache
        if self.quant == "int8":
            (c.k_pages, c.k_scales), (c.v_pages, c.v_scales) = kps, vps
        else:
            c.k_pages, c.v_pages = kps, vps

    def _pool_sds(self):
        """ShapeDtypeStruct mirror of :meth:`_pools` for contracts and
        AOT warmup."""
        c = self.cache
        kp = jax.ShapeDtypeStruct(jnp.shape(c.k_pages),
                                  c.k_pages.dtype)
        if self.quant == "int8":
            sc = jax.ShapeDtypeStruct(jnp.shape(c.k_scales),
                                      c.k_scales.dtype)
            return (kp, sc)
        return kp

    def _register_contracts(self):
        """Register the serving programs' graph contracts at
        representative shapes (lint traces ShapeDtypeStructs only — no
        device work).  Chunk shapes pick past cover == chunk length so
        the donation aliasing opportunity is visible to the checker.

        Quantized builds register under ``.int8``-suffixed names: the
        registry is replace-by-name and lint_graph builds BOTH engine
        flavors, so the suffix keeps the quantized decode/verify
        programs linted alongside (not instead of) the plain ones.  The
        contract ``compute_dtype`` comes from the cache's COMPUTE dtype,
        never the pool storage dtype — the int8→f32 dequant inside the
        programs is the point, not an upcast violation."""
        cache = self.cache
        cfg = self.config
        L = cfg.num_hidden_layers
        KV, D = cfg.num_key_value_heads, cfg.head_dim
        ps, B, pps = cache.page_size, cache.max_seqs, \
            cache.max_pages_per_seq
        sfx = ".int8" if self.quant == "int8" else ""

        def sds(tree):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a), a.dtype),
                tree)

        def i32(*shape):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        layers, tops = sds(self.layers), sds(self.tops)
        kp = self._pool_sds()
        past = jax.ShapeDtypeStruct((L, KV, ps, D),
                                    cache.compute_dtype)
        # reduced-precision compute => bf16 serving build: flag big f32
        # intermediates as upcasts (f32 builds skip the check)
        cd = np.dtype(cache.compute_dtype)
        common = dict(
            compute_dtype=str(cd) if cd.itemsize < 4 else None,
            # single-device programs must stay collective-free
            expected_collectives={},
            # checkpoint restore sweeps this hook (registry.aot_warmup)
            # so a rolled-back replica re-warms its executables; a no-op
            # until the engine has run aot_warmup once
            aot_hook=self._aot_rewarm,
        )
        register_program(ProgramContract(
            name="serve.prefill" + sfx, fn=self._prefill_fwd,
            args=(layers, tops, i32(1, 2 * ps)), **common))
        register_program(ProgramContract(
            name="serve.prefill_chunk" + sfx, fn=self._chunk_fwd,
            args=(layers, tops, i32(1, ps), i32(), past, past, i32()),
            donate_argnums=self._jit_chunk.donate_argnums, **common))
        if self._jit_chunk_sp is not None:
            # the ONLY serving program allowed collectives, and its
            # inventory is exact: the per-layer ring-gather costs
            # 2*(n-1) ppermute hops (k and v, counted once for the
            # scan body), and the final-logits row costs exactly one
            # all_gather at the end — anything else (a stray psum, a
            # per-layer all_gather) is a regression lint must catch.
            # Host-sync stays banned like every serving program.
            nsp = self._sp_n
            register_program(ProgramContract(
                name="serve.prefill_sp" + sfx, fn=self._sp_chunk_fwd,
                args=(layers, tops, i32(1, nsp * max(2, ps)), i32(),
                      past, past, i32()),
                donate_argnums=self._jit_chunk_sp.donate_argnums,
                **{**common,
                   "expected_collectives": {"ppermute": 2 * (nsp - 1),
                                            "all_gather": 1}}))
        register_program(ProgramContract(
            name="serve.decode" + sfx, fn=self._decode_fwd,
            args=(layers, tops, i32(B), i32(B), kp, kp, i32(B),
                  i32(B, pps)),
            donate_argnums=self._jit_decode.donate_argnums, **common))
        register_program(ProgramContract(
            name="serve.decode_async" + sfx, fn=self._decode_tok_fwd,
            args=(layers, tops, i32(B), i32(B), kp, kp, i32(B),
                  i32(B, pps)),
            donate_argnums=self._jit_decode_async.donate_argnums,
            **common))
        register_program(ProgramContract(
            name="serve.decode_n" + sfx, fn=self._decode_n_fwd,
            args=(layers, tops, i32(B), i32(B), kp, kp, i32(B),
                  i32(B, pps)),
            kwargs={"n": 2},
            donate_argnums=self._jit_decode_n.donate_argnums, **common))
        register_program(ProgramContract(
            name="serve.verify" + sfx, fn=self._verify_fwd,
            args=(layers, tops, i32(B, 2), kp, kp, i32(B), i32(B, pps),
                  i32(B)),
            donate_argnums=self._jit_verify.donate_argnums, **common))

    # -- AOT warmup (core/aot.py) ---------------------------------------

    def aot_warmup(self, prefill_chunk=None, compile_cache=None,
                   spec_window=None, decode_n_steps=(), ladder=None):
        """Pre-compile every (program x shape-rung) pair the bucketed
        executor can dispatch, so a warmed engine serves with ZERO
        post-warmup traces.

        The shape universe is finite by construction:

        * ``serve.prefill_chunk`` — chunk length runs over the pow2
          ``ladder`` rungs (the scheduler floor-quantizes onto them and
          any prompt decomposes into descending rungs), past-KV cover
          over the feasible page buckets (a chunk of C at rung r can
          only ever see ``<= ceil((max_len - C) / page_size)`` past
          pages).  Whole-prompt prefill is routed through this program
          (``serve.prefill`` has an unbounded [1, S] shape — the reason
          chunking exists).
        * ``serve.decode`` / ``serve.decode_async`` / ``serve.verify``
          — batch runs over exactly 1..max_seqs (``verify`` only when
          ``spec_window`` gives the draft window W = k + 1).
        * ``serve.decode_n`` — per requested static ``n``.

        Each entry resolves warm (already in-process) / disk (the
        persistent ``compile_cache``) / compile; a failing entry is
        recorded and skipped — warmup must never take the engine down.
        Returns the warmup report and arms ``self.aot_ladder``.
        """
        import time as _time

        from ...core import aot

        kvc = self.cache
        cfg = self.config
        L = cfg.num_hidden_layers
        KV, D = cfg.num_key_value_heads, cfg.head_dim
        ps, pps = kvc.page_size, kvc.max_pages_per_seq
        # past-KV gathers come back dense in the COMPUTE dtype (int8
        # pools dequantize inside gather_dense), so the chunk program's
        # past SDS must not mirror the pool storage dtype
        past_dt = kvc.compute_dtype

        def sds(tree):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a), a.dtype),
                tree)

        def i32(*shape):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        layers, tops = sds(self.layers), sds(self.tops)
        kp = self._pool_sds()

        cap = (min(int(prefill_chunk), self.max_len)
               if prefill_chunk else self.max_len)
        if ladder is None:
            ladder = aot.BucketLadder.pow2(cap)
        buckets = aot.page_buckets(pps)

        plan = []  # (CountedJit, args, kwargs)
        for C in ladder.rungs:
            # feasible past covers for a chunk of C: the chunk's last
            # token still fits in max_len, so past <= max_len - C
            pmax = aot.bucket_pages(-(-(self.max_len - C) // ps),
                                    buckets)
            for b in (x for x in buckets if x <= pmax):
                past = jax.ShapeDtypeStruct((L, KV, b * ps, D), past_dt)
                plan.append((self._jit_chunk,
                             (layers, tops, i32(1, C), i32(), past,
                              past, i32()), {}))
        if self.sp_degree > 1:
            # sequence-parallel rungs: a chunk only stripes when its
            # length splits evenly across the ranks, so warmup covers
            # exactly the (prefill_sp x rung) pairs the scheduler can
            # dispatch — the sp_min_tokens_effective() floor sits on a
            # rung by construction
            nsp = self._sp_n
            for C in (c for c in ladder.rungs
                      if c % nsp == 0 and c >= 2 * nsp):
                pmax = aot.bucket_pages(-(-(self.max_len - C) // ps),
                                        buckets)
                for b in (x for x in buckets if x <= pmax):
                    past = jax.ShapeDtypeStruct((L, KV, b * ps, D),
                                                past_dt)
                    plan.append((self._jit_chunk_sp,
                                 (layers, tops, i32(1, C), i32(),
                                  past, past, i32()), {}))
        for B in range(1, kvc.max_seqs + 1):
            dec = (layers, tops, i32(B), i32(B), kp, kp, i32(B),
                   i32(B, pps))
            plan.append((self._jit_decode, dec, {}))
            plan.append((self._jit_decode_async, dec, {}))
            for n in decode_n_steps:
                plan.append((self._jit_decode_n, dec, {"n": int(n)}))
            if spec_window:
                plan.append((self._jit_verify,
                             (layers, tops, i32(B, int(spec_window)),
                              kp, kp, i32(B), i32(B, pps), i32(B)), {}))

        t0 = _time.perf_counter()
        report = {"compile": 0, "disk": 0, "warm": 0, "failed": [],
                  "programs": {}, "ladder": ladder.rungs,
                  "page_buckets": buckets}
        for prog, args, kwargs in plan:
            try:
                how = prog.aot_compile(args, kwargs,
                                       cache=compile_cache)
            except Exception as e:  # a failed entry must not kill warmup
                report["failed"].append((prog.name, str(e)))
                continue
            report[how] += 1
            report["programs"][prog.name] = \
                report["programs"].get(prog.name, 0) + 1
        report["entries"] = len(plan)
        report["seconds"] = round(_time.perf_counter() - t0, 3)

        self.aot_ladder = ladder
        self._aot_page_buckets = buckets
        self._aot_config = dict(prefill_chunk=prefill_chunk,
                                compile_cache=compile_cache,
                                spec_window=spec_window,
                                decode_n_steps=tuple(decode_n_steps),
                                ladder=ladder)
        h = obs.handle()
        if h is not None:
            h.recorder.record("aot.warmup", **{
                k: report[k] for k in
                ("compile", "disk", "warm", "entries", "seconds")})
        return report

    def _aot_rewarm(self):
        """Contract ``aot_hook``: re-run the last warmup configuration
        (checkpoint restore / guardian rollback path); no-op until the
        engine has warmed once."""
        if self._aot_config is None:
            return None
        return self.aot_warmup(**self._aot_config)

    def seal(self):
        """PT_AOT=strict: forbid post-warmup compilation.  Every warmed
        program's table is sealed (a miss raises AotMissError) and
        whole-prompt ``prefill`` — un-bucketable, routed through chunks
        by the scheduler — starts refusing direct calls too."""
        if self.aot_ladder is None:
            raise ValueError("seal() before aot_warmup()")
        for prog in self.programs.values():
            if prog._exe:
                prog.seal()
        self._aot_sealed = True

    def _head(self, x, tops):
        w = tops["head_w"]
        return x @ (w.T if self._tied else w)

    # -- pure forwards --------------------------------------------------

    def _prefill_fwd(self, layers, tops, ids):
        """[1, S] prompt -> (last-token logits [V], k [L,KV,S,D],
        v [L,KV,S,D]) — plain causal attention, KV returned for the
        page writer."""
        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        B, S = ids.shape
        x = tops["embed"][ids]
        pos = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        scale = 1.0 / np.sqrt(d)

        def block(x, lp):
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = _mm(h, lp["self_attn.q_proj.weight"]) \
                .reshape(B, S, nh, d)
            k = _mm(h, lp["self_attn.k_proj.weight"]) \
                .reshape(B, S, nkv, d)
            v = _mm(h, lp["self_attn.v_proj.weight"]) \
                .reshape(B, S, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            g = nh // nkv
            qt = jnp.swapaxes(q, 1, 2)              # [B, nh, S, d]
            kt = jnp.swapaxes(k, 1, 2)              # [B, nkv, S, d]
            vt = jnp.swapaxes(v, 1, 2)
            if g > 1:                               # GQA: expand KV heads
                kt = jnp.repeat(kt, g, axis=1)
                vt = jnp.repeat(vt, g, axis=1)
            # standard 4-D attention: the 5-D grouped einsum + rank-5
            # masked-broadcast variant compiled pathologically slowly on
            # the TPU AOT path (95s+ for 2 layers; minutes at vocab 32k)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
            causal = jnp.tril(jnp.ones((S, S), bool))
            logits = jnp.where(causal[None, None], logits,
                               jnp.finfo(logits.dtype).min)
            p = jax.nn.softmax(logits.astype(jnp.float32), -1) \
                .astype(x.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
            o = jnp.swapaxes(o, 1, 2).reshape(B, S, nh * d)
            x = x + _mm(o, lp["self_attn.o_proj.weight"])
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = _mm(h2, lp["mlp.gate_proj.weight"])
            up = _mm(h2, lp["mlp.up_proj.weight"])
            x = x + _mm(jax.nn.silu(gate) * up,
                        lp["mlp.down_proj.weight"])
            return x, (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))

        x, (ks, vs) = jax.lax.scan(block, x, layers)
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        return self._head(x[:, -1], tops)[0], ks[:, 0], vs[:, 0]

    def _chunk_fwd(self, layers, tops, ids, pos0, past_k, past_v,
                   past_len):
        """Chunked-prefill forward: ids [1, C] at positions
        ``pos0..pos0+C-1``; past_k/past_v [L, KV, P, D] are the
        sequence's already-written KV gathered dense (P = page-multiple
        cover, positions >= past_len masked).  Returns (last-position
        logits [V], chunk k [L,KV,C,D], chunk v [L,KV,C,D]).

        This is what lets the scheduler interleave one long prompt's
        prefill with in-flight decodes: each scheduler iteration runs
        ONE chunk, so a 10k-token prompt never stalls the decode batch
        for its whole prefill."""
        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        B, C = ids.shape
        P = past_k.shape[2]
        x = tops["embed"][ids]
        pos = pos0 + jnp.broadcast_to(jnp.arange(C)[None], (B, C))
        scale = 1.0 / np.sqrt(d)
        # past cols valid below past_len; chunk cols causal within chunk
        mask = jnp.concatenate(
            [jnp.broadcast_to((jnp.arange(P) < past_len)[None], (C, P)),
             jnp.tril(jnp.ones((C, C), bool))], axis=1)  # [C, P+C]

        def block(x, lp_kv):
            lp, pk, pv = lp_kv
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = _mm(h, lp["self_attn.q_proj.weight"]) \
                .reshape(B, C, nh, d)
            k = _mm(h, lp["self_attn.k_proj.weight"]) \
                .reshape(B, C, nkv, d)
            v = _mm(h, lp["self_attn.v_proj.weight"]) \
                .reshape(B, C, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            g = nh // nkv
            qt = jnp.swapaxes(q, 1, 2)              # [B, nh, C, d]
            kt = jnp.swapaxes(k, 1, 2)              # [B, nkv, C, d]
            vt = jnp.swapaxes(v, 1, 2)
            kf = jnp.concatenate([pk[None].astype(kt.dtype), kt], axis=2)
            vf = jnp.concatenate([pv[None].astype(vt.dtype), vt], axis=2)
            if g > 1:                               # GQA: expand KV heads
                kf = jnp.repeat(kf, g, axis=1)
                vf = jnp.repeat(vf, g, axis=1)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kf) * scale
            logits = jnp.where(mask[None, None], logits,
                               jnp.finfo(logits.dtype).min)
            p = jax.nn.softmax(logits.astype(jnp.float32), -1) \
                .astype(x.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
            o = jnp.swapaxes(o, 1, 2).reshape(B, C, nh * d)
            x = x + _mm(o, lp["self_attn.o_proj.weight"])
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = _mm(h2, lp["mlp.gate_proj.weight"])
            up = _mm(h2, lp["mlp.up_proj.weight"])
            x = x + _mm(jax.nn.silu(gate) * up,
                        lp["mlp.down_proj.weight"])
            return x, (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))

        x, (ks, vs) = jax.lax.scan(block, x, (layers, past_k, past_v))
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        return self._head(x[:, -1], tops)[0], ks[:, 0], vs[:, 0]

    def _sp_chunk_fwd(self, layers, tops, ids, pos0, past_k, past_v,
                      past_len):
        """Sequence-parallel twin of :meth:`_chunk_fwd`: the chunk's
        ``C`` rows stripe contiguously across the mesh's sp axis (rank
        r owns rows ``[r*C/n, (r+1)*C/n)``), weights/past-KV stay
        replicated, and the outputs are the SAME (logits [V], chunk k/v
        [L, KV, C, D]) — k/v assembled sequence-sharded by the
        out_specs.

        Bit-identity with the single-device program is the design
        constraint (the off-gate, recovery and the prefix cache all
        compare token streams exactly), which rules out the training
        ring's online softmax: instead each rank ring-gathers the chunk
        K/V into canonical order (:func:`ring_gather_seq`, n-1 ppermute
        hops each for k and v) and runs the unmodified dense
        mask/softmax/PV math on its row stripe, so every per-(row, col)
        dot product — and every reduction order — is byte-for-byte the
        dense path's.  The final logits row lives on the last rank, so
        one ``all_gather`` of the last hidden row ends the program:
        total collective inventory exactly {ppermute: 2*(n-1),
        all_gather: 1}, which the registered contract pins.

        ``check_vma=False``: the all_gather-derived replication of the
        logits output is not statically inferable by the old check_rep
        machinery this jax's shard_map shim maps onto."""
        rep = _P()
        mapped = jax.shard_map(
            self._sp_chunk_local, mesh=self._sp_jmesh,
            in_specs=(jax.tree.map(lambda _: rep, layers),
                      jax.tree.map(lambda _: rep, tops),
                      _P(None, self._sp_axis), rep, rep, rep, rep),
            out_specs=(rep, _P(None, None, self._sp_axis, None),
                       _P(None, None, self._sp_axis, None)),
            check_vma=False)
        return mapped(layers, tops, ids, pos0, past_k, past_v,
                      past_len)

    def _sp_chunk_local(self, layers, tops, ids, pos0, past_k, past_v,
                        past_len):
        """Per-rank body of :meth:`_sp_chunk_fwd`.  ``ids`` [1, C/n] is
        this rank's row stripe; everything else is replicated."""
        from ...distributed.ring_attention import ring_gather_seq

        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        axis, n = self._sp_axis, self._sp_n
        B, Cl = ids.shape
        C = Cl * n
        P = past_k.shape[2]
        r = jax.lax.axis_index(axis)
        x = tops["embed"][ids]
        rows = r * Cl + jnp.arange(Cl)               # global row ids
        pos = pos0 + jnp.broadcast_to(rows[None], (B, Cl))
        scale = 1.0 / np.sqrt(d)
        # same mask as _chunk_fwd, restricted to this rank's rows:
        # past cols valid below past_len; chunk cols causal globally
        mask = jnp.concatenate(
            [jnp.broadcast_to((jnp.arange(P) < past_len)[None],
                              (Cl, P)),
             rows[:, None] >= jnp.arange(C)[None]], axis=1)

        def block(x, lp_kv):
            lp, pk, pv = lp_kv
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = _mm(h, lp["self_attn.q_proj.weight"]) \
                .reshape(B, Cl, nh, d)
            k = _mm(h, lp["self_attn.k_proj.weight"]) \
                .reshape(B, Cl, nkv, d)
            v = _mm(h, lp["self_attn.v_proj.weight"]) \
                .reshape(B, Cl, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            g = nh // nkv
            qt = jnp.swapaxes(q, 1, 2)              # [B, nh, Cl, d]
            kt = jnp.swapaxes(k, 1, 2)              # [B, nkv, Cl, d]
            vt = jnp.swapaxes(v, 1, 2)
            # every rank needs every chunk key: ring-gather the K/V
            # stripes into canonical order (the bit-exact alternative
            # to streaming blocks through an online softmax)
            ktf = ring_gather_seq(kt, axis, n)      # [B, nkv, C, d]
            vtf = ring_gather_seq(vt, axis, n)
            kf = jnp.concatenate([pk[None].astype(ktf.dtype), ktf],
                                 axis=2)
            vf = jnp.concatenate([pv[None].astype(vtf.dtype), vtf],
                                 axis=2)
            if g > 1:                               # GQA: expand KV heads
                kf = jnp.repeat(kf, g, axis=1)
                vf = jnp.repeat(vf, g, axis=1)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kf) * scale
            logits = jnp.where(mask[None, None], logits,
                               jnp.finfo(logits.dtype).min)
            p = jax.nn.softmax(logits.astype(jnp.float32), -1) \
                .astype(x.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
            o = jnp.swapaxes(o, 1, 2).reshape(B, Cl, nh * d)
            x = x + _mm(o, lp["self_attn.o_proj.weight"])
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = _mm(h2, lp["mlp.gate_proj.weight"])
            up = _mm(h2, lp["mlp.up_proj.weight"])
            x = x + _mm(jax.nn.silu(gate) * up,
                        lp["mlp.down_proj.weight"])
            return x, (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))

        x, (ks, vs) = jax.lax.scan(block, x, (layers, past_k, past_v))
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        # the chunk's last row lives on the last rank: one all_gather
        # of the final hidden row, then every rank computes the same
        # replicated logits (the head matmul is cheap at [1, V])
        last = jax.lax.all_gather(x[:, -1], axis)     # [n, B, h]
        return self._head(last[n - 1], tops)[0], ks[:, 0], vs[:, 0]

    def _decode_fwd(self, layers, tops, ids, positions, k_pages, v_pages,
                    lengths, page_tables):
        """One token per active sequence: ids [B], positions [B] (the
        token's position).  Each layer writes the new token's KV into
        its page (write-then-attend, so the paged attention over
        lengths+1 includes the self term), then attends over the pool.
        Returns (logits [B, V], k_pages', v_pages')."""
        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        ps = self.cache.page_size
        B = ids.shape[0]
        x = tops["embed"][ids][:, None]           # [B, 1, h]
        pos = positions[:, None]
        pids = page_tables[jnp.arange(B), positions // ps]  # [B]
        offs = positions % ps

        def block(x, lp_kv):
            lp, kp, vp = lp_kv
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = _mm(h, lp["self_attn.q_proj.weight"]) \
                .reshape(B, 1, nh, d)
            k = _mm(h, lp["self_attn.k_proj.weight"]) \
                .reshape(B, 1, nkv, d)
            v = _mm(h, lp["self_attn.v_proj.weight"]) \
                .reshape(B, 1, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            kh = jnp.swapaxes(k, 1, 2)[:, :, 0]   # [B, nkv, d]
            vh = jnp.swapaxes(v, 1, 2)[:, :, 0]
            if isinstance(kp, tuple):
                # int8 pool slice (pages, per-page scales): quantize
                # the new token on write (scale grow + resident
                # requant), attend with the scales threaded through
                kp = _quant.kv_write(kp[0], kp[1], pids, offs,
                                     jnp.swapaxes(kh, 0, 1))
                vp = _quant.kv_write(vp[0], vp[1], pids, offs,
                                     jnp.swapaxes(vh, 0, 1))
                o = paged_decode_attention(
                    jnp.swapaxes(q, 1, 2)[:, :, 0], kp[0], vp[0],
                    lengths + 1, page_tables,
                    k_scales=kp[1], v_scales=vp[1])
            else:
                kp = kp.at[:, pids, offs].set(
                    jnp.swapaxes(kh, 0, 1).astype(kp.dtype))
                vp = vp.at[:, pids, offs].set(
                    jnp.swapaxes(vh, 0, 1).astype(vp.dtype))
                o = paged_decode_attention(
                    jnp.swapaxes(q, 1, 2)[:, :, 0], kp, vp,
                    lengths + 1, page_tables)     # [B, nh, d]
            o = o.reshape(B, 1, nh * d).astype(x.dtype)
            x = x + _mm(o, lp["self_attn.o_proj.weight"])
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = _mm(h2, lp["mlp.gate_proj.weight"])
            up = _mm(h2, lp["mlp.up_proj.weight"])
            x = x + _mm(jax.nn.silu(gate) * up,
                        lp["mlp.down_proj.weight"])
            return x, (kp, vp)

        x, (kps, vps) = jax.lax.scan(
            block, x, (layers, k_pages, v_pages))
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        return self._head(x[:, 0], tops), kps, vps

    def _decode_tok_fwd(self, layers, tops, ids, positions, k_pages,
                        v_pages, lengths, page_tables):
        """:meth:`_decode_fwd` with the greedy argmax folded in-graph
        (the spec-verify program already does this): the async executor
        keeps the step's entire host sync down to one int32 [B]
        transfer at the commit fence.  Returns (tokens [B], k_pages',
        v_pages')."""
        logits, kps, vps = self._decode_fwd(
            layers, tops, ids, positions, k_pages, v_pages, lengths,
            page_tables)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), kps, vps

    def _verify_fwd(self, layers, tops, ids, k_pages, v_pages, lengths,
                    page_tables, limits):
        """Speculative-verify forward: every running sequence's draft
        window in ONE program.  ``ids`` [B, W] is each sequence's last
        committed token followed by its (padded) draft; window token w
        sits at position ``lengths[b] + w``.  ``limits`` [B] caps how
        many window tokens each sequence may commit (page budget /
        length cap / actual draft length), 1 <= limit <= W.

        Write-then-attend like _decode_fwd, widened to the window: each
        layer scatters all valid window KV into the pages (positions
        past a sequence's limit are pushed out of bounds and dropped),
        then attends with B*W query rows through the SAME
        paged_decode_attention — row (b, w) masked to lengths[b]+w+1
        keys, so causality inside the window comes from the length
        mask, not a new kernel.

        Greedy acceptance in-graph: with t = argmax(logits) per window
        position, draft token w+1 is accepted iff every earlier draft
        token matched the model's choice — so the committed stream is
        bit-identical to plain greedy decode by construction.  The
        ragged accepted prefixes are packed with one variadic
        ``lax.sort`` (the MoE-dispatch trick): valid (b, w) cells keep
        their rank key, invalid cells sort to the tail, and the host
        reads ONE dense token vector + per-seq counts — no [B, k] host
        loop anywhere.

        Returns (packed_tokens [B*W], emit_n [B], k_pages', v_pages').
        """
        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        ps = self.cache.page_size
        B, W = ids.shape
        pps = page_tables.shape[1]
        num_pages = (k_pages[0] if isinstance(k_pages, tuple)
                     else k_pages).shape[2]
        x = tops["embed"][ids]                         # [B, W, h]
        pos = lengths[:, None] + jnp.arange(W)[None]   # [B, W]
        slot = pos // ps
        pids = jnp.take_along_axis(page_tables,
                                   jnp.minimum(slot, pps - 1), axis=1)
        # invalid window cells (past the commit limit, or past the
        # per-seq page budget) write out of bounds -> mode='drop'
        valid_w = ((jnp.arange(W)[None] < limits[:, None])
                   & (slot < pps))
        pids = jnp.where(valid_w, pids, num_pages).reshape(-1)
        offs = (pos % ps).reshape(-1)
        # one attention row per window cell; the +w+1 length mask is
        # the in-window causal mask
        lens_f = (lengths[:, None] + jnp.arange(W)[None] + 1).reshape(-1)
        tables_f = jnp.repeat(page_tables, W, axis=0)  # [B*W, pps]

        def block(x, lp_kv):
            lp, kp, vp = lp_kv
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = _mm(h, lp["self_attn.q_proj.weight"]) \
                .reshape(B, W, nh, d)
            k = _mm(h, lp["self_attn.k_proj.weight"]) \
                .reshape(B, W, nkv, d)
            v = _mm(h, lp["self_attn.v_proj.weight"]) \
                .reshape(B, W, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            kf = jnp.swapaxes(k.reshape(B * W, nkv, d), 0, 1)
            vf = jnp.swapaxes(v.reshape(B * W, nkv, d), 0, 1)
            if isinstance(kp, tuple):
                # kv_write scatters with mode='drop' throughout, so the
                # num_pages sentinel pid of invalid window cells is
                # dropped exactly like the plain path's scatter
                kp = _quant.kv_write(kp[0], kp[1], pids, offs, kf)
                vp = _quant.kv_write(vp[0], vp[1], pids, offs, vf)
                o = paged_decode_attention(
                    q.reshape(B * W, nh, d), kp[0], vp[0], lens_f,
                    tables_f, k_scales=kp[1], v_scales=vp[1])
            else:
                kp = kp.at[:, pids, offs].set(kf.astype(kp.dtype),
                                              mode="drop")
                vp = vp.at[:, pids, offs].set(vf.astype(vp.dtype),
                                              mode="drop")
                o = paged_decode_attention(
                    q.reshape(B * W, nh, d), kp, vp, lens_f, tables_f)
            o = o.reshape(B, W, nh * d).astype(x.dtype)
            x = x + _mm(o, lp["self_attn.o_proj.weight"])
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = _mm(h2, lp["mlp.gate_proj.weight"])
            up = _mm(h2, lp["mlp.up_proj.weight"])
            x = x + _mm(jax.nn.silu(gate) * up,
                        lp["mlp.down_proj.weight"])
            return x, (kp, vp)

        x, (kps, vps) = jax.lax.scan(
            block, x, (layers, k_pages, v_pages))
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        t = jnp.argmax(self._head(x, tops), -1).astype(jnp.int32)
        # accepted = longest prefix of drafts matching the model's own
        # greedy choices; always commit 1 + accepted (the model's next
        # token after the accepted run), clamped to the per-seq limit
        m = (ids[:, 1:] == t[:, :-1]).astype(jnp.int32)
        acc = jnp.sum(jnp.cumprod(m, axis=1), axis=1)
        emit_n = jnp.minimum(acc + 1, limits)
        rank = jnp.arange(B * W, dtype=jnp.int32).reshape(B, W)
        key = jnp.where(jnp.arange(W)[None] < emit_n[:, None],
                        rank, B * W).reshape(-1)
        _, packed = jax.lax.sort((key, t.reshape(-1)), num_keys=1,
                                 is_stable=True)
        return packed, emit_n, kps, vps

    def _decode_n_fwd(self, layers, tops, ids, positions, k_pages,
                      v_pages, lengths, page_tables, n):
        """``n`` greedy steps in ONE dispatched program: the argmax
        feedback stays on device (greedy needs no host), so the
        per-token tunnel/dispatch cost is amortized n ways — the decode
        analog of CompiledTrainStep.multi_step."""

        def body(carry, _):
            ids, positions, kp, vp, lengths = carry
            logits, kp, vp = self._decode_fwd(
                layers, tops, ids, positions, kp, vp, lengths,
                page_tables)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return (nxt, positions + 1, kp, vp, lengths + 1), nxt

        carry, toks = jax.lax.scan(
            body, (ids, positions, k_pages, v_pages, lengths), None,
            length=n)
        _ids, _pos, kp, vp, _len = carry
        return toks, kp, vp

    # -- slot-granular control plane ------------------------------------

    @property
    def free_slots(self) -> int:
        return self.cache.free_slots

    @property
    def free_pages(self) -> int:
        return self.cache.free_pages

    def pages_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.cache.page_size)

    def alloc_slot(self) -> int:
        return self.cache.allocate()

    def free_slot(self, sid: int) -> None:
        self.cache.free(sid)
        self.last_token.pop(sid, None)
        self._sp_written.discard(sid)

    def attach_prefix(self, sid: int, page_ids, n_tokens: int) -> None:
        """Point a fresh slot's page table at already-computed prefix
        pages (cache hit): chunked prefill then starts at token
        ``n_tokens`` instead of 0."""
        self.cache.attach(sid, page_ids, n_tokens)

    def prepare_write(self, sid: int, start: int, n_tokens: int) -> None:
        """Pre-commit the page work for a prefill chunk covering
        positions [start, start + n_tokens): allocate missing pages
        (prefix eviction is tried before pool-exhausted) and
        copy-on-write any shared page in the window.  The scheduler
        calls this BEFORE its per-request fault bracket so a pool raise
        or an injected ``prefix.cow`` fault preempts/retries cleanly
        instead of failing the request."""
        self.cache._ensure_capacity(sid, start + n_tokens)
        self.cache.make_writable(sid, start, start + n_tokens)

    def prefill(self, sid: int, prompt_ids) -> int:
        """Whole-prompt prefill into an allocated slot; returns the
        first greedy token."""
        if self._aot_sealed:
            from ...core.aot import AotMissError

            raise AotMissError(
                "[serve.prefill] PT_AOT=strict: whole-prompt prefill "
                "has an unbounded [1, S] shape and cannot be warmed — "
                "the scheduler routes prompts through prefill_chunk's "
                "bucket ladder instead")
        ids = jnp.asarray(np.asarray(prompt_ids)[None], jnp.int32)
        self.prefill_events.append((sid, int(ids.shape[1])))
        logits, k, v = self._jit_prefill(self.layers, self.tops, ids)
        self.cache.prefill(sid, k, v)
        tok = int(jnp.argmax(logits))
        self.last_token[sid] = tok
        return tok

    def prefill_chunk(self, sid: int, chunk_ids, start: int,
                      final: bool) -> int | None:
        """One prefill chunk at position ``start``; attends the slot's
        already-written pages.  When ``final``, records and returns the
        prompt's first greedy token; else returns None."""
        past_k, past_v = self.cache.gather_dense(sid, start)
        if self.aot_ladder is not None:
            # bucket the past cover so its shape comes from the finite
            # warmup set: pad to the next page bucket with zeros — the
            # in-graph `arange(P) < past_len` mask drops the padding's
            # contribution entirely, so numerics are exact
            from ...core.aot import bucket_pages

            ps = self.cache.page_size
            pages = past_k.shape[2] // ps
            b = bucket_pages(pages, self._aot_page_buckets)
            if b > pages:
                pad = ((0, 0), (0, 0), (0, (b - pages) * ps), (0, 0))
                past_k = jnp.pad(past_k, pad)
                past_v = jnp.pad(past_v, pad)
        ids = jnp.asarray(np.asarray(chunk_ids)[None], jnp.int32)
        self.prefill_events.append((sid, int(ids.shape[1])))
        # past_k/past_v are donated: gather_dense returns fresh dense
        # copies nothing else references, and when the past cover
        # equals the chunk length XLA writes the chunk KV in place.
        # Shapes where the alias is impossible (cover != chunk) would
        # warn once per compile — expected, so silenced here.
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            logits, k, v = self._jit_chunk(
                self.layers, self.tops, ids, jnp.int32(start), past_k,
                past_v, jnp.int32(start))
        self.cache.write_at(sid, k, v, start)
        if not final:
            return None
        if sid in self._sp_written:
            # earlier chunks of this prompt landed range-sharded: the
            # prefill->decode page gather still belongs to THIS
            # transition even though the last (short) chunk ran dense
            self.cache.gather_shards(sid)
            self._sp_written.discard(sid)
        tok = int(jnp.argmax(logits))
        self.last_token[sid] = tok
        return tok

    def prefill_sp(self, sid: int, chunk_ids, start: int,
                   final: bool) -> int | None:
        """One SEQUENCE-PARALLEL prefill chunk at position ``start``:
        the chunk stripes across the mesh (serve.prefill_sp), its KV
        lands in the pool as per-rank ranges (``write_sharded``), and
        the final chunk all-gathers the pages once so decode runs
        byte-identical to the single-device path.  Same signature and
        same results as :meth:`prefill_chunk` — the scheduler swaps
        one for the other above the length threshold."""
        n = self.sp_degree
        # stripes of a single row hit XLA's matrix-VECTOR matmul path,
        # whose accumulation order differs from the gemm the dense
        # program runs — measurably (1e-6) non-bit-identical on CPU.
        # A chunk must give every rank >= 2 rows; anything smaller
        # takes the single-device program (same results by definition).
        if n <= 1 or int(np.shape(chunk_ids)[0]) < 2 * n:
            return self.prefill_chunk(sid, chunk_ids, start, final)
        past_k, past_v = self.cache.gather_dense(sid, start)
        if self.aot_ladder is not None:
            # page-bucket the past cover exactly like prefill_chunk:
            # the in-graph past_len mask zeroes the padding
            from ...core.aot import bucket_pages

            ps = self.cache.page_size
            pages = past_k.shape[2] // ps
            b = bucket_pages(pages, self._aot_page_buckets)
            if b > pages:
                pad = ((0, 0), (0, 0), (0, (b - pages) * ps), (0, 0))
                past_k = jnp.pad(past_k, pad)
                past_v = jnp.pad(past_v, pad)
        ids = jnp.asarray(np.asarray(chunk_ids)[None], jnp.int32)
        C = int(ids.shape[1])
        if C % n:
            raise ValueError(
                f"sp prefill chunk of {C} tokens does not stripe over "
                f"{n} ranks — the scheduler must plan sp chunks on "
                f"rank-divisible rungs")
        self.prefill_events.append((sid, C))
        # placement bracket: the pool (and everything derived from it,
        # like the gathered past) lives on the scheduler's home device,
        # while the shard_map program computes over the mesh's device
        # set — committed single-device operands would be refused.  The
        # past-KV broadcast IN and the chunk-KV landing OUT are exactly
        # the per-chunk transfers a range-sharded sp prefill pays, made
        # explicit here so the pool's own placement never changes and
        # the dense programs (plain jit AND rigid AOT-compiled
        # executables) keep their single-device signatures.
        rep = jax.NamedSharding(self._sp_jmesh, _P())
        past_k = jax.device_put(past_k, rep)
        past_v = jax.device_put(past_v, rep)
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            logits, k, v = self._jit_chunk_sp(
                self.layers, self.tops, ids, jnp.int32(start), past_k,
                past_v, jnp.int32(start))
        k = jax.device_put(k, self.cache.k_pages.sharding)
        v = jax.device_put(v, self.cache.v_pages.sharding)
        self.cache.write_sharded(sid, k, v, start, n)
        self._sp_written.add(sid)
        self.sp_prefill_tokens += C
        h = obs.handle()
        if h is not None:
            h.registry.counter(
                "sp_prefill_tokens_total",
                "prompt tokens prefilled sequence-parallel over the "
                "mesh",
            ).inc(C)
        if not final:
            return None
        self.cache.gather_shards(sid)
        self._sp_written.discard(sid)
        tok = int(jnp.argmax(logits))
        self.last_token[sid] = tok
        return tok

    def decode(self, sids) -> dict:
        """One greedy decode step over an explicit batch of slots.
        Returns {sid: next_token}."""
        sids = list(sids)
        if not sids:
            return {}
        cache = self.cache
        # batch-atomic page reservation BEFORE the jitted
        # write-then-attend: a per-sequence loop would strand earlier
        # sequences' fresh pages when a later one exhausts the pool
        cache.reserve(sids, extra_tokens=1)
        # the in-graph page write must never land on a shared page
        for s in sids:
            pos = int(cache.lengths[s])
            cache.make_writable(s, pos, pos + 1)
        ids = jnp.asarray([self.last_token[s] for s in sids], jnp.int32)
        positions = jnp.asarray([int(cache.lengths[s]) for s in sids],
                                jnp.int32)
        tables = jnp.asarray(np.maximum(cache.page_table[sids], 0))
        lengths = jnp.asarray(cache.lengths[sids])
        kp, vp = self._pools()
        logits, kps, vps = self._jit_decode(
            self.layers, self.tops, ids, positions, kp, vp, lengths,
            tables)
        self._set_pools(kps, vps)
        for s in sids:
            cache.lengths[s] += 1
        # single batched argmax + ONE host transfer for the whole step
        toks = np.asarray(jnp.argmax(logits, axis=-1))
        out = {}
        for i, s in enumerate(sids):
            tok = int(toks[i])
            self.last_token[s] = tok
            out[s] = tok
        return out

    def decode_async(self, sids) -> _PendingDecode:
        """Dispatch one greedy decode step WITHOUT realizing the
        result.  All page work and the length bookkeeping happen now —
        so the scheduler can plan the NEXT step against post-step
        lengths while the device runs — and the returned pending
        object's :meth:`~_PendingDecode.wait` is the step's only host
        sync point (one transfer, last-token updates)."""
        sids = list(sids)
        if not sids:
            return _PendingDecode(self, [], np.zeros((0,), np.int32))
        cache = self.cache
        cache.reserve(sids, extra_tokens=1)
        for s in sids:
            pos = int(cache.lengths[s])
            cache.make_writable(s, pos, pos + 1)
        ids = jnp.asarray([self.last_token[s] for s in sids], jnp.int32)
        positions = jnp.asarray([int(cache.lengths[s]) for s in sids],
                                jnp.int32)
        tables = jnp.asarray(np.maximum(cache.page_table[sids], 0))
        lengths = jnp.asarray(cache.lengths[sids])
        kp, vp = self._pools()
        toks, kps, vps = self._jit_decode_async(
            self.layers, self.tops, ids, positions, kp, vp, lengths,
            tables)
        self._set_pools(kps, vps)
        for s in sids:
            cache.lengths[s] += 1
        return _PendingDecode(self, sids, toks)

    def verify(self, sids, drafts, limits, k):
        """Speculative decode step: run each listed slot's draft window
        through one jitted verify forward and commit the longest
        model-agreed prefix plus the model's own next token.

        ``drafts`` and ``limits`` align with ``sids``: up to ``k``
        proposed tokens and the per-seq commit cap (>= 1; the caller
        clamps it to the page budget, the remaining generation cap and
        the actual draft length).  Returns ({sid: [tokens...]},
        {sid: accepted_draft_tokens}); every sequence advances by
        1 + accepted tokens, exactly the greedy stream.
        """
        sids = list(sids)
        if not sids:
            return {}, {}
        cache = self.cache
        W = int(k) + 1
        limits = [int(x) for x in limits]
        # batch-atomic per-seq lookahead reservation, then the COW
        # guard over each window — same write discipline as decode()
        cache.reserve(sids, extra_tokens=limits)
        for s, lim in zip(sids, limits):
            pos = int(cache.lengths[s])
            cache.make_writable(s, pos, pos + lim)
        ids = np.zeros((len(sids), W), np.int32)
        for i, (s, dr) in enumerate(zip(sids, drafts)):
            ids[i, 0] = self.last_token[s]
            dr = np.asarray(dr, np.int32).reshape(-1)[:k]
            ids[i, 1:1 + len(dr)] = dr
        tables = jnp.asarray(np.maximum(cache.page_table[sids], 0))
        lengths = jnp.asarray(cache.lengths[sids])
        kp, vp = self._pools()
        packed, emit_n, kps, vps = self._jit_verify(
            self.layers, self.tops, jnp.asarray(ids), kp, vp, lengths,
            tables, jnp.asarray(limits, jnp.int32))
        self._set_pools(kps, vps)
        # ONE host transfer: the sort-packed token block + counts;
        # splitting it is per-SEQUENCE host work, never per-token-cell
        packed = np.asarray(packed)
        counts = np.asarray(emit_n)
        out, accepted = {}, {}
        off = 0
        for i, s in enumerate(sids):
            n = int(counts[i])
            toks = [int(t) for t in packed[off:off + n]]
            off += n
            cache.lengths[s] += n
            self.last_token[s] = toks[-1]
            out[s] = toks
            accepted[s] = n - 1
        return out, accepted

    def verify_async(self, sids, drafts, limits, k) -> _PendingVerify:
        """:meth:`verify` split at its one natural sync point: the
        jitted window verification is dispatched here (pages reserved,
        windows COW'd, KV written in-graph), and the packed-token /
        count transfers plus all length bookkeeping move into the
        returned pending object's :meth:`~_PendingVerify.wait`."""
        sids = list(sids)
        if not sids:
            return _PendingVerify(self, [], np.zeros((0,), np.int32),
                                  np.zeros((0,), np.int32))
        cache = self.cache
        W = int(k) + 1
        limits = [int(x) for x in limits]
        cache.reserve(sids, extra_tokens=limits)
        for s, lim in zip(sids, limits):
            pos = int(cache.lengths[s])
            cache.make_writable(s, pos, pos + lim)
        ids = np.zeros((len(sids), W), np.int32)
        for i, (s, dr) in enumerate(zip(sids, drafts)):
            ids[i, 0] = self.last_token[s]
            dr = np.asarray(dr, np.int32).reshape(-1)[:k]
            ids[i, 1:1 + len(dr)] = dr
        tables = jnp.asarray(np.maximum(cache.page_table[sids], 0))
        lengths = jnp.asarray(cache.lengths[sids])
        kp, vp = self._pools()
        packed, emit_n, kps, vps = self._jit_verify(
            self.layers, self.tops, jnp.asarray(ids), kp, vp, lengths,
            tables, jnp.asarray(limits, jnp.int32))
        self._set_pools(kps, vps)
        return _PendingVerify(self, sids, packed, emit_n)

    def rollback(self, sids) -> int:
        """Release pages reserved for rejected draft positions: trim
        every listed slot's page table back to its committed length.
        Returns total pages released."""
        freed = sum(self.cache.trim(s) for s in sids)
        self.rollback_pages += freed
        return freed

    def decode_n(self, sids, n) -> dict:
        """``n`` greedy tokens per listed slot in one dispatch.
        Returns {sid: [tok_1..tok_n]}.  Pages for all n tokens are
        reserved up front (batch-atomic), so the in-graph page writes
        can never overflow a sequence's table."""
        sids = list(sids)
        if not sids:
            return {}
        cache = self.cache
        cache.reserve(sids, extra_tokens=n)
        for s in sids:
            pos = int(cache.lengths[s])
            cache.make_writable(s, pos, pos + n)
        ids = jnp.asarray([self.last_token[s] for s in sids], jnp.int32)
        positions = jnp.asarray([int(cache.lengths[s]) for s in sids],
                                jnp.int32)
        tables = jnp.asarray(np.maximum(cache.page_table[sids], 0))
        lengths = jnp.asarray(cache.lengths[sids])
        kp, vp = self._pools()
        toks, kps, vps = self._jit_decode_n(
            self.layers, self.tops, ids, positions, kp, vp, lengths,
            tables, n=int(n))
        self._set_pools(kps, vps)
        toks = np.asarray(toks)                     # [n, B]
        out = {}
        for i, s in enumerate(sids):
            cache.lengths[s] += n
            self.last_token[s] = int(toks[-1, i])
            out[s] = toks[:, i].tolist()
        return out
