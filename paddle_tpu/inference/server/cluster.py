"""Multi-replica serving fleet over the shared logical clock.

One :class:`ServingEngine` is a single box; the fleet wraps N of them
(each with its own page pool and executor) behind a :class:`Router`
that places every request by **prefix affinity** — probe each
replica's radix tree with the read-only
:meth:`PrefixCache.match_len` — falling back to page-pool headroom
and queue depth, so shared-prefix traffic lands where its KV pages
already live (SGLang-style radix-affinity scheduling).  Elastic
scale: :meth:`ServingCluster.drain` closes one replica's admission
and re-steers its queued requests while in-flight work finishes in
place; :meth:`ServingCluster.join` builds a fresh replica whose AOT
warmup resolves from the fleet's shared persistent compile cache, so
a new box serves in seconds.  Opt-in disaggregation
(``disaggregated=True``) splits roles DistServe-style: prefill
replicas compute prompt KV, then ship each finished sequence's pages
to a decode replica as ONE bulk copy through the pool's
``gather_dense``/``write_at`` seams — pages land refcounted, and the
COW/prefix invariants hold on both pools.

Determinism: replicas step in lockstep — one cluster ``step()`` steps
every live replica once — and greedy streams depend only on weights +
prompt (page identity never enters the numerics), so per-request token
streams are bit-identical to a single engine whatever the routing,
and across drain/join re-steers and KV handoffs, in all four serving
variants (plain / prefix / spec / async).

Gate: ``PT_CLUSTER`` (off|on; anything else raises).  Off, the
cluster degenerates to ONE replica with a pass-through router — the
bit-exact single-engine path.

Fault points: ``route.pick`` brackets one placement decision,
``replica.drain`` / ``replica.join`` bracket the elastic transitions,
``kv.handoff`` brackets one page shipment.  All four DEGRADE on an
injected raise — fallback placement, aborted transition, or the
request keeps decoding where it is — never request loss (the
aot.cache discipline: a dead replica is a miss, not a crash).

Survivability (the :class:`ReplicaSupervisor`): replicas heartbeat on
the logical clock; a crash, hang, or escaping exception marks the
replica FAILED and every request it held fails over — re-queued
through the router for a bit-identical prompt+generated re-prefill
(the preemption-recompute idiom) on a healthy replica, handles
untouched.  Failed replicas auto-restart after exponential backoff
(engine rebuilt, AOT re-warmed from the shared persistent compile
cache) under a consecutive-failure circuit breaker that permanently
retires flappers.  Admission control (``max_queue`` +
deadline-aware early rejection) sheds saturating load as terminal
REJECTED-with-retry-after — never silent loss.  Chaos points:
``replica.fail`` (crash/hang/raise consumed in-process),
``replica.restart``, ``req.failover``, ``req.shed``.
"""
from __future__ import annotations

import os
import time
import zlib

import numpy as np

from ... import obs
from ...testing import faults
from . import wal as wal_mod
from .engine import ServingEngine
from .wal import resolve_wal, stream_crc
from .request import (Request, RequestHandle, RequestRejected,
                      RequestState)


def _cluster_enabled() -> bool:
    mode = os.environ.get("PT_CLUSTER", "off").lower()
    if mode not in ("off", "on"):
        raise ValueError(f"PT_CLUSTER={mode!r}: expected off|on")
    return mode == "on"


#: replica lifecycle states (statusz/gauge encoding in this order;
#: the survivability states append so r20 gauge values are unchanged).
REPLICA_STATES = ("active", "draining", "drained",
                  "failed", "restarting", "retired")

#: states in which a replica no longer steps or holds live requests.
DEAD_STATES = ("drained", "failed", "restarting", "retired")


class Replica:
    """One engine plus its fleet-side control state."""

    __slots__ = ("name", "engine", "role", "state",
                 "last_beat", "hung", "fail_streak", "fails",
                 "restarts", "restart_at", "probation_until")

    def __init__(self, name, engine, role="mixed"):
        self.name = name
        self.engine = engine
        self.role = role            # mixed | prefill | decode
        self.state = "active"
        # survivability bookkeeping (the ReplicaSupervisor's state):
        self.last_beat = 0          # cluster tick of the last full step
        self.hung = False           # injected silent stall in progress
        self.fail_streak = 0        # consecutive failures (breaker)
        self.fails = 0              # lifetime failures
        self.restarts = 0           # lifetime successful restarts
        self.restart_at = None      # tick of the next restart attempt
        self.probation_until = None  # healthy-until tick resets streak

    @property
    def depth(self) -> int:
        """Queue depth the router balances on: everything holding or
        waiting for a slot."""
        s = self.engine.scheduler
        return len(s.queue) + len(s.prefilling) + len(s.running)

    @property
    def admitting(self) -> bool:
        return (self.state == "active" and not self.hung
                and self.role in ("mixed", "prefill"))

    def __repr__(self):
        return (f"Replica({self.name}, role={self.role}, "
                f"state={self.state}, depth={self.depth})")


class Router:
    """Placement policy over the admitting replicas.

    ``affinity`` (default): maximize the prefix-affinity probe
    (tokens of the prompt already resident in the replica's radix
    tree), tie-broken by sequence-parallel fit (a long prompt prefers
    a mesh-backed replica that can stripe its prefill), then lowest
    queue depth, then most free pages, then lowest replica index —
    fully deterministic.  ``random``: seeded uniform pick, the bench
    A/B control arm.
    """

    POLICIES = ("affinity", "random")

    def __init__(self, policy="affinity", seed=0):
        if policy not in self.POLICIES:
            raise ValueError(
                f"router policy must be one of {self.POLICIES}, "
                f"got {policy!r}")
        self.policy = policy
        self._rng = np.random.RandomState(seed)
        self.decisions = 0
        self.affinity_hits = 0     # picks that landed on cached pages
        self.degraded = 0          # injected-fault fallback placements

    def pick(self, candidates, prompt_ids):
        """(replica, affinity_tokens) for one request.

        The admitting flag is re-checked HERE, at decision time, not
        just when the candidate list was snapshotted: a replica whose
        ``drain()`` (or failure) landed between the snapshot and the
        pick must never win the placement.  When every candidate went
        stale the original list is kept — the caller owns the
        no-admitting-replica error path.
        """
        live = [r for r in candidates if r.admitting]
        if live:
            candidates = live
        if self.policy == "random":
            return candidates[int(self._rng.randint(
                len(candidates)))], 0
        best, best_key = None, None
        for i, rep in enumerate(candidates):
            prefix = rep.engine.prefix
            aff = (prefix.match_len(prompt_ids)
                   if prefix is not None else 0)
            ex = rep.engine.executor
            # long prompts score toward a mesh-backed replica: when
            # this prompt meets the replica's sequence-parallel
            # threshold, its prefill cost divides by the sp degree
            # there.  Ranked BELOW affinity (resident prefix pages
            # save recompute outright) and ABOVE depth; zero on every
            # replica of an sp-free fleet, so those orderings are
            # byte-identical to r22.
            sp_fit = int(getattr(ex, "sp_degree", 1) > 1
                         and len(prompt_ids)
                         >= ex.sp_min_tokens_effective())
            key = (aff, sp_fit, -rep.depth, ex.free_pages, -i)
            if best is None or key > best_key:
                best, best_key = rep, key
        if best_key[0] > 0:
            self.affinity_hits += 1
        return best, best_key[0]


class ReplicaSupervisor:
    """Crash/hang detection and closed-loop recovery for one fleet.

    Detection is two-pronged, both deterministic on the logical
    clock's side: a replica that completes a step beats
    (``last_beat = cluster tick``, mirrored into the obs heartbeat
    plane as ``replica.<name>``); one that misses ``beat_timeout``
    consecutive beats — a hang, silent or injected — is marked FAILED.
    ``watchdog_s`` (off by default: wall time is nondeterministic)
    additionally bounds one step's wall-clock; a step that finishes
    but blows the deadline fails the replica AFTER its tokens are
    kept (they are valid — greedy streams depend only on weights +
    prompt).

    Failure handling is the tentpole loop: every non-terminal request
    on the failed replica is failed over — re-queued through the
    :class:`Router` for a bit-identical prompt+generated re-prefill
    (the preemption-recompute path; prefix-cache hits make it cheap)
    on a healthy replica, its :class:`RequestHandle` untouched.  With
    no healthy target the request parks on the cluster's orphan list
    (never lost) and re-homes as soon as a replica restarts or joins.

    Restart is automatic (``auto_restart``): after an exponential
    backoff (``backoff_base * 2**(streak-1)`` ticks) the replica
    rebuilds its engine — AOT re-warmed from the fleet's shared
    persistent compile cache, zero new compiles — and rejoins
    admission.  A circuit breaker retires it permanently once
    ``fail_streak`` exceeds ``restart_budget``; the streak resets
    only after the replica survives a probation window
    (``2 * beat_timeout`` ticks), so a flapping replica keeps
    accumulating strikes.
    """

    def __init__(self, cluster, beat_timeout=3, watchdog_s=None,
                 auto_restart=True, restart_budget=3, backoff_base=2):
        if int(beat_timeout) < 1:
            raise ValueError(
                f"beat_timeout must be >= 1, got {beat_timeout}")
        self.cluster = cluster
        self.beat_timeout = int(beat_timeout)
        self.watchdog_s = (None if watchdog_s is None
                           else float(watchdog_s))
        self.auto_restart = bool(auto_restart)
        self.restart_budget = int(restart_budget)
        self.backoff_base = max(1, int(backoff_base))
        self.probation = 2 * self.beat_timeout

    # -- supervised stepping --------------------------------------------

    def step_replica(self, rep) -> dict:
        """One replica step under supervision; returns its emitted
        map ({} when the replica stalled, crashed, or failed)."""
        cl = self.cluster
        hit = faults.consume("replica.fail", "before")
        if hit is not None:
            action = hit[0]
            if action == "hang":
                rep.hung = True     # silent: no step, no beat
            elif action == "crash":
                self.fail(rep, "crash")
                return {}
            else:                   # raise & friends: exception path
                self.fail(rep, f"injected:{action}")
                return {}
        if rep.hung:
            return {}
        t0 = (time.monotonic() if self.watchdog_s is not None
              else None)
        try:
            out = rep.engine.step()
        except Exception as e:
            # one replica's step blowing up must not take the fleet
            # down: confine it, fail the replica, fail over its work.
            self.fail(rep, f"{type(e).__name__}: {e}", error=e)
            return {}
        rep.last_beat = cl._tick
        if cl._obs is not None:
            obs.beat(f"replica.{rep.name}",
                     now=rep.engine.metrics._t_last)
        if t0 is not None and time.monotonic() - t0 > self.watchdog_s:
            # the step finished but blew its wall-clock deadline: the
            # tokens it emitted are valid and are returned — the
            # replica is failed afterwards.
            self.fail(rep, "watchdog")
        return out

    # -- detection + recovery loop --------------------------------------

    def poll(self) -> None:
        """Once per cluster step: missed-beat detection, probation
        expiry, due restarts, orphan re-homing."""
        cl = self.cluster
        tick = cl._tick
        for rep in list(cl.replicas):
            if rep.state in ("active", "draining"):
                if tick - rep.last_beat >= self.beat_timeout:
                    self.fail(rep, "missed_beats")
                elif (rep.fail_streak
                      and rep.probation_until is not None
                      and tick >= rep.probation_until):
                    rep.fail_streak = 0     # survived probation
                    rep.probation_until = None
            elif (rep.state == "failed" and self.auto_restart
                  and rep.restart_at is not None
                  and tick >= rep.restart_at):
                self.restart(rep)
        if cl._orphans:
            self._rehome()

    def fail(self, rep, reason, error=None) -> None:
        """Mark one replica FAILED and fail over every non-terminal
        request it holds.  Idempotent on already-dead replicas."""
        cl = self.cluster
        if rep.state in ("failed", "restarting", "retired", "drained"):
            return
        # a HUNG replica stopped stepping but its engine is intact:
        # the page pool is still readable, so running requests can be
        # salvaged (KV pages migrated) instead of re-prefilled.  A
        # crashed/raising replica's engine is garbage — capture the
        # distinction BEFORE the hung flag is cleared below.
        salvageable = cl.salvage and (
            rep.hung or reason in ("missed_beats", "watchdog"))
        in_flight = rep.engine.in_flight
        rep.state = "failed"
        rep.hung = False
        rep.fails += 1
        rep.fail_streak += 1
        rep.probation_until = None
        if cl._obs is not None:
            cl._obs.events.log(
                "replica.fail", replica=rep.name, reason=reason,
                in_flight=in_flight, fail_streak=rep.fail_streak,
                tick=cl._tick)
            cl._obs.recorder.record(
                "replica.fail", replica=rep.name, reason=reason,
                tick=cl._tick)
        try:
            faults.fire("replica.fail", "after")
        except faults.InjectedFault:
            pass            # the failure is already being handled
        # strip every live request off the dead scheduler (its engine
        # is garbage — the restart path rebuilds it from scratch, so
        # no slot/page bookkeeping is owed here) and fail each over.
        sch = rep.engine.scheduler
        live = [r for r in sch.requests.values() if not r.terminal]
        for req in live:
            for pool in (sch.queue, sch.prefilling, sch.running):
                if req in pool:
                    pool.remove(req)
            sch.requests.pop(req.rid, None)
            if sch.spec is not None:
                try:
                    sch.spec.on_release(req)
                except Exception:
                    pass    # dead engine's draft state is garbage too
            cl._owner.pop(req.rid, None)
            if salvageable and req.state is RequestState.RUNNING \
                    and req.sid is not None \
                    and self._salvage(req, rep):
                continue
            self._failover(req, rep)
        # schedule the restart — or trip the breaker.
        if rep.fail_streak > self.restart_budget:
            self.retire(rep)
        elif self.auto_restart:
            backoff = self.backoff_base * (
                2 ** (rep.fail_streak - 1))
            rep.restart_at = cl._tick + backoff
        if error is not None and cl._obs is not None:
            obs.auto_dump(f"replica-failed-{rep.name}",
                          extra={"replica": rep.name,
                                 "reason": reason})

    def _failover(self, req, src) -> None:
        """Fail one request over to a healthy replica (or the orphan
        list).  The recompute resume is the preemption idiom: prefill
        prompt+generated from scratch, decode resumes bit-identically
        after the already-streamed tokens."""
        cl = self.cluster
        cl.failovers += 1
        if cl._obs is not None:
            cl._obs.registry.counter(
                "cluster_failovers_total",
                "Requests failed over off a dead replica").inc()
        if not req.terminal:
            req.resume_ids = np.concatenate(
                [req.prompt_ids,
                 np.asarray(req.generated, np.int32)]).astype(np.int32)
            req.prefill_done = 0
            req.sid = None
            req.state = RequestState.QUEUED
        placed = self._place(req, src=src)
        if not placed:
            cl._orphans.append(req)
            if cl._obs is not None:
                cl._obs.events.log(
                    "req.failover", rid=req.rid, src=src.name,
                    dst=None, orphaned=1,
                    tokens_done=len(req.generated), tick=cl._tick)

    def _salvage(self, req, src) -> bool:
        """Migrate one RUNNING request's committed KV pages off a hung
        replica onto an admitting one through the dense gather→write
        handoff path, skipping the re-prefill entirely: decoding
        resumes from the same last token over the same pages, so the
        stream continues bit-identically at recompute-free cost.

        The copy is crc32-verified end to end (gather source → land →
        re-gather target); any mismatch, capacity shortfall, injected
        ``kv.salvage`` raise, or unreadable source degrades to False —
        the caller falls back to the recompute failover, never loss."""
        cl = self.cluster
        src_ex = src.engine.executor
        try:
            length = int(src_ex.cache.lengths[req.sid])
        except Exception:
            return False
        if length <= 0:
            return False
        dst = None
        for cand in sorted(
                (r for r in cl._admitting() if r is not src),
                key=lambda r: (r.depth, -r.engine.executor.free_pages)):
            ex = cand.engine.executor
            if ex.free_slots >= 1 \
                    and ex.free_pages >= ex.pages_for(length + 1):
                dst = cand
                break
        if dst is None:
            return False
        try:
            faults.fire("kv.salvage", "before")
            k, v = src_ex.cache.gather_dense(req.sid, length)
        except Exception:
            cl.salvages_failed += 1
            return False
        # gather_dense pads to the page-multiple cover: positions >=
        # length are garbage and must never enter the checksum
        k = np.asarray(k)[:, :, :length]
        v = np.asarray(v)[:, :, :length]
        crc = zlib.crc32(v.tobytes(), zlib.crc32(k.tobytes()))
        if faults.poll("kv.salvage") is not None:
            # injected in-flight corruption: the verify MUST catch it
            k = k.copy()
            k.flat[k.size // 2] = k.flat[k.size // 2] + 1
        dst_ex = dst.engine.executor
        dst_sid = dst_ex.alloc_slot()
        crc_got = None
        try:
            dst_ex.cache.write_at(dst_sid, k, v, 0)
            k2, v2 = dst_ex.cache.gather_dense(dst_sid, length)
            k2 = np.asarray(k2)[:, :, :length]
            v2 = np.asarray(v2)[:, :, :length]
            crc_got = zlib.crc32(v2.tobytes(), zlib.crc32(k2.tobytes()))
        except Exception:
            pass
        if crc_got != crc:
            # corrupt copy: give the pages back, recompute instead
            dst_ex.free_slot(dst_sid)
            cl.salvages_failed += 1
            if cl._obs is not None:
                cl._obs.events.log(
                    "kv.salvage", rid=req.rid, src=src.name,
                    dst=dst.name, ok=0, crc_ok=0, tokens=length,
                    tick=cl._tick)
            return False
        dst_ex.last_token[dst_sid] = src_ex.last_token[req.sid]
        try:
            faults.fire("kv.salvage", "after")
        except faults.InjectedFault:
            pass            # pages landed verified: the salvage commits
        req.sid = dst_sid
        dst_sch = dst.engine.scheduler
        dst_sch.requests[req.rid] = req
        dst_sch.running.append(req)
        dst_sch._pending = None     # stale async plan must replan
        if dst_sch.spec is not None:
            dst_sch.spec.on_running(req)
        cl._owner[req.rid] = dst
        cl.failovers += 1           # a salvage IS a (cheap) failover
        cl.salvages += 1
        pages = int((dst_ex.cache.page_table[dst_sid] >= 0).sum())
        cl.salvaged_pages += pages
        if cl._obs is not None:
            cl._obs.registry.counter(
                "cluster_failovers_total",
                "Requests failed over off a dead replica").inc()
            cl._obs.registry.counter(
                "kv_pages_salvaged_total",
                "KV pages migrated off hung replicas").inc(pages)
            cl._obs.events.log(
                "kv.salvage", rid=req.rid, src=src.name, dst=dst.name,
                ok=1, crc_ok=1, tokens=length, pages=pages,
                tick=cl._tick)
            cl._obs.tracer.instant(
                "kv.salvage", cat="cluster", trace_id=req.rid,
                src=src.name, dst=dst.name, tokens=length, pages=pages)
        return True

    def _place(self, req, src=None) -> bool:
        """Route one failed-over request onto an admitting replica;
        False when none exists.  An injected ``req.failover`` raise
        degrades to the first admitting replica — never loss."""
        cl = self.cluster
        targets = cl._admitting()
        if not targets:
            return False
        degraded = False
        try:
            faults.fire("req.failover", "before")
            dst, aff = cl.router.pick(targets, req.resume_ids)
        except faults.InjectedFault:
            cl.router.degraded += 1
            dst, aff, degraded = targets[0], 0, True
        dst.engine.scheduler.add(req)
        cl._owner[req.rid] = dst
        if cl._obs is not None:
            cl._obs.events.log(
                "req.failover", rid=req.rid,
                src=None if src is None else src.name, dst=dst.name,
                orphaned=0, aff_tokens=int(aff), degraded=int(degraded),
                tokens_done=len(req.generated), tick=cl._tick)
        try:
            faults.fire("req.failover", "after")
        except faults.InjectedFault:
            pass            # the migration is already committed
        return True

    def _rehome(self) -> None:
        """Drain the orphan list onto whatever is admitting now."""
        cl = self.cluster
        remaining = []
        for req in cl._orphans:
            if req.terminal:
                continue
            if not self._place(req):
                remaining.append(req)
        cl._orphans[:] = remaining

    # -- restart + circuit breaker --------------------------------------

    def restart(self, rep):
        """One automatic restart attempt: rebuild the engine (AOT
        re-warmed from the fleet's shared persistent compile cache)
        and rejoin admission.  A failed attempt counts against the
        breaker budget and doubles the backoff."""
        cl = self.cluster
        if rep.state != "failed":
            raise ValueError(
                f"cannot restart {rep.name}: state={rep.state!r}")
        rep.state = "restarting"
        rep.restart_at = None
        try:
            faults.fire("replica.restart", "before")
            eng = cl._build_engine()
            faults.fire("replica.restart", "after")
        except Exception:
            cl.restarts_failed += 1
            rep.fail_streak += 1
            if cl._obs is not None:
                cl._obs.events.log(
                    "replica.restart", replica=rep.name, ok=0,
                    fail_streak=rep.fail_streak, tick=cl._tick)
            if rep.fail_streak > self.restart_budget:
                self.retire(rep)
            else:
                rep.state = "failed"
                backoff = self.backoff_base * (
                    2 ** (rep.fail_streak - 1))
                rep.restart_at = cl._tick + backoff
            return None
        rep.engine = eng
        rep.state = "active"
        rep.hung = False
        rep.last_beat = cl._tick
        rep.restarts += 1
        rep.probation_until = cl._tick + self.probation
        cl.restarts += 1
        if cl._obs is not None:
            report = eng._aot_report or {}
            cl._obs.events.log(
                "replica.restart", replica=rep.name, ok=1,
                restarts=rep.restarts,
                aot_compiled=int(report.get("compile", 0)),
                aot_disk=int(report.get("disk", 0)), tick=cl._tick)
        self._rehome()
        return rep

    def retire(self, rep) -> None:
        """Circuit breaker: permanently remove a flapping replica
        from rotation (state ``retired`` — never restarted)."""
        cl = self.cluster
        if rep.state == "retired":
            return
        rep.state = "retired"
        rep.restart_at = None
        cl.retired += 1
        if cl._obs is not None:
            cl._obs.events.log(
                "replica.retire", replica=rep.name,
                fail_streak=rep.fail_streak,
                budget=self.restart_budget, tick=cl._tick)

    def statusz(self) -> dict:
        return {
            "beat_timeout": self.beat_timeout,
            "watchdog_s": self.watchdog_s,
            "auto_restart": self.auto_restart,
            "restart_budget": self.restart_budget,
            "backoff_base": self.backoff_base,
            "probation": self.probation,
        }


class ServingCluster:
    """N engine replicas behind a :class:`Router`, stepped in lockstep
    on one logical clock.  Exposes the single-engine driving surface
    (``submit`` / ``step`` / ``run`` / ``tick`` / ``in_flight`` /
    ``stats``), so :func:`paddle_tpu.testing.load.run_load` drives a
    fleet exactly like one engine.

    ``cluster``: None = follow ``PT_CLUSTER`` (default off — the
    cluster collapses to one replica, bit-exact single-engine);
    True/False force it (tests / bench A/B).  Engine keyword arguments
    (``max_seqs``, ``page_size``, ``prefix_cache``, ``aot``, ...)
    apply to every replica.
    """

    def __init__(self, model, n_replicas=2, cluster=None,
                 router_policy="affinity", router_seed=0,
                 disaggregated=False, n_prefill=None, clock=None,
                 compile_cache=None, beat_timeout=3, watchdog_s=None,
                 auto_restart=True, restart_budget=3, backoff_base=2,
                 max_queue=None, shed_deadlines=None, wal=None,
                 salvage=True, **engine_kwargs):
        if cluster is None:
            cluster = _cluster_enabled()
        self.enabled = bool(cluster)
        if not self.enabled:
            n_replicas, disaggregated = 1, False
        n_replicas = int(n_replicas)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if disaggregated and n_replicas < 2:
            raise ValueError(
                "disaggregated mode needs >= 2 replicas "
                "(at least one prefill and one decode role)")
        self.model = model
        self.disaggregated = bool(disaggregated)
        self._engine_kwargs = dict(engine_kwargs)
        # durable serving: ONE write-ahead journal shared by the whole
        # fleet (wal: None = follow PT_WAL, default off/bit-exact;
        # a path or WriteAheadLog forces on).  The cluster resolves
        # the gate once and pins the engines to its decision — two
        # engines must never race separate writers onto one journal
        # directory.
        self.wal = resolve_wal(wal)
        self._engine_kwargs["wal"] = (self.wal if self.wal is not None
                                      else False)
        # salvage: migrate a HUNG victim's committed KV pages to the
        # failover target instead of re-prefilling (crash victims
        # still recompute — a crashed engine's pool is garbage)
        self.salvage = bool(salvage)
        self._clock = clock
        # one persistent compile cache shared by the whole fleet when
        # AOT is in play: join() re-warms a fresh replica from disk
        from paddle_tpu.core import aot as aot_mod

        aot = engine_kwargs.get("aot")
        if aot is None:
            aot = aot_mod.mode()
        self._compile_cache = None
        if aot != "off":
            if isinstance(compile_cache, aot_mod.CompileCache):
                self._compile_cache = compile_cache
            else:
                self._compile_cache = aot_mod.CompileCache(
                    path=compile_cache)
        self.router = Router(policy=router_policy, seed=router_seed)
        self.replicas: list = []
        self._n_built = 0
        self._tick = 0
        self._next_rid = 0
        self._owner: dict = {}      # rid -> Replica (current home)
        self.handoffs = 0
        self.handoff_tokens = 0
        self.handoffs_skipped = 0
        self.drains = 0
        self.drains_aborted = 0
        self.joins = 0
        self.joins_aborted = 0
        self.resteered = 0
        # survivability plane: supervisor policy + counters.  All of
        # it is inert without failures — a fault-free run is
        # bit-exact r20 whatever the knobs.
        self.supervisor = ReplicaSupervisor(
            self, beat_timeout=beat_timeout, watchdog_s=watchdog_s,
            auto_restart=auto_restart, restart_budget=restart_budget,
            backoff_base=backoff_base)
        # admission control: max_queue bounds the fleet-wide queued
        # backlog; shed_deadlines (default: on iff max_queue is set)
        # early-rejects requests whose deadline the router can already
        # prove unmeetable.  Both default OFF-equivalent so legacy
        # submits are untouched.
        self.max_queue = None if max_queue is None else int(max_queue)
        self.shed_deadlines = (self.max_queue is not None
                               if shed_deadlines is None
                               else bool(shed_deadlines))
        self.failovers = 0
        self.sheds = 0
        self.restarts = 0
        self.restarts_failed = 0
        self.retired = 0
        self.salvages = 0           # hung-replica KV-page migrations
        self.salvages_failed = 0    # fell back to recompute
        self.salvaged_pages = 0
        self.dedup_hits = 0         # duplicate submits deduplicated
        self._orphans: list = []    # failed-over, awaiting a home
        self._served: dict = {}     # rid -> terminal Request restored
        #                             from the WAL (served from the log)
        self.recovery = None        # report dict set by recover()
        self.recovered_handles = {}  # rid -> handle, set by recover()
        self._obs = obs.handle()
        n_pre = 0
        if self.disaggregated:
            n_pre = (max(1, n_replicas // 2) if n_prefill is None
                     else int(n_prefill))
            if not 1 <= n_pre < n_replicas:
                raise ValueError(
                    f"n_prefill must be in [1, {n_replicas - 1}], "
                    f"got {n_pre}")
        for i in range(n_replicas):
            role = "mixed"
            if self.disaggregated:
                role = "prefill" if i < n_pre else "decode"
            self._build_replica(role)
        if self._obs is not None:
            self._obs.statusz["cluster"] = self._statusz
            self._obs.statusz["survivability"] = \
                self._survivability_statusz
            self._obs.statusz["durability"] = self._durability_statusz

    def _build_engine(self) -> ServingEngine:
        """One replica engine, AOT-warmed (when on) from the fleet's
        shared persistent compile cache — the join() AND restart
        rebuild path."""
        eng = ServingEngine(self.model, clock=self._clock,
                            compile_cache=self._compile_cache,
                            **self._engine_kwargs)
        # a fresh engine (restart/join) registers its engine-scoped
        # durability provider; the fleet-level view stays authoritative
        if self._obs is not None:
            self._obs.statusz["durability"] = self._durability_statusz
        return eng

    def _build_replica(self, role="mixed") -> Replica:
        name = f"r{self._n_built}"
        self._n_built += 1
        rep = Replica(name, self._build_engine(), role=role)
        rep.last_beat = self._tick
        self.replicas.append(rep)
        return rep

    def replica(self, name) -> Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r} "
                       f"(have {[r.name for r in self.replicas]})")

    # -- routing + submission -------------------------------------------

    def _admitting(self):
        return [r for r in self.replicas if r.admitting]

    def _recovering(self) -> bool:
        """True when capacity is expected back: some replica is mid
        restart or failed with a restart already scheduled."""
        return any(
            r.state == "restarting"
            or (r.state == "failed" and r.restart_at is not None)
            for r in self.replicas)

    def _route(self, rid, prompt_ids, resteer=False):
        cands = self._admitting()
        if not cands:
            raise RuntimeError(
                "ServingCluster: no admitting replica "
                "(all draining/drained)")
        self.router.decisions += 1
        degraded = False
        try:
            faults.fire("route.pick", "before")
            rep, aff = self.router.pick(cands, prompt_ids)
        except faults.InjectedFault:
            # degraded placement: deterministic fallback to the first
            # admitting replica — the request is never dropped
            self.router.degraded += 1
            rep, aff, degraded = cands[0], 0, True
        if not degraded:
            try:
                faults.fire("route.pick", "after")
            except faults.InjectedFault:
                self.router.degraded += 1
                degraded = True     # decision stands; nothing was lost
        if self._obs is not None:
            self._obs.events.log(
                "route.decide", rid=rid, replica=rep.name,
                policy=self.router.policy, aff_tokens=int(aff),
                depth=rep.depth,
                free_pages=rep.engine.executor.free_pages,
                degraded=int(degraded), resteer=int(resteer),
                tick=self._tick)
        return rep, aff

    def submit(self, prompt_ids, max_new_tokens=16, priority=0,
               deadline=None, on_token=None, rid=None) -> RequestHandle:
        """Route one request to a replica; the returned handle drives
        the whole CLUSTER (handle.result()/stream() step every
        replica), so it stays live across re-steers and handoffs."""
        if rid is None:
            # auto rids must never collide with journaled, recovered or
            # client-supplied rids: skip ahead until unused (recover()
            # also advances _next_rid past every replayed req-N)
            rid = f"req-{self._next_rid}"
            while self._known(rid) is not None:
                self._next_rid += 1
                rid = f"req-{self._next_rid}"
        known = self._known(rid)
        if known is not None:
            # idempotent duplicate submit: at-least-once clients get
            # the ORIGINAL request back (live, orphaned, or terminal —
            # including streams recovered from the WAL), never a
            # second stream; the dedup is journaled.
            self.dedup_hits += 1
            if self.wal is not None:
                self.wal.append({"t": "dedup", "rid": rid})
            if self._obs is not None:
                self._obs.events.log("req.dedup", rid=rid,
                                     state=known.state.value,
                                     tick=self._tick)
            return RequestHandle(self, known)
        self._next_rid += 1
        verdict = self._shed_verdict(deadline)
        if verdict is not None:
            shed = self._shed(rid, prompt_ids, max_new_tokens,
                              priority, deadline, on_token, verdict)
            if shed is not None:
                return shed     # REJECTED terminal, never silent loss
        if not self._admitting() and self._recovering():
            # the whole admitting set is down but a restart is already
            # scheduled: park the request on the orphan list (never
            # refused, never lost) — the supervisor re-homes it the
            # moment a replica rejoins.
            req = Request(rid, prompt_ids,
                          max_new_tokens=max_new_tokens,
                          priority=priority, deadline=deadline,
                          on_token=on_token)
            if len(req.prompt_ids) == 0:
                raise ValueError("prompt_ids must be non-empty")
            if req.max_new_tokens < 1:
                raise ValueError("max_new_tokens must be >= 1")
            self._orphans.append(req)
            if self.wal is not None:
                # parked submits are accepted work: journal them like
                # any other so a crash while orphaned still recovers
                self.wal.append({
                    "t": "submit", "rid": rid,
                    "prompt": req.prompt_ids.tolist(),
                    "max_new": req.max_new_tokens,
                    "prio": req.priority, "deadline": req.deadline})
            if self._obs is not None:
                self._obs.events.log("req.parked", rid=rid,
                                     tick=self._tick)
            return RequestHandle(self, req)
        rep, _ = self._route(rid, np.asarray(
            prompt_ids, np.int32).reshape(-1))
        handle = rep.engine.submit(
            prompt_ids, max_new_tokens=max_new_tokens,
            priority=priority, deadline=deadline, on_token=on_token,
            rid=rid)
        self._owner[rid] = rep
        return RequestHandle(self, handle._req)

    # -- admission control (overload shedding) --------------------------

    def _queued_total(self) -> int:
        return len(self._orphans) + sum(
            len(r.engine.scheduler.queue) for r in self.replicas
            if r.state not in DEAD_STATES)

    def _shed_verdict(self, deadline):
        """(reason, retry_after_steps) to reject NOW, else None.

        Deterministic on the logical clock: the backlog bound counts
        every queued-not-admitted request fleet-wide; the deadline
        check uses a lower bound on TTFT (one prefill step plus the
        queue overflow ahead of the best replica) — if even the bound
        misses the deadline, admission would only discover the same
        truncation later, holding pages the whole wait.
        """
        queued = self._queued_total()
        if self.max_queue is not None and queued >= self.max_queue:
            return ("overload", max(1, queued - self.max_queue + 1))
        if deadline is not None and self.shed_deadlines:
            best = None
            for rep in self._admitting():
                est = 1 + max(0, rep.depth
                              - rep.engine.executor.cache.max_seqs)
                if best is None or est < best:
                    best = est
            if best is not None and best > int(deadline):
                return ("deadline_unmeetable",
                        max(1, best - int(deadline)))
        return None

    def _shed(self, rid, prompt_ids, max_new_tokens, priority,
              deadline, on_token, verdict):
        """Reject one request at the boundary: terminal REJECTED with
        a retry-after hint.  An injected ``req.shed`` before-raise
        degrades to ADMITTING the request (returns None) — shedding
        must never turn into loss."""
        reason, retry_after = verdict
        try:
            faults.fire("req.shed", "before")
        except faults.InjectedFault:
            return None
        req = Request(rid, prompt_ids, max_new_tokens=max_new_tokens,
                      priority=priority, deadline=deadline,
                      on_token=on_token)
        req.state = RequestState.REJECTED
        req.finish_reason = reason
        req.retry_after = int(retry_after)
        req.error = RequestRejected(rid, reason, retry_after)
        self.sheds += 1
        # NOT added to the dedup set: a retry_after verdict is an
        # invitation to resubmit the same rid after backing off
        if self.wal is not None:
            self.wal.append({"t": "reject", "rid": rid,
                             "reason": reason,
                             "retry_after": int(retry_after)})
        if self._obs is not None:
            self._obs.registry.counter(
                "cluster_shed_total",
                "Requests rejected by cluster admission control").inc()
            self._obs.events.log(
                "req.shed", rid=rid, reason=reason,
                retry_after=int(retry_after),
                queued=self._queued_total(), tick=self._tick)
        try:
            faults.fire("req.shed", "after")
        except faults.InjectedFault:
            pass                # the rejection is already terminal
        return RequestHandle(self, req)

    def cancel(self, rid) -> None:
        rep = self._owner.get(rid)
        if rep is not None:
            rep.engine.cancel(rid)
            return
        for req in self._orphans:   # cancelled while awaiting a home
            if req.rid == rid and not req.terminal:
                req.cancel_flag = True

    def request(self, rid):
        return self._known(rid)

    def _known(self, rid):
        """The live/terminal Request for ``rid`` wherever it lives —
        its owning replica, the WAL-restored terminal set, or the
        orphan list — else None."""
        rep = self._owner.get(rid)
        if rep is not None:
            req = rep.engine.request(rid)
            if req is not None:
                return req
        req = self._served.get(rid)
        if req is not None:
            return req
        for req in self._orphans:
            if req.rid == rid:
                return req
        return None

    # -- driving ---------------------------------------------------------

    def step(self) -> dict:
        """One cluster iteration: every live replica steps once (the
        shared logical clock) under the supervisor's watch, then
        disaggregated migrations run, the supervisor polls (missed-
        beat detection, restarts, orphan re-homing) and finished
        drains are retired.  Returns the merged {rid: [tokens]} map."""
        self._tick += 1
        emitted: dict = {}
        for rep in list(self.replicas):
            if rep.state in DEAD_STATES:
                continue
            for rid, toks in self.supervisor.step_replica(rep).items():
                emitted.setdefault(rid, []).extend(toks)
        if self.disaggregated:
            self._migrate()
        self.supervisor.poll()
        for rep in self.replicas:
            if rep.state == "draining" and rep.engine.in_flight == 0:
                rep.state = "drained"
                if self._obs is not None:
                    self._obs.events.log("replica.drained",
                                         replica=rep.name,
                                         tick=self._tick)
        self._publish_gauges()
        return emitted

    def run(self, max_steps=100000) -> dict:
        while self.in_flight:
            if self._tick >= max_steps:
                raise RuntimeError(
                    f"cluster did not drain in {max_steps} steps")
            self.step()
        return self.stats()

    @property
    def tick(self) -> int:
        return self._tick

    @property
    def in_flight(self) -> int:
        return len(self._orphans) + sum(
            rep.engine.in_flight for rep in self.replicas
            if rep.state not in DEAD_STATES)

    # -- elastic scale ---------------------------------------------------

    def fail(self, name, reason="operator") -> Replica:
        """Force one replica FAILED (ops hook and the bench's kill
        switch): in-flight requests fail over immediately, the
        supervisor owns the restart/breaker follow-up."""
        rep = self.replica(name) if not isinstance(name, Replica) \
            else name
        self.supervisor.fail(rep, reason)
        return rep

    def drain(self, name) -> Replica:
        """Close one replica's admission and re-steer its queued
        requests; prefilling/running work finishes in place and the
        replica retires (state ``drained``) once idle.  Refuses to
        drain the last admitting replica — the fleet must keep
        accepting traffic.

        Idempotency is deterministic: draining an already
        ``draining``/``drained`` replica is a pure no-op (same object
        back, no counters, no re-steer, no fault firing); draining a
        ``failed``/``restarting``/``retired`` replica raises — there
        is nothing to hand off and pretending otherwise would hide a
        dead box from the operator."""
        rep = self.replica(name) if not isinstance(name, Replica) \
            else name
        if rep.state in ("draining", "drained"):
            if self._obs is not None:
                self._obs.events.log("replica.drain", replica=rep.name,
                                     idempotent=1, tick=self._tick)
            return rep
        if rep.state != "active":
            raise ValueError(
                f"cannot drain {rep.name}: state={rep.state!r} "
                f"(only active replicas drain)")
        targets = [r for r in self.replicas
                   if r is not rep and r.admitting]
        if rep.admitting and not targets:
            raise RuntimeError(
                f"cannot drain {rep.name}: it is the last admitting "
                f"replica")
        try:
            faults.fire("replica.drain", "before")
        except faults.InjectedFault:
            # drain aborted before anything moved: replica stays active
            self.drains_aborted += 1
            if self._obs is not None:
                self._obs.events.log("replica.drain", replica=rep.name,
                                     aborted=1, tick=self._tick)
            return rep
        rep.state = "draining"
        sch = rep.engine.scheduler
        moved = list(sch.queue)
        for req in moved:
            sch.queue.remove(req)
            sch.requests.pop(req.rid, None)
            self._owner.pop(req.rid, None)
        for req in moved:
            dst, aff = self.router.pick(targets, req.resume_ids)
            dst.engine.scheduler.add(req)
            self._owner[req.rid] = dst
            self.resteered += 1
            if self._obs is not None:
                self._obs.events.log(
                    "route.decide", rid=req.rid, replica=dst.name,
                    policy=self.router.policy, aff_tokens=int(aff),
                    depth=dst.depth,
                    free_pages=dst.engine.executor.free_pages,
                    degraded=0, resteer=1, tick=self._tick)
        try:
            faults.fire("replica.drain", "after")
        except faults.InjectedFault:
            pass                    # the drain is already committed
        self.drains += 1
        if self._obs is not None:
            self._obs.events.log(
                "replica.drain", replica=rep.name, aborted=0,
                resteered=len(moved), in_flight=rep.engine.in_flight,
                tick=self._tick)
        return rep

    def join(self, role=None):
        """Add a fresh replica to the fleet.  Under AOT the new
        engine's warmup resolves from the shared persistent compile
        cache (disk hits, zero compiles) — elastic join in seconds.
        Returns the new :class:`Replica`, or None when an injected
        ``replica.join`` fault aborts the build (fleet unchanged).

        Deterministic while a drain is in progress: the join commits
        independently (fresh name, fresh engine), never resurrects or
        touches the draining replica, and the draining replica's
        re-steered queue may land on the newcomer on the NEXT routing
        decision only — the in-progress transition is untouched."""
        if role is None:
            role = "decode" if self.disaggregated else "mixed"
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"join role must be mixed|prefill|decode, got {role!r}")
        try:
            faults.fire("replica.join", "before")
        except faults.InjectedFault:
            self.joins_aborted += 1
            if self._obs is not None:
                self._obs.events.log("replica.join", aborted=1,
                                     tick=self._tick)
            return None
        rep = self._build_replica(role=role)
        try:
            faults.fire("replica.join", "after")
        except faults.InjectedFault:
            pass            # engine built and warmed: join committed
        self.joins += 1
        if self._obs is not None:
            report = rep.engine._aot_report or {}
            self._obs.events.log(
                "replica.join", replica=rep.name, role=role, aborted=0,
                aot_compiled=int(report.get("compile", 0)),
                aot_disk=int(report.get("disk", 0)), tick=self._tick)
        return rep

    # -- disaggregated prefill -> decode handoff ------------------------

    def _migrate(self):
        decode_reps = [r for r in self.replicas
                       if r.role == "decode" and r.state == "active"]
        if not decode_reps:
            return
        for rep in self.replicas:
            if rep.role != "prefill" or rep.state == "drained":
                continue
            for req in list(rep.engine.scheduler.running):
                self._handoff(rep, req, decode_reps)

    def _handoff(self, src, req, decode_reps) -> bool:
        """Ship one RUNNING sequence's KV pages from a prefill replica
        to a decode replica as one bulk copy, then move the request.
        Skips (request keeps decoding on the source — degradation,
        never loss) when no decode replica has room or an injected
        ``kv.handoff`` before-fault fires."""
        src_ex = src.engine.executor
        length = int(src_ex.cache.lengths[req.sid])
        dst = None
        for cand in sorted(
                decode_reps,
                key=lambda r: (r.depth, -r.engine.executor.free_pages)):
            ex = cand.engine.executor
            if ex.free_slots >= 1 \
                    and ex.free_pages >= ex.pages_for(length + 1):
                dst = cand
                break
        if dst is None:
            self.handoffs_skipped += 1
            return False
        try:
            faults.fire("kv.handoff", "before")
        except faults.InjectedFault:
            self.handoffs_skipped += 1
            if self._obs is not None:
                self._obs.events.log("kv.handoff", rid=req.rid,
                                     src=src.name, dst=dst.name,
                                     skipped=1, tick=self._tick)
            return False
        dst_ex = dst.engine.executor
        k, v = src_ex.cache.gather_dense(req.sid, length)
        dst_sid = dst_ex.alloc_slot()
        dst_ex.cache.write_at(dst_sid, k[:, :, :length],
                              v[:, :, :length], 0)
        dst_ex.last_token[dst_sid] = src_ex.last_token[req.sid]
        try:
            faults.fire("kv.handoff", "after")
        except faults.InjectedFault:
            pass    # pages landed refcounted: the handoff commits
        src_sch = src.engine.scheduler
        if src_sch.spec is not None:
            src_sch.spec.on_release(req)
        src_sch.running.remove(req)
        src_sch.requests.pop(req.rid, None)
        src_ex.free_slot(req.sid)
        src_sch._pending = None   # any parked plan names the old sid
        dst_sch = dst.engine.scheduler
        req.sid = dst_sid
        dst_sch.requests[req.rid] = req
        dst_sch.running.append(req)
        dst_sch._pending = None   # predicted running set just changed
        if dst_sch.spec is not None:
            dst_sch.spec.on_running(req)
        self._owner[req.rid] = dst
        self.handoffs += 1
        self.handoff_tokens += length
        pages = int((dst_ex.cache.page_table[dst_sid] >= 0).sum())
        if self._obs is not None:
            self._obs.events.log(
                "kv.handoff", rid=req.rid, src=src.name, dst=dst.name,
                skipped=0, tokens=length, pages=pages, tick=self._tick)
            self._obs.tracer.instant(
                "kv.handoff", cat="serve", trace_id=req.rid,
                src=src.name, dst=dst.name, tokens=length)
        return True

    # -- observability ---------------------------------------------------

    def _publish_gauges(self):
        h = self._obs
        if h is None:
            return
        reg = h.registry
        g_pages = reg.gauge("cluster_replica_free_pages",
                            "Free KV pages on one fleet replica",
                            labels=("replica",))
        g_depth = reg.gauge(
            "cluster_replica_in_flight",
            "Queued+prefilling+running requests on one fleet replica",
            labels=("replica",))
        g_state = reg.gauge(
            "cluster_replica_state",
            "Replica lifecycle (0=active, 1=draining, 2=drained, "
            "3=failed, 4=restarting, 5=retired)",
            labels=("replica",))
        for rep in self.replicas:
            g_pages.labels(replica=rep.name).set(
                rep.engine.executor.free_pages)
            g_depth.labels(replica=rep.name).set(rep.depth)
            g_state.labels(replica=rep.name).set(
                REPLICA_STATES.index(rep.state))
        reg.gauge("cluster_replicas_active",
                  "Fleet replicas currently accepting work").set(
            sum(1 for r in self.replicas if r.state == "active"))
        reg.gauge("cluster_orphan_requests",
                  "Failed-over requests still awaiting a healthy "
                  "replica").set(len(self._orphans))

    def _statusz(self) -> dict:
        return {
            "tick": self._tick,
            "enabled": self.enabled,
            "disaggregated": self.disaggregated,
            "router": {
                "policy": self.router.policy,
                "decisions": self.router.decisions,
                "affinity_hits": self.router.affinity_hits,
                "degraded": self.router.degraded,
                "resteered": self.resteered,
            },
            "handoffs": {
                "done": self.handoffs,
                "tokens": self.handoff_tokens,
                "skipped": self.handoffs_skipped,
            },
            "drains": {"done": self.drains,
                       "aborted": self.drains_aborted},
            "joins": {"done": self.joins,
                      "aborted": self.joins_aborted},
            "survivability": {
                "failovers": self.failovers,
                "shed": self.sheds,
                "orphans": len(self._orphans),
                "restarts": {"done": self.restarts,
                             "failed": self.restarts_failed},
                "retired": self.retired,
            },
            "replicas": [
                {
                    "name": rep.name,
                    "role": rep.role,
                    "state": rep.state,
                    "tick": rep.engine.tick,
                    "in_flight": rep.engine.in_flight,
                    "queued": len(rep.engine.scheduler.queue),
                    "running": len(rep.engine.scheduler.running),
                    "pool": {
                        "num_pages":
                            rep.engine.executor.cache.num_pages,
                        "free_pages": rep.engine.executor.free_pages,
                    },
                    "prefix": (None if rep.engine.prefix is None
                               else rep.engine.prefix.stats()),
                }
                for rep in self.replicas
            ],
        }

    def _durability_statusz(self) -> dict:
        """/statusz provider: WAL segment/fsync state, dedup hits,
        salvage counters and the last recovery report."""
        return {
            "wal": None if self.wal is None else self.wal.statusz(),
            "dedup_hits": self.dedup_hits,
            "salvage": {
                "enabled": self.salvage,
                "done": self.salvages,
                "failed": self.salvages_failed,
                "pages": self.salvaged_pages,
            },
            "recovery": self.recovery,
        }

    def _survivability_statusz(self) -> dict:
        """/statusz provider: supervisor policy, recovery counters,
        and the per-replica breaker table."""
        return {
            "tick": self._tick,
            "policy": self.supervisor.statusz(),
            "admission": {
                "max_queue": self.max_queue,
                "shed_deadlines": self.shed_deadlines,
                "queued": self._queued_total(),
            },
            "failovers": self.failovers,
            "shed": self.sheds,
            "orphans": len(self._orphans),
            "restarts": {"done": self.restarts,
                         "failed": self.restarts_failed},
            "retired": self.retired,
            "replicas": [
                {
                    "name": rep.name,
                    "state": rep.state,
                    "hung": rep.hung,
                    "last_beat": rep.last_beat,
                    "missed_beats": max(0, self._tick - rep.last_beat),
                    "fails": rep.fails,
                    "fail_streak": rep.fail_streak,
                    "restarts": rep.restarts,
                    "restart_at": rep.restart_at,
                    "probation_until": rep.probation_until,
                }
                for rep in self.replicas
            ],
        }

    def stats(self) -> dict:
        """Aggregate fleet stats plus each replica's full engine
        stats.  ``agg_tok_per_step`` is the fleet-level throughput on
        the LOGICAL clock — decode tokens per cluster tick — the
        scaling metric the bench gates (wall time cannot scale when N
        simulated replicas share one CPU)."""
        per = {rep.name: rep.engine.stats() for rep in self.replicas}
        reqs: dict = {}
        for p in per.values():
            for k, n in p["requests"].items():
                reqs[k] = reqs.get(k, 0) + n
        decode = sum(p["decode_tokens"] for p in per.values())
        prefill = sum(p["prefill_tokens"] for p in per.values())
        cached = sum(p["cached_tokens"] for p in per.values())
        return {
            "steps": self._tick,
            "replicas": len(self.replicas),
            "requests": reqs,
            "decode_tokens": decode,
            "prefill_tokens": prefill,
            "cached_tokens": cached,
            "agg_tok_per_step": round(decode / max(self._tick, 1), 4),
            "prefix_hit_rate": round(
                cached / max(cached + prefill, 1), 4),
            "router": {
                "policy": self.router.policy,
                "decisions": self.router.decisions,
                "affinity_hits": self.router.affinity_hits,
                "degraded": self.router.degraded,
                "resteered": self.resteered,
            },
            "handoffs": self.handoffs,
            "handoffs_skipped": self.handoffs_skipped,
            "failovers": self.failovers,
            "shed": self.sheds,
            "orphans": len(self._orphans),
            "restarts": self.restarts,
            "restarts_failed": self.restarts_failed,
            "retired": self.retired,
            "salvages": self.salvages,
            "salvages_failed": self.salvages_failed,
            "salvaged_pages": self.salvaged_pages,
            "dedup_hits": self.dedup_hits,
            "wal_appended": (0 if self.wal is None
                             else self.wal.appended),
            "per_replica": per,
        }

    # -- whole-process crash recovery -----------------------------------

    @classmethod
    def recover(cls, model, wal_dir, **kwargs) -> "ServingCluster":
        """Rebuild a serving fleet from its write-ahead journal after
        a whole-process crash (SIGKILL included).

        Replays the journal (torn tails truncated, corrupt records
        skipped and counted), rebuilds the cluster — AOT re-warmed
        from the persistent compile cache when configured, so a warmed
        cache means zero fresh compiles — and then settles every
        journaled request into exactly one of:

        - **served from the log**: a finish record whose token count
          and crc32 match the replayed stream (or a reject record)
          restores the terminal request verbatim — no recompute;
        - **resubmitted**: anything in flight at the crash (or whose
          tail records were torn/corrupt) re-enters through the
          preemption-recompute idiom — prompt + replayed tokens
          re-prefill and decoding resumes, so the final stream is
          bit-identical to an uninterrupted run.

        Journaling continues into the same directory (a fresh
        segment), so recovery is itself crash-safe and repeatable.
        Client resubmits of any journaled rid dedupe to the restored
        request (at-least-once submission, exactly-once result).
        ``cluster.recovery`` holds the report; ``recovered_handles``
        maps every journaled rid to a live handle.  Deadlines are not
        reconstructed — the logical clock restarted.
        """
        records, report = wal_mod.replay(wal_dir)
        cl = cls(model, wal=wal_dir, **kwargs)
        by: dict = {}
        order: list = []
        for rec in records:
            t, rid = rec.get("t"), rec.get("rid")
            if rid is None:
                continue
            e = by.get(rid)
            if e is None:
                e = by[rid] = {"tokens": []}
                order.append(rid)
            if t == "submit":
                if "reject" in e:
                    # shed rids are deliberately not deduped, so a
                    # submit record AFTER a reject is the client's
                    # post-backoff retry: it supersedes the rejection
                    # and starts a fresh stream
                    e["submit"] = rec
                    e["tokens"] = []
                    del e["reject"]
                elif "submit" not in e:
                    e["submit"] = rec   # at-least-once: first write wins
            elif t == "token":
                # only the contiguous-from-zero prefix is trustworthy:
                # a corrupt interior token record leaves a gap, and a
                # token past a gap must be recomputed, not replayed (a
                # later incarnation's re-journaled tokens re-extend the
                # prefix exactly where the verified copy ends)
                if int(rec.get("i", len(e["tokens"]))) == len(e["tokens"]):
                    e["tokens"].append(int(rec["tok"]))
            elif t == "finish":
                e["finish"] = rec
            elif t == "reject":
                e["reject"] = rec
        # advance the auto-rid counter past every journaled req-N so a
        # fresh anonymous submit can never collide with (and silently
        # dedup to) a recovered request
        for rid in by:
            if isinstance(rid, str) and rid.startswith("req-"):
                try:
                    cl._next_rid = max(cl._next_rid, int(rid[4:]) + 1)
                except ValueError:
                    pass
        served = resubmitted = 0
        cl.recovered_handles = {}
        for seq, rid in enumerate(order):
            e = by[rid]
            sub = e.get("submit")
            if sub is None:
                if "reject" in e:
                    # shed at the boundary and never resubmitted: the
                    # rejection (with its retry_after) was already
                    # delivered live, and shed rids are deliberately
                    # not deduped — nothing to restore
                    continue
                # lifecycle records without a submit record (its line
                # was corrupt): there is no prompt to recompute from —
                # surface it in the report, the client's at-least-once
                # resubmit serves it fresh
                report["corrupt"] += 1
                continue
            req = Request(rid, np.asarray(sub["prompt"], np.int32),
                          max_new_tokens=sub["max_new"],
                          priority=sub.get("prio", 0),
                          arrival_seq=seq)
            req.recovered = True
            fin, rej, toks = e.get("finish"), e.get("reject"), e["tokens"]
            if rej is not None:
                req.state = RequestState.REJECTED
                req.finish_reason = rej["reason"]
                req.retry_after = int(rej["retry_after"])
                req.error = RequestRejected(rid, rej["reason"],
                                            rej["retry_after"])
                # like the live shed path, NOT added to the dedup set:
                # a retry_after verdict is an invitation to resubmit
                # the same rid after backing off
                served += 1
            elif fin is not None and fin["n"] == len(toks) \
                    and fin["crc"] == stream_crc(toks):
                # the journaled stream is provably complete: serve it
                # straight from the log, zero recompute
                req.generated = list(toks)
                req.state = RequestState(fin["state"])
                req.finish_reason = fin["reason"]
                if req.state is RequestState.FAILED:
                    req.error = RuntimeError(fin["reason"])
                cl._served[rid] = req
                served += 1
            else:
                # in flight at the crash (or its finish/token records
                # were torn): the preemption-recompute idiom resumes
                # it bit-identically after the replayed prefix
                req.generated = list(toks)
                req.resume_ids = np.concatenate(
                    [req.prompt_ids,
                     np.asarray(toks, np.int32)]).astype(np.int32)
                req.prefill_done = 0
                req.state = RequestState.QUEUED
                if not cl.supervisor._place(req):
                    cl._orphans.append(req)
                resubmitted += 1
            cl.recovered_handles[rid] = RequestHandle(cl, req)
        cl.recovery = {
            "segments": report["segments"],
            "records": report["records"],
            "corrupt": report["corrupt"],
            "torn_bytes": report["torn_bytes"],
            "served_from_log": served,
            "resubmitted": resubmitted,
            "orphaned": len(cl._orphans),
        }
        if cl.wal is not None:
            cl.wal.append({"t": "recover", **cl.recovery})
            cl.wal.fsync()
        if cl._obs is not None:
            cl._obs.events.log("wal.replay", dir=os.fspath(wal_dir),
                               **cl.recovery)
        return cl
