"""Multi-replica serving fleet over the shared logical clock.

One :class:`ServingEngine` is a single box; the fleet wraps N of them
(each with its own page pool and executor) behind a :class:`Router`
that places every request by **prefix affinity** — probe each
replica's radix tree with the read-only
:meth:`PrefixCache.match_len` — falling back to page-pool headroom
and queue depth, so shared-prefix traffic lands where its KV pages
already live (SGLang-style radix-affinity scheduling).  Elastic
scale: :meth:`ServingCluster.drain` closes one replica's admission
and re-steers its queued requests while in-flight work finishes in
place; :meth:`ServingCluster.join` builds a fresh replica whose AOT
warmup resolves from the fleet's shared persistent compile cache, so
a new box serves in seconds.  Opt-in disaggregation
(``disaggregated=True``) splits roles DistServe-style: prefill
replicas compute prompt KV, then ship each finished sequence's pages
to a decode replica as ONE bulk copy through the pool's
``gather_dense``/``write_at`` seams — pages land refcounted, and the
COW/prefix invariants hold on both pools.

Determinism: replicas step in lockstep — one cluster ``step()`` steps
every live replica once — and greedy streams depend only on weights +
prompt (page identity never enters the numerics), so per-request token
streams are bit-identical to a single engine whatever the routing,
and across drain/join re-steers and KV handoffs, in all four serving
variants (plain / prefix / spec / async).

Gate: ``PT_CLUSTER`` (off|on; anything else raises).  Off, the
cluster degenerates to ONE replica with a pass-through router — the
bit-exact single-engine path.

Fault points: ``route.pick`` brackets one placement decision,
``replica.drain`` / ``replica.join`` bracket the elastic transitions,
``kv.handoff`` brackets one page shipment.  All four DEGRADE on an
injected raise — fallback placement, aborted transition, or the
request keeps decoding where it is — never request loss (the
aot.cache discipline: a dead replica is a miss, not a crash).
"""
from __future__ import annotations

import os

import numpy as np

from ... import obs
from ...testing import faults
from .engine import ServingEngine
from .request import RequestHandle


def _cluster_enabled() -> bool:
    mode = os.environ.get("PT_CLUSTER", "off").lower()
    if mode not in ("off", "on"):
        raise ValueError(f"PT_CLUSTER={mode!r}: expected off|on")
    return mode == "on"


#: replica lifecycle states (statusz/gauge encoding in this order).
REPLICA_STATES = ("active", "draining", "drained")


class Replica:
    """One engine plus its fleet-side control state."""

    __slots__ = ("name", "engine", "role", "state")

    def __init__(self, name, engine, role="mixed"):
        self.name = name
        self.engine = engine
        self.role = role            # mixed | prefill | decode
        self.state = "active"

    @property
    def depth(self) -> int:
        """Queue depth the router balances on: everything holding or
        waiting for a slot."""
        s = self.engine.scheduler
        return len(s.queue) + len(s.prefilling) + len(s.running)

    @property
    def admitting(self) -> bool:
        return self.state == "active" and self.role in ("mixed",
                                                        "prefill")

    def __repr__(self):
        return (f"Replica({self.name}, role={self.role}, "
                f"state={self.state}, depth={self.depth})")


class Router:
    """Placement policy over the admitting replicas.

    ``affinity`` (default): maximize the prefix-affinity probe
    (tokens of the prompt already resident in the replica's radix
    tree), tie-broken by lowest queue depth, then most free pages,
    then lowest replica index — fully deterministic.  ``random``:
    seeded uniform pick, the bench A/B control arm.
    """

    POLICIES = ("affinity", "random")

    def __init__(self, policy="affinity", seed=0):
        if policy not in self.POLICIES:
            raise ValueError(
                f"router policy must be one of {self.POLICIES}, "
                f"got {policy!r}")
        self.policy = policy
        self._rng = np.random.RandomState(seed)
        self.decisions = 0
        self.affinity_hits = 0     # picks that landed on cached pages
        self.degraded = 0          # injected-fault fallback placements

    def pick(self, candidates, prompt_ids):
        """(replica, affinity_tokens) for one request."""
        if self.policy == "random":
            return candidates[int(self._rng.randint(
                len(candidates)))], 0
        best, best_key = None, None
        for i, rep in enumerate(candidates):
            prefix = rep.engine.prefix
            aff = (prefix.match_len(prompt_ids)
                   if prefix is not None else 0)
            key = (aff, -rep.depth, rep.engine.executor.free_pages, -i)
            if best is None or key > best_key:
                best, best_key = rep, key
        if best_key[0] > 0:
            self.affinity_hits += 1
        return best, best_key[0]


class ServingCluster:
    """N engine replicas behind a :class:`Router`, stepped in lockstep
    on one logical clock.  Exposes the single-engine driving surface
    (``submit`` / ``step`` / ``run`` / ``tick`` / ``in_flight`` /
    ``stats``), so :func:`paddle_tpu.testing.load.run_load` drives a
    fleet exactly like one engine.

    ``cluster``: None = follow ``PT_CLUSTER`` (default off — the
    cluster collapses to one replica, bit-exact single-engine);
    True/False force it (tests / bench A/B).  Engine keyword arguments
    (``max_seqs``, ``page_size``, ``prefix_cache``, ``aot``, ...)
    apply to every replica.
    """

    def __init__(self, model, n_replicas=2, cluster=None,
                 router_policy="affinity", router_seed=0,
                 disaggregated=False, n_prefill=None, clock=None,
                 compile_cache=None, **engine_kwargs):
        if cluster is None:
            cluster = _cluster_enabled()
        self.enabled = bool(cluster)
        if not self.enabled:
            n_replicas, disaggregated = 1, False
        n_replicas = int(n_replicas)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if disaggregated and n_replicas < 2:
            raise ValueError(
                "disaggregated mode needs >= 2 replicas "
                "(at least one prefill and one decode role)")
        self.model = model
        self.disaggregated = bool(disaggregated)
        self._engine_kwargs = dict(engine_kwargs)
        self._clock = clock
        # one persistent compile cache shared by the whole fleet when
        # AOT is in play: join() re-warms a fresh replica from disk
        from paddle_tpu.core import aot as aot_mod

        aot = engine_kwargs.get("aot")
        if aot is None:
            aot = aot_mod.mode()
        self._compile_cache = None
        if aot != "off":
            if isinstance(compile_cache, aot_mod.CompileCache):
                self._compile_cache = compile_cache
            else:
                self._compile_cache = aot_mod.CompileCache(
                    path=compile_cache)
        self.router = Router(policy=router_policy, seed=router_seed)
        self.replicas: list = []
        self._n_built = 0
        self._tick = 0
        self._next_rid = 0
        self._owner: dict = {}      # rid -> Replica (current home)
        self.handoffs = 0
        self.handoff_tokens = 0
        self.handoffs_skipped = 0
        self.drains = 0
        self.drains_aborted = 0
        self.joins = 0
        self.joins_aborted = 0
        self.resteered = 0
        self._obs = obs.handle()
        n_pre = 0
        if self.disaggregated:
            n_pre = (max(1, n_replicas // 2) if n_prefill is None
                     else int(n_prefill))
            if not 1 <= n_pre < n_replicas:
                raise ValueError(
                    f"n_prefill must be in [1, {n_replicas - 1}], "
                    f"got {n_pre}")
        for i in range(n_replicas):
            role = "mixed"
            if self.disaggregated:
                role = "prefill" if i < n_pre else "decode"
            self._build_replica(role)
        if self._obs is not None:
            self._obs.statusz["cluster"] = self._statusz

    def _build_replica(self, role="mixed") -> Replica:
        name = f"r{self._n_built}"
        self._n_built += 1
        eng = ServingEngine(self.model, clock=self._clock,
                            compile_cache=self._compile_cache,
                            **self._engine_kwargs)
        rep = Replica(name, eng, role=role)
        self.replicas.append(rep)
        return rep

    def replica(self, name) -> Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r} "
                       f"(have {[r.name for r in self.replicas]})")

    # -- routing + submission -------------------------------------------

    def _admitting(self):
        return [r for r in self.replicas if r.admitting]

    def _route(self, rid, prompt_ids, resteer=False):
        cands = self._admitting()
        if not cands:
            raise RuntimeError(
                "ServingCluster: no admitting replica "
                "(all draining/drained)")
        self.router.decisions += 1
        degraded = False
        try:
            faults.fire("route.pick", "before")
            rep, aff = self.router.pick(cands, prompt_ids)
        except faults.InjectedFault:
            # degraded placement: deterministic fallback to the first
            # admitting replica — the request is never dropped
            self.router.degraded += 1
            rep, aff, degraded = cands[0], 0, True
        if not degraded:
            try:
                faults.fire("route.pick", "after")
            except faults.InjectedFault:
                self.router.degraded += 1
                degraded = True     # decision stands; nothing was lost
        if self._obs is not None:
            self._obs.events.log(
                "route.decide", rid=rid, replica=rep.name,
                policy=self.router.policy, aff_tokens=int(aff),
                depth=rep.depth,
                free_pages=rep.engine.executor.free_pages,
                degraded=int(degraded), resteer=int(resteer),
                tick=self._tick)
        return rep, aff

    def submit(self, prompt_ids, max_new_tokens=16, priority=0,
               deadline=None, on_token=None, rid=None) -> RequestHandle:
        """Route one request to a replica; the returned handle drives
        the whole CLUSTER (handle.result()/stream() step every
        replica), so it stays live across re-steers and handoffs."""
        if rid is None:
            rid = f"req-{self._next_rid}"
        if rid in self._owner:
            raise ValueError(f"duplicate request id {rid!r}")
        self._next_rid += 1
        rep, _ = self._route(rid, np.asarray(
            prompt_ids, np.int32).reshape(-1))
        handle = rep.engine.submit(
            prompt_ids, max_new_tokens=max_new_tokens,
            priority=priority, deadline=deadline, on_token=on_token,
            rid=rid)
        self._owner[rid] = rep
        return RequestHandle(self, handle._req)

    def cancel(self, rid) -> None:
        rep = self._owner.get(rid)
        if rep is not None:
            rep.engine.cancel(rid)

    def request(self, rid):
        rep = self._owner.get(rid)
        return None if rep is None else rep.engine.request(rid)

    # -- driving ---------------------------------------------------------

    def step(self) -> dict:
        """One cluster iteration: every live replica steps once (the
        shared logical clock), then disaggregated migrations run and
        finished drains are retired.  Returns the merged
        {rid: [tokens]} map."""
        self._tick += 1
        emitted: dict = {}
        for rep in list(self.replicas):
            if rep.state == "drained":
                continue
            for rid, toks in rep.engine.step().items():
                emitted.setdefault(rid, []).extend(toks)
        if self.disaggregated:
            self._migrate()
        for rep in self.replicas:
            if rep.state == "draining" and rep.engine.in_flight == 0:
                rep.state = "drained"
                if self._obs is not None:
                    self._obs.events.log("replica.drained",
                                         replica=rep.name,
                                         tick=self._tick)
        self._publish_gauges()
        return emitted

    def run(self, max_steps=100000) -> dict:
        while self.in_flight:
            if self._tick >= max_steps:
                raise RuntimeError(
                    f"cluster did not drain in {max_steps} steps")
            self.step()
        return self.stats()

    @property
    def tick(self) -> int:
        return self._tick

    @property
    def in_flight(self) -> int:
        return sum(rep.engine.in_flight for rep in self.replicas)

    # -- elastic scale ---------------------------------------------------

    def drain(self, name) -> Replica:
        """Close one replica's admission and re-steer its queued
        requests; prefilling/running work finishes in place and the
        replica retires (state ``drained``) once idle.  Refuses to
        drain the last admitting replica — the fleet must keep
        accepting traffic."""
        rep = self.replica(name) if not isinstance(name, Replica) \
            else name
        if rep.state != "active":
            return rep
        targets = [r for r in self.replicas
                   if r is not rep and r.admitting]
        if rep.admitting and not targets:
            raise RuntimeError(
                f"cannot drain {rep.name}: it is the last admitting "
                f"replica")
        try:
            faults.fire("replica.drain", "before")
        except faults.InjectedFault:
            # drain aborted before anything moved: replica stays active
            self.drains_aborted += 1
            if self._obs is not None:
                self._obs.events.log("replica.drain", replica=rep.name,
                                     aborted=1, tick=self._tick)
            return rep
        rep.state = "draining"
        sch = rep.engine.scheduler
        moved = list(sch.queue)
        for req in moved:
            sch.queue.remove(req)
            sch.requests.pop(req.rid, None)
            self._owner.pop(req.rid, None)
        for req in moved:
            dst, aff = self.router.pick(targets, req.resume_ids)
            dst.engine.scheduler.add(req)
            self._owner[req.rid] = dst
            self.resteered += 1
            if self._obs is not None:
                self._obs.events.log(
                    "route.decide", rid=req.rid, replica=dst.name,
                    policy=self.router.policy, aff_tokens=int(aff),
                    depth=dst.depth,
                    free_pages=dst.engine.executor.free_pages,
                    degraded=0, resteer=1, tick=self._tick)
        try:
            faults.fire("replica.drain", "after")
        except faults.InjectedFault:
            pass                    # the drain is already committed
        self.drains += 1
        if self._obs is not None:
            self._obs.events.log(
                "replica.drain", replica=rep.name, aborted=0,
                resteered=len(moved), in_flight=rep.engine.in_flight,
                tick=self._tick)
        return rep

    def join(self, role=None):
        """Add a fresh replica to the fleet.  Under AOT the new
        engine's warmup resolves from the shared persistent compile
        cache (disk hits, zero compiles) — elastic join in seconds.
        Returns the new :class:`Replica`, or None when an injected
        ``replica.join`` fault aborts the build (fleet unchanged)."""
        if role is None:
            role = "decode" if self.disaggregated else "mixed"
        try:
            faults.fire("replica.join", "before")
        except faults.InjectedFault:
            self.joins_aborted += 1
            if self._obs is not None:
                self._obs.events.log("replica.join", aborted=1,
                                     tick=self._tick)
            return None
        rep = self._build_replica(role=role)
        try:
            faults.fire("replica.join", "after")
        except faults.InjectedFault:
            pass            # engine built and warmed: join committed
        self.joins += 1
        if self._obs is not None:
            report = rep.engine._aot_report or {}
            self._obs.events.log(
                "replica.join", replica=rep.name, role=role, aborted=0,
                aot_compiled=int(report.get("compile", 0)),
                aot_disk=int(report.get("disk", 0)), tick=self._tick)
        return rep

    # -- disaggregated prefill -> decode handoff ------------------------

    def _migrate(self):
        decode_reps = [r for r in self.replicas
                       if r.role == "decode" and r.state == "active"]
        if not decode_reps:
            return
        for rep in self.replicas:
            if rep.role != "prefill" or rep.state == "drained":
                continue
            for req in list(rep.engine.scheduler.running):
                self._handoff(rep, req, decode_reps)

    def _handoff(self, src, req, decode_reps) -> bool:
        """Ship one RUNNING sequence's KV pages from a prefill replica
        to a decode replica as one bulk copy, then move the request.
        Skips (request keeps decoding on the source — degradation,
        never loss) when no decode replica has room or an injected
        ``kv.handoff`` before-fault fires."""
        src_ex = src.engine.executor
        length = int(src_ex.cache.lengths[req.sid])
        dst = None
        for cand in sorted(
                decode_reps,
                key=lambda r: (r.depth, -r.engine.executor.free_pages)):
            ex = cand.engine.executor
            if ex.free_slots >= 1 \
                    and ex.free_pages >= ex.pages_for(length + 1):
                dst = cand
                break
        if dst is None:
            self.handoffs_skipped += 1
            return False
        try:
            faults.fire("kv.handoff", "before")
        except faults.InjectedFault:
            self.handoffs_skipped += 1
            if self._obs is not None:
                self._obs.events.log("kv.handoff", rid=req.rid,
                                     src=src.name, dst=dst.name,
                                     skipped=1, tick=self._tick)
            return False
        dst_ex = dst.engine.executor
        k, v = src_ex.cache.gather_dense(req.sid, length)
        dst_sid = dst_ex.alloc_slot()
        dst_ex.cache.write_at(dst_sid, k[:, :, :length],
                              v[:, :, :length], 0)
        dst_ex.last_token[dst_sid] = src_ex.last_token[req.sid]
        try:
            faults.fire("kv.handoff", "after")
        except faults.InjectedFault:
            pass    # pages landed refcounted: the handoff commits
        src_sch = src.engine.scheduler
        if src_sch.spec is not None:
            src_sch.spec.on_release(req)
        src_sch.running.remove(req)
        src_sch.requests.pop(req.rid, None)
        src_ex.free_slot(req.sid)
        src_sch._pending = None   # any parked plan names the old sid
        dst_sch = dst.engine.scheduler
        req.sid = dst_sid
        dst_sch.requests[req.rid] = req
        dst_sch.running.append(req)
        dst_sch._pending = None   # predicted running set just changed
        if dst_sch.spec is not None:
            dst_sch.spec.on_running(req)
        self._owner[req.rid] = dst
        self.handoffs += 1
        self.handoff_tokens += length
        pages = int((dst_ex.cache.page_table[dst_sid] >= 0).sum())
        if self._obs is not None:
            self._obs.events.log(
                "kv.handoff", rid=req.rid, src=src.name, dst=dst.name,
                skipped=0, tokens=length, pages=pages, tick=self._tick)
            self._obs.tracer.instant(
                "kv.handoff", cat="serve", trace_id=req.rid,
                src=src.name, dst=dst.name, tokens=length)
        return True

    # -- observability ---------------------------------------------------

    def _publish_gauges(self):
        h = self._obs
        if h is None:
            return
        reg = h.registry
        g_pages = reg.gauge("cluster_replica_free_pages",
                            "Free KV pages on one fleet replica",
                            labels=("replica",))
        g_depth = reg.gauge(
            "cluster_replica_in_flight",
            "Queued+prefilling+running requests on one fleet replica",
            labels=("replica",))
        g_state = reg.gauge(
            "cluster_replica_state",
            "Replica lifecycle (0=active, 1=draining, 2=drained)",
            labels=("replica",))
        for rep in self.replicas:
            g_pages.labels(replica=rep.name).set(
                rep.engine.executor.free_pages)
            g_depth.labels(replica=rep.name).set(rep.depth)
            g_state.labels(replica=rep.name).set(
                REPLICA_STATES.index(rep.state))
        reg.gauge("cluster_replicas_active",
                  "Fleet replicas currently accepting work").set(
            sum(1 for r in self.replicas if r.state == "active"))

    def _statusz(self) -> dict:
        return {
            "tick": self._tick,
            "enabled": self.enabled,
            "disaggregated": self.disaggregated,
            "router": {
                "policy": self.router.policy,
                "decisions": self.router.decisions,
                "affinity_hits": self.router.affinity_hits,
                "degraded": self.router.degraded,
                "resteered": self.resteered,
            },
            "handoffs": {
                "done": self.handoffs,
                "tokens": self.handoff_tokens,
                "skipped": self.handoffs_skipped,
            },
            "drains": {"done": self.drains,
                       "aborted": self.drains_aborted},
            "joins": {"done": self.joins,
                      "aborted": self.joins_aborted},
            "replicas": [
                {
                    "name": rep.name,
                    "role": rep.role,
                    "state": rep.state,
                    "tick": rep.engine.tick,
                    "in_flight": rep.engine.in_flight,
                    "queued": len(rep.engine.scheduler.queue),
                    "running": len(rep.engine.scheduler.running),
                    "pool": {
                        "num_pages":
                            rep.engine.executor.cache.num_pages,
                        "free_pages": rep.engine.executor.free_pages,
                    },
                    "prefix": (None if rep.engine.prefix is None
                               else rep.engine.prefix.stats()),
                }
                for rep in self.replicas
            ],
        }

    def stats(self) -> dict:
        """Aggregate fleet stats plus each replica's full engine
        stats.  ``agg_tok_per_step`` is the fleet-level throughput on
        the LOGICAL clock — decode tokens per cluster tick — the
        scaling metric the bench gates (wall time cannot scale when N
        simulated replicas share one CPU)."""
        per = {rep.name: rep.engine.stats() for rep in self.replicas}
        reqs: dict = {}
        for p in per.values():
            for k, n in p["requests"].items():
                reqs[k] = reqs.get(k, 0) + n
        decode = sum(p["decode_tokens"] for p in per.values())
        prefill = sum(p["prefill_tokens"] for p in per.values())
        cached = sum(p["cached_tokens"] for p in per.values())
        return {
            "steps": self._tick,
            "replicas": len(self.replicas),
            "requests": reqs,
            "decode_tokens": decode,
            "prefill_tokens": prefill,
            "cached_tokens": cached,
            "agg_tok_per_step": round(decode / max(self._tick, 1), 4),
            "prefix_hit_rate": round(
                cached / max(cached + prefill, 1), 4),
            "router": {
                "policy": self.router.policy,
                "decisions": self.router.decisions,
                "affinity_hits": self.router.affinity_hits,
                "degraded": self.router.degraded,
                "resteered": self.resteered,
            },
            "handoffs": self.handoffs,
            "handoffs_skipped": self.handoffs_skipped,
            "per_replica": per,
        }
