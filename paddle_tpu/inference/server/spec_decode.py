"""Self-speculative decoding: n-gram / prompt-lookup drafting.

The serving TPOT floor below batch saturation is HBM bandwidth — every
decode step re-reads the whole model to produce ONE token per sequence.
Draft-and-verify [Leviathan et al., "Fast Inference from Transformers
via Speculative Decoding"] trades cheap FLOPs for those reads: guess
``k`` tokens, run ONE forward over the ``k+1``-token window, keep the
longest prefix the model agrees with.  Greedy acceptance (token match
against the argmax) makes the output stream bit-identical to plain
greedy decode by construction — position ``w`` is only committed when
positions ``< w`` fed the model exactly the tokens it would have
chosen itself.

The draft source here is the sequence's OWN history (prompt-lookup /
n-gram drafting, no second model): generated text constantly re-quotes
its prompt and itself — code, JSON, retrieval contexts, multi-turn
chatter — so matching the tail n-gram of ``prompt + generated`` against
an earlier occurrence and proposing the tokens that followed it is free
and surprisingly accurate on structured workloads.

:class:`NGramProposer` keeps one incrementally-maintained index per
request: ``index[n][ngram] -> position right after that n-gram's most
recent PREVIOUS occurrence``.  An n-gram is recorded only once a token
lands after it, so the tail n-gram (which has no continuation yet)
never matches itself.  Longest ``n`` wins at propose time.

:class:`SpecDecode` is the bundle the scheduler drives (mode + ``k`` +
proposer); built by the engine when ``PT_SPEC_DECODE=ngram``.

Env knobs::

    PT_SPEC_DECODE  off | ngram      (default off; bit-exact legacy)
    PT_SPEC_K       max draft tokens per step   (default 4)
    PT_SPEC_NGRAM   longest n-gram matched      (default 3)
"""
from __future__ import annotations

import os

import numpy as np


def spec_mode() -> str:
    """Validated ``PT_SPEC_DECODE`` value."""
    mode = os.environ.get("PT_SPEC_DECODE", "off").lower()
    if mode not in ("off", "ngram"):
        raise ValueError(
            f"PT_SPEC_DECODE={mode!r}: expected off|ngram")
    return mode


class NGramProposer:
    """Per-request prompt-lookup draft index, maintained incrementally.

    ``begin(rid, tokens)`` seeds from a full history (admission /
    re-admission after preemption rebuilds it from
    ``prompt + generated``, so preempted streams draft identically to
    never-preempted ones), ``extend(rid, tok)`` appends one accepted
    token, ``propose(rid, k)`` returns up to ``k`` continuation tokens.
    """

    def __init__(self, max_ngram=3, min_ngram=1):
        if max_ngram < min_ngram or min_ngram < 1:
            raise ValueError(
                f"need max_ngram >= min_ngram >= 1, got "
                f"{max_ngram}/{min_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self._tokens: dict = {}   # rid -> [int, ...]
        self._index: dict = {}    # rid -> {n: {ngram tuple: cont pos}}

    def begin(self, rid, tokens) -> None:
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        self._tokens[rid] = []
        self._index[rid] = {n: {} for n in
                            range(self.min_ngram, self.max_ngram + 1)}
        for t in toks:
            self.extend(rid, t)

    def extend(self, rid, tok) -> None:
        """Append one token; index the n-grams it gives a continuation
        to.  The n-gram ENDING at the new token is deliberately not
        indexed yet — it has no continuation, and skipping it is what
        keeps the tail from matching itself at propose time."""
        toks = self._tokens[rid]
        idx = self._index[rid]
        p = len(toks)            # the new token's position
        toks.append(int(tok))
        for n in range(self.min_ngram, self.max_ngram + 1):
            if p >= n:
                idx[n][tuple(toks[p - n:p])] = p
        # an unbounded per-request index is fine at serving lengths
        # (max_len tokens x max_ngram entries); dropped at release

    def drop(self, rid) -> None:
        self._tokens.pop(rid, None)
        self._index.pop(rid, None)

    def propose(self, rid, k) -> np.ndarray:
        """Up to ``k`` draft tokens continuing ``rid``'s history, or an
        empty array when no earlier occurrence of the tail matches
        (the step then degrades to plain one-token decode)."""
        toks = self._tokens.get(rid)
        if toks is None or k <= 0:
            return np.zeros((0,), np.int32)
        L = len(toks)
        idx = self._index[rid]
        for n in range(min(self.max_ngram, L), self.min_ngram - 1, -1):
            pos = idx[n].get(tuple(toks[L - n:L]))
            if pos is not None:
                return np.asarray(toks[pos:pos + k], np.int32)
        return np.zeros((0,), np.int32)

    def history_len(self, rid) -> int:
        toks = self._tokens.get(rid)
        return 0 if toks is None else len(toks)


class SpecDecode:
    """Mode bundle the scheduler drives: draft budget + proposer.

    ``k`` is the max drafted tokens per sequence per step, so the
    verify window is ``k + 1`` wide and admission charges the
    worst-case ``k + 1`` token lookahead.
    """

    def __init__(self, k=None, max_ngram=None):
        if k is None:
            k = int(os.environ.get("PT_SPEC_K", "4"))
        if max_ngram is None:
            max_ngram = int(os.environ.get("PT_SPEC_NGRAM", "3"))
        if k < 1:
            raise ValueError(f"PT_SPEC_K must be >= 1, got {k}")
        self.k = int(k)
        self.proposer = NGramProposer(max_ngram=max_ngram)

    # -- scheduler lifecycle hooks --------------------------------------

    def on_running(self, req) -> None:
        """Request entered RUNNING (final prefill chunk landed): seed
        the draft index from prompt + everything generated so far
        (non-empty ``generated`` = resumed after preemption)."""
        history = np.concatenate(
            [np.asarray(req.prompt_ids, np.int32),
             np.asarray(req.generated, np.int32)])
        self.proposer.begin(req.rid, history)

    def on_token(self, req, tok) -> None:
        if req.rid in self.proposer._tokens:
            self.proposer.extend(req.rid, tok)

    def on_release(self, req) -> None:
        self.proposer.drop(req.rid)

    def propose(self, req, max_len=None) -> np.ndarray:
        cap = self.k if max_len is None else min(self.k, int(max_len))
        return self.proposer.propose(req.rid, cap)
