"""User-facing continuous-batching serving engine.

Single-threaded by design: ``submit()`` enqueues, ``step()`` runs one
scheduler iteration, and handles pull results by driving ``step()``
themselves.  This keeps every test deterministic (the logical clock IS
the iteration count) while the control flow matches what a threaded
front-end would do per tick.

    engine = ServingEngine(model, max_seqs=4, page_size=16)
    h = engine.submit(prompt_ids, max_new_tokens=32)
    for tok in h.stream():   # drives engine.step() under the hood
        ...
    engine.stats()           # SLO metrics dict
"""
from __future__ import annotations

import os

import jax.numpy as jnp

from .executor import PagedExecutor
from .metrics import EngineMetrics
from .prefix_cache import PrefixCache
from .request import Request, RequestHandle, RequestState
from .scheduler import Scheduler
from .spec_decode import SpecDecode, spec_mode
from .wal import resolve_wal


def _prefix_cache_enabled() -> bool:
    mode = os.environ.get("PT_PREFIX_CACHE", "off").lower()
    if mode not in ("off", "on"):
        raise ValueError(
            f"PT_PREFIX_CACHE={mode!r}: expected off|on")
    return mode == "on"


def _async_exec_enabled() -> bool:
    mode = os.environ.get("PT_ASYNC_EXEC", "off").lower()
    if mode not in ("off", "on"):
        raise ValueError(
            f"PT_ASYNC_EXEC={mode!r}: expected off|on")
    return mode == "on"


class ServingEngine:
    def __init__(self, model, max_seqs=4, page_size=16, max_len=256,
                 dtype=jnp.float32, num_pages=None, policy="fifo",
                 prefill_chunk=None, eos_token_id=None,
                 max_preemptions=4, prefix_cache=None,
                 spec_decode=None, clock=None, slos=None,
                 slo_rules=None, async_exec=None, aot=None,
                 compile_cache=None, decode_n_steps=(), quant=None,
                 wal=None, sp_mesh=None, sp_prefill=None,
                 sp_min_tokens=None, sp_axis=None):
        # quant: None = follow PT_QUANT (default none, bit-exact legacy
        # path); "none"/"int8" force it (bench A/B).  int8 = per-channel
        # int8 projection weights + per-page int8 KV pools.
        # sp_prefill: None = follow PT_SP_PREFILL (default off,
        # bit-exact legacy path); True/False force it.  On, prompts at
        # or above sp_min_tokens (PT_SP_PREFILL_MIN_TOKENS) prefill
        # sequence-parallel over sp_mesh's sp axis (default: a 1-D
        # mesh over every local device).
        self.executor = PagedExecutor(
            model, max_seqs=max_seqs, page_size=page_size,
            max_len=max_len, dtype=dtype, num_pages=num_pages,
            quant=quant, sp_mesh=sp_mesh, sp_prefill=sp_prefill,
            sp_min_tokens=sp_min_tokens, sp_axis=sp_axis)
        # clock: injectable wall-clock source for the SLO metrics and
        # per-request timestamps (default time.perf_counter; seeded
        # tests pass obs.LogicalClock() for exact ms percentiles)
        self.metrics = EngineMetrics(
            max_seqs=max_seqs, num_pages=self.executor.cache.num_pages,
            clock=clock)
        # prefix_cache: None = follow PT_PREFIX_CACHE (default off,
        # bit-exact legacy path); True/False force it (bench A/B)
        if prefix_cache is None:
            prefix_cache = _prefix_cache_enabled()
        self.prefix = None
        if prefix_cache:
            self.prefix = PrefixCache(
                self.executor.cache,
                on_evict=self.metrics.on_prefix_evict)
            # allocation shortfalls try LRU eviction of cold cached
            # pages before raising pool-exhausted (eviction is cheaper
            # than preempt-and-recompute)
            self.executor.cache.reclaimer = self.prefix.evict
        # spec_decode: None = follow PT_SPEC_DECODE (default off,
        # bit-exact legacy path); "off"/"ngram" or False/True force it
        # (bench A/B).  "ngram" drafts from each request's own
        # prompt+generated history — no second model.
        if spec_decode is None:
            spec_decode = spec_mode() == "ngram"
        elif isinstance(spec_decode, str):
            if spec_decode not in ("off", "ngram"):
                raise ValueError(
                    f"spec_decode={spec_decode!r}: expected off|ngram")
            spec_decode = spec_decode == "ngram"
        self.spec = SpecDecode() if spec_decode else None
        # async_exec: None = follow PT_ASYNC_EXEC (default off,
        # bit-exact legacy path); True/False force it (bench A/B).
        # On = double-buffered steps: unrealized dispatch, next-step
        # planning overlapped behind the device, commit at the fence.
        if async_exec is None:
            async_exec = _async_exec_enabled()
        # wal: None = follow PT_WAL (default off, bit-exact legacy
        # path); False forces off (a cluster passes its own shared
        # journal or False so engines never double-resolve the env);
        # a path/WriteAheadLog forces on (bench A/B, recovery).
        self.wal = resolve_wal(wal)
        self.dedup_hits = 0
        self.scheduler = Scheduler(
            self.executor, self.metrics, policy=policy,
            prefill_chunk=prefill_chunk, eos_token_id=eos_token_id,
            max_preemptions=max_preemptions, prefix_cache=self.prefix,
            spec=self.spec, async_exec=async_exec, wal=self.wal)
        self._next_rid = 0
        # aot: None = follow PT_AOT (default off, bit-exact legacy
        # path); "off"/"warm"/"strict" force it (bench A/B).  warm =
        # AOT-compile every (program x shape-rung) pair at build via
        # the persistent compile cache; strict additionally seals the
        # programs so a post-warmup miss raises instead of compiling
        # mid-traffic.  compile_cache: a core.aot.CompileCache, a cache
        # dir path, or None for the PT_COMPILE_CACHE default.
        from paddle_tpu.core import aot as aot_mod

        if aot is None:
            aot = aot_mod.mode()
        if aot not in aot_mod.MODES:
            raise ValueError(f"aot={aot!r}: expected off|warm|strict")
        self.compile_cache = None
        self._aot_report = None
        self.aot_mode = aot
        if aot != "off":
            if not isinstance(compile_cache, aot_mod.CompileCache):
                self.compile_cache = aot_mod.CompileCache(
                    path=compile_cache)
            else:
                self.compile_cache = compile_cache
            self._aot_report = self.executor.aot_warmup(
                prefill_chunk=prefill_chunk,
                compile_cache=self.compile_cache,
                spec_window=(self.spec.k + 1 if self.spec else None),
                decode_n_steps=decode_n_steps)
            if aot == "strict":
                self.executor.seal()
            from paddle_tpu import obs as _obs

            if _obs.handle() is not None:
                _obs.handle().statusz["compile_cache"] = \
                    self.compile_cache.statusz
        # health plane: when telemetry is on, the engine owns an SLO
        # engine evaluated once per step, beats the "serving"
        # heartbeat, and feeds the /statusz pool/occupancy provider.
        # slos: None = stock serving objectives; [] disables; a list
        # of health.*Objective customizes (tests pass tight TTFT
        # objectives with LogicalClock-scale burn windows).
        from paddle_tpu import obs
        from paddle_tpu.obs import health

        self._health = None
        h = obs.handle()
        if h is not None:
            if slos is None:
                slos = health.default_serving_slos()
            if slos:
                self._health = health.SLOEngine(
                    slos, rules=slo_rules or health.DEFAULT_BURN_RULES,
                    handle=h, source="serving",
                    now=self.metrics._t_start)
            h.statusz["serving"] = self._statusz
            if self.wal is not None:
                # a cluster re-registers its own provider after its
                # engines are built (last registration wins)
                h.statusz["durability"] = self._durability_statusz

    # -- submission ------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=16, priority=0,
               deadline=None, on_token=None, rid=None) -> RequestHandle:
        """Enqueue a request; admission happens at the next step().

        ``deadline`` is in scheduler iterations (logical steps) from
        submission; ``on_token(rid, tok)`` streams tokens as they land.
        """
        if rid is None:
            # auto rids must never collide with client-supplied rids:
            # skip ahead until unused so an anonymous submit can never
            # silently dedup to someone else's stream
            rid = f"req-{self._next_rid}"
            while rid in self.scheduler.requests:
                self._next_rid += 1
                rid = f"req-{self._next_rid}"
        elif rid in self.scheduler.requests:
            # idempotent duplicate submit: at-least-once clients get
            # the ORIGINAL handle (live or terminal), never a second
            # stream — the dedup is journaled so recovery replays to
            # the same exactly-once outcome
            return self._dedup(rid, self.scheduler.requests[rid])
        req = Request(rid, prompt_ids, max_new_tokens=max_new_tokens,
                      priority=priority, deadline=deadline,
                      on_token=on_token, arrival_seq=self._next_rid,
                      clock=self.metrics.clock)
        self._next_rid += 1
        if len(req.prompt_ids) == 0:
            raise ValueError("prompt_ids must be non-empty")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.wal is not None:
            # journal acceptance BEFORE the scheduler sees the request
            # so no accepted request can outrun its submit record
            self.wal.append({
                "t": "submit", "rid": rid,
                "prompt": req.prompt_ids.tolist(),
                "max_new": req.max_new_tokens,
                "prio": req.priority, "deadline": req.deadline})
        self.scheduler.add(req)
        return RequestHandle(self, req)

    def _dedup(self, rid, req) -> RequestHandle:
        self.dedup_hits += 1
        if self.wal is not None:
            self.wal.append({"t": "dedup", "rid": rid})
        from paddle_tpu import obs

        h = obs.handle()
        if h is not None:
            h.events.log("req.dedup", rid=rid,
                         state=req.state.value)
        return RequestHandle(self, req)

    def cancel(self, rid) -> None:
        """Flag a request for cancellation; it turns CANCELLED at the
        start of the next step() (pages freed there, not here)."""
        req = self.scheduler.requests.get(rid)
        if req is not None and not req.terminal:
            req.cancel_flag = True

    # -- driving ---------------------------------------------------------

    def step(self) -> dict:
        """One scheduler iteration; returns {rid: [new tokens]}."""
        out = self.scheduler.step()
        if self._health is not None:
            # reuse the timestamp metrics.on_step just read so the
            # health plane adds no clock reads to the step path
            self._health.evaluate(step=self.scheduler.tick,
                                  now=self.metrics._t_last)
            from paddle_tpu import obs

            obs.beat("serving", now=self.metrics._t_last)
        return out

    def run(self, max_steps=100000) -> dict:
        """Step until no request is in flight; returns stats()."""
        while self.scheduler.has_work():
            if self.scheduler.tick >= max_steps:
                raise RuntimeError(
                    f"serving engine did not drain in {max_steps} steps")
            self.step()
        return self.stats()

    # -- introspection ---------------------------------------------------

    @property
    def tick(self) -> int:
        return self.scheduler.tick

    @property
    def in_flight(self) -> int:
        s = self.scheduler
        return len(s.queue) + len(s.prefilling) + len(s.running)

    def request(self, rid):
        return self.scheduler.requests.get(rid)

    def stats(self) -> dict:
        out = self.metrics.stats()
        from paddle_tpu import obs

        if obs.handle() is not None:
            # Pull-model roofline join over the scheduler's spans —
            # stats() time only, never on the per-step hot path.  The
            # scheduler's span names differ from the executor's program
            # names where one span covers several programs.
            out["roofline"] = obs.perf.attribute_from_tracer(
                mapping={"req.prefill": "serve.prefill_chunk"})
        return out

    def _statusz(self) -> dict:
        """/statusz provider: live pool/occupancy plus the roofline
        rows and request-state counts from stats()."""
        cache = self.executor.cache
        s = self.scheduler
        return {
            "tick": s.tick,
            "in_flight": self.in_flight,
            "queued": len(s.queue),
            "prefilling": len(s.prefilling),
            "running": len(s.running),
            "pool": {
                "num_pages": cache.num_pages,
                "free_pages": cache.free_pages,
                "used_pages": cache.num_pages - cache.free_pages,
            },
            "quant": {
                "mode": self.executor.quant,
                "kv_pool_dtype": str(cache.k_pages.dtype),
                "weight_format": ("int8+per-channel-scale"
                                  if self.executor.quant == "int8"
                                  else "checkpoint"),
                "kv_scale_bytes": (0 if cache.k_scales is None else
                                   cache.k_scales.nbytes
                                   + cache.v_scales.nbytes),
            },
            "sp": {
                "mode": ("on" if self.executor.sp_degree > 1
                         else "off"),
                "degree": self.executor.sp_degree,
                "axis": self.executor._sp_axis,
                "min_tokens": self.executor.sp_min_tokens_effective(),
                "prefill_tokens": self.executor.sp_prefill_tokens,
            },
            "async": {
                "mode": "on" if s.async_mode else "off",
                "replans": s.replans,
                "host_overlap_ratio": s.host_overlap_ratio,
                "step_phase_seconds": dict(s.last_phase_seconds),
                "phase_seconds_total": dict(s.phase_totals),
            },
            "stats": self.stats(),
        }

    def _durability_statusz(self) -> dict:
        return {
            "wal": None if self.wal is None else self.wal.statusz(),
            "dedup_hits": self.dedup_hits,
        }
