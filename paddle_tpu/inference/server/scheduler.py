"""Iteration-level (continuous-batching) scheduler.

Orca-style [Yu et al., OSDI 2022]: scheduling decisions happen every
model iteration, not per request.  Each :meth:`step`:

  1. sweeps cancellations and logical deadlines,
  2. runs ONE batched decode over every RUNNING sequence — preempting
     the lowest-priority / latest-arrival victim when the page pool
     cannot cover the batch's next token (freed pages, request
     re-queued for recompute, vLLM-style),
  3. admits queued requests while slots AND pages fit (page-aware
     admission over the PagedKVCache free list),
  4. advances every PREFILLING request by one chunk, so a long prompt
     costs each iteration only ``prefill_chunk`` tokens of prefill and
     in-flight decodes never stall behind it.

Fault points (``paddle_tpu.testing.faults``): ``serve.step`` brackets
the iteration, ``serve.admit`` brackets one admission (before = no
slot allocated yet), ``serve.decode`` brackets the batched decode
dispatch (before = pages reserved, nothing written), and
``serve.request`` brackets one request's prefill work — an exception
there is confined to THAT request (state FAILED), which is the
poisoned-request isolation the tests prove.  Under speculative decode
(``PT_SPEC_DECODE=ngram``) ``spec.draft`` / ``spec.verify`` /
``spec.rollback`` bracket the three phases of :meth:`_decode_spec`
with the same discipline.  Every ``before`` site fires with engine
state either untouched or already committed, so an injected raise
never leaves a half-mutated scheduler.

Double-buffered execution (``PT_ASYNC_EXEC=on``): the iteration is
split into a pure-host ``plan`` (sweeps, preemption decisions, page
reservations — a :class:`StepPlan`) and a ``commit`` that applies the
device results, with the dispatch left UNREALIZED in between.  While
step N runs on device the scheduler optimistically plans step N+1
against the predicted post-N state; if commit invalidates the
prediction (a request finished/failed/was cancelled under the
planner's feet) the plan is discarded and rebuilt — ``replans`` is
the audit counter.  ``async.plan`` / ``async.commit`` /
``async.replan`` bracket the new phases: a commit interrupted by an
injected raise parks the pending device output on ``_inflight`` and
the next step completes it first, so no device work (and no token)
is ever lost.  The interleaving stays deterministic on the logical
clock — the async stream is bit-identical to the sync one.
"""
from __future__ import annotations

import numpy as np

from ... import obs
from ...profiler import RecordEvent
from ...testing import faults
from .request import Request, RequestState
from .wal import stream_crc

_POOL_EXHAUSTED = "KV page pool exhausted"


class StepPlan:
    """The host half of one scheduler iteration, split out so the
    double-buffered path can build step N+1's plan while step N is in
    flight.  ``fingerprint`` is the predicted sorted
    ``(rid, sid, generated)`` tuple the running set must match when
    the plan is adopted; any divergence (finish, failure, cancel,
    deadline) re-plans from live state and bumps the audit counter —
    prediction quality affects only the overlap ratio, never the
    stream."""

    __slots__ = ("tick", "sids", "by_sid", "fingerprint", "kind",
                 "drafts")

    def __init__(self, tick, sids, by_sid, fingerprint=None,
                 kind="decode", drafts=None):
        self.tick = tick
        self.sids = sids
        self.by_sid = by_sid
        self.fingerprint = fingerprint
        self.kind = kind
        self.drafts = drafts


class Scheduler:
    def __init__(self, executor, metrics, policy="fifo",
                 prefill_chunk=None, eos_token_id=None,
                 max_preemptions=4, prefix_cache=None, spec=None,
                 async_exec=False, wal=None):
        if policy not in ("fifo", "priority"):
            raise ValueError(
                f"policy must be 'fifo' or 'priority', got {policy!r}")
        self.executor = executor
        self.metrics = metrics
        self.prefix = prefix_cache   # radix prefix index (None = off)
        self.spec = spec             # SpecDecode bundle (None = off)
        self.policy = policy
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        self.eos_token_id = eos_token_id
        self.max_preemptions = int(max_preemptions)
        self.requests: dict = {}     # rid -> Request (all ever seen)
        self.queue: list = []        # QUEUED, admission order
        self.prefilling: list = []   # hold a slot, prompt KV partial
        self.running: list = []      # hold a slot, decoding
        self.tick = 0                # logical clock (iterations)
        self._last_decode_batch = 0
        # telemetry handle cached at construction: the off path is one
        # None check per site, and tests reconfigure obs BEFORE
        # building the engine under test
        self._obs = obs.handle()
        # write-ahead request journal (None = off, bit-exact): the
        # scheduler owns the admit/token/finish records — every token
        # from the sync, async, spec-verify and prefill-final paths
        # funnels through _on_token, so one hook covers all variants
        self.wal = wal
        # double-buffered execution state (PT_ASYNC_EXEC=on): the plan
        # built while the previous step was in flight, a commit a
        # fault interrupted mid-step, the replan audit counter, and
        # the host-overlap accounting the statusz/bench surfaces read
        self.async_mode = bool(async_exec)
        self._pending = None     # StepPlan parked for the next step
        self._inflight = None    # (StepPlan, pending) awaiting commit
        self.replans = 0
        self.overlapped_s = 0.0  # host seconds hidden behind device
        self.device_s = 0.0      # dispatch-to-fence wall seconds
        self.last_phase_seconds = {}
        self.phase_totals = {}
        self._timer = None
        if self.async_mode:
            from ...obs.perf import StepTimer

            self._timer = StepTimer("serve.step_async")
            self._timer.PHASES = ("plan", "dispatch", "overlap",
                                  "fence", "commit")

    # -- submission boundary (called by the engine) ---------------------

    def add(self, req: Request) -> None:
        self.requests[req.rid] = req
        self.metrics.on_submit(req, self.tick)
        if self._obs is not None:
            self._obs.tracer.instant(
                "req.submit", cat="serve", trace_id=req.rid,
                prompt_tokens=len(req.prompt_ids), tick=self.tick)
        ex = self.executor
        budget_tokens = (ex.cache.max_pages_per_seq
                         * ex.cache.page_size)
        # +1: the first decode step writes the token AFTER the prompt
        if (len(req.prompt_ids) + 1 > min(ex.max_len, budget_tokens)
                or ex.pages_for(len(req.prompt_ids) + 1)
                > ex.cache.num_pages):
            self._finish(req, RequestState.EVICTED, "too_large")
            return
        self.queue.append(req)

    def has_work(self) -> bool:
        return bool(self.queue or self.prefilling or self.running)

    # -- the iteration --------------------------------------------------

    def step(self) -> dict:
        """One scheduler iteration.  Returns {rid: [tokens emitted]}."""
        faults.fire("serve.step", "before")
        self.tick += 1
        emitted: dict = {}
        h = self._obs
        sp = (h.tracer.span("serve.step", cat="serve", tick=self.tick)
              if h is not None else obs.NULL_SPAN)
        with sp, RecordEvent("serve.step"):
            if self.async_mode:
                self._step_async(emitted)
            else:
                self._sweep_cancelled()
                self._sweep_deadlines()
                self._decode(emitted)
                self._admit()
                self._prefill(emitted)
        self.metrics.on_step(
            decode_batch=self._last_decode_batch,
            pages_used=(self.executor.cache.num_pages
                        - self.executor.free_pages),
            in_flight=len(self.queue) + len(self.prefilling)
            + len(self.running))
        faults.fire("serve.step", "after")
        return emitted

    # -- sweeps ---------------------------------------------------------

    def _sweep_cancelled(self):
        for r in [r for r in self.requests.values()
                  if r.cancel_flag and not r.terminal]:
            self._finish(r, RequestState.CANCELLED, "cancelled")

    def _sweep_deadlines(self):
        for r in [r for r in self.requests.values()
                  if not r.terminal and r.deadline is not None
                  and self.tick - r.submit_step > r.deadline]:
            self._finish(r, RequestState.TRUNCATED, "deadline")

    # -- decode with preemption under page pressure ---------------------

    def _reserve_decode_batch(self, extra_fn):
        """Preemption-under-pressure reservation loop shared by the
        sync and async paths: reserve each RUNNING sequence's lookahead
        (``extra_fn(sids, by_sid)`` -> extra_tokens for reserve()),
        preempting the victim policy's pick while the pool cannot cover
        the batch.  Returns the surviving run list ([] when every
        holder failed/preempted away).  The reservation is idempotent,
        so the executor's own reserve() inside decode()/verify()
        re-verifies without re-allocating."""
        run = [r for r in self.running]
        while run:
            sids = sorted(r.sid for r in run)
            by_sid = {r.sid: r for r in run}
            try:
                self.executor.cache.reserve(
                    sids, extra_tokens=extra_fn(sids, by_sid))
                return run
            except RuntimeError as e:
                if _POOL_EXHAUSTED not in str(e):
                    raise
                victim = self._pick_victim()
                if victim is None or (len(run) == 1 and victim is run[0]
                                      and not self.prefilling):
                    # the lone sequence cannot grow even with the whole
                    # pool free: the pool is undersized for one request
                    self._finish(
                        run[0], RequestState.FAILED, "pool_exhausted",
                        error=RuntimeError(
                            f"{_POOL_EXHAUSTED} for a single sequence "
                            f"(pool {self.executor.cache.num_pages} "
                            f"pages)"))
                    run = [r for r in self.running]
                    continue
                self._preempt(victim)
                run = [r for r in self.running]
        return run

    def _decode(self, emitted):
        if self.spec is not None:
            self._decode_spec(emitted)
            return
        self._last_decode_batch = 0
        run = self._reserve_decode_batch(lambda sids, by_sid: 1)
        if not run:
            return
        sids = sorted(r.sid for r in run)
        by_sid = {r.sid: r for r in run}
        faults.fire("serve.decode", "before")
        h = self._obs
        sp = (h.tracer.span("serve.decode", cat="serve",
                            batch=len(sids), tick=self.tick)
              if h is not None else obs.NULL_SPAN)
        with sp, RecordEvent("serve.decode"):
            toks = self.executor.decode(sids)
        self._last_decode_batch = len(sids)
        self.metrics.on_decode_tokens(len(sids))
        for sid in sids:
            self._on_token(by_sid[sid], toks[sid], emitted)
        faults.fire("serve.decode", "after")

    # -- speculative decode (draft -> batched verify -> rollback) -------

    def _spec_limit(self, req, draft_len):
        """How many window tokens this sequence may COMMIT this step:
        1 (the plain greedy token) plus at most ``draft_len`` accepted
        drafts, clamped to the per-seq page budget and the remaining
        generation cap — so a verify step can never overshoot
        ``max_new_tokens``/``max_len`` or write past the page table."""
        ex = self.executor
        budget = ex.cache.max_pages_per_seq * ex.cache.page_size
        cap = min(req.max_new_tokens,
                  ex.max_len - len(req.prompt_ids))
        return max(1, min(self.spec.k + 1, int(draft_len) + 1,
                          cap - len(req.generated),
                          budget - int(ex.cache.lengths[req.sid])))

    def _decode_spec(self, emitted):
        """Spec-mode decode iteration: propose per-request drafts from
        the n-gram index, reserve each sequence's clamped lookahead
        (same preemption-under-pressure loop as plain decode, just a
        wider ask), verify every window in ONE jitted call, emit
        ``1 + accepted`` tokens per sequence, then trim the pages the
        rejected tail had reserved.

        Fault points: ``spec.draft`` brackets the (pure) draft sweep,
        ``spec.verify`` brackets dispatch-through-emission (before =
        pages reserved, nothing written — a raise retries cleanly next
        step), ``spec.rollback`` brackets the page trim (a raise leaves
        pages assigned-but-unused, which free()/the next trim recovers).
        """
        ex = self.executor
        run = [r for r in self.running]
        self._last_decode_batch = 0
        if not run:
            return
        # draft sweep: pure reads of the per-request n-gram index —
        # an injected raise here escapes step() with nothing mutated
        faults.fire("spec.draft", "before")
        drafts = {r.rid: self.spec.propose(r) for r in run}
        faults.fire("spec.draft", "after")
        run = self._reserve_decode_batch(
            lambda sids, by_sid: [
                self._spec_limit(by_sid[s], len(drafts[by_sid[s].rid]))
                for s in sids])
        if not run:
            return
        sids = sorted(r.sid for r in run)
        by_sid = {r.sid: r for r in run}
        lims = [self._spec_limit(by_sid[s], len(drafts[by_sid[s].rid]))
                for s in sids]
        dr = [drafts[by_sid[s].rid][:lim - 1]
              for s, lim in zip(sids, lims)]
        faults.fire("spec.verify", "before")
        h = self._obs
        sp = (h.tracer.span("serve.verify", cat="serve",
                            batch=len(sids), tick=self.tick,
                            drafted=sum(len(v) for v in dr))
              if h is not None else obs.NULL_SPAN)
        with sp, RecordEvent("serve.decode"):
            toks, accepted = ex.verify(sids, dr, lims, self.spec.k)
        self._last_decode_batch = len(sids)
        self.metrics.on_decode_step(
            slots=len(sids), tokens=sum(len(v) for v in toks.values()))
        self.metrics.on_spec(proposed=sum(len(d) for d in dr),
                             accepted=sum(accepted.values()))
        for i, sid in enumerate(sids):
            req = by_sid[sid]
            req.draft_proposed += len(dr[i])
            req.draft_accepted += accepted[sid]
            for tok in toks[sid]:
                if req.terminal:
                    break   # tokens past eos/cap are dropped
                self._on_token(req, tok, emitted)
        faults.fire("spec.verify", "after")
        faults.fire("spec.rollback", "before")
        ex.rollback([r.sid for r in run if r.sid is not None])
        if h is not None:
            # per-request rollback journal: the rejected-draft tail of
            # every verified window is trimmed here
            for i, sid in enumerate(sids):
                rejected = len(dr[i]) - accepted[sid]
                if rejected > 0:
                    h.recorder.record("spec.rollback",
                                      rid=by_sid[sid].rid,
                                      rejected=rejected, tick=self.tick)
                    h.tracer.instant("req.spec_rollback", cat="serve",
                                     trace_id=by_sid[sid].rid,
                                     rejected=rejected)
        faults.fire("spec.rollback", "after")

    # -- double-buffered execution (PT_ASYNC_EXEC=on) -------------------

    @property
    def host_overlap_ratio(self) -> float:
        """Overlapped-host-seconds / device-compute-seconds over the
        scheduler's lifetime (0.0 before the first async decode)."""
        return (self.overlapped_s / self.device_s
                if self.device_s > 0 else 0.0)

    def _step_async(self, emitted):
        """One double-buffered iteration: adopt (or rebuild) the plan
        parked while the previous step was in flight, dispatch without
        realizing the result, plan the NEXT step against the predicted
        post-step state while the device runs, then fence + commit."""
        clk = self.metrics.clock
        ph = {}
        t0 = clk()
        faults.fire("async.plan", "before")
        if self._inflight is not None:
            # a fault escaped between dispatch and commit last step:
            # complete the parked commit first so no device work (and
            # no token) is lost — they land in THIS step's emitted map
            # but every per-request stream stays exact
            plan0, pending0 = self._inflight
            pending0.wait()
            self._inflight = None
            if plan0.kind == "verify":
                self._commit_verify(plan0, pending0, emitted)
            else:
                self._commit_decode(plan0, pending0, emitted)
        self._sweep_cancelled()
        self._sweep_deadlines()
        if self.spec is not None:
            t1 = self._step_async_spec(emitted, clk, ph, t0)
        else:
            t1 = self._step_async_plain(emitted, clk, ph, t0)
        self._admit()
        self._prefill(emitted)
        ph["commit"] = ph.get("commit", 0.0) + (clk() - t1)
        self._publish_phases(ph)

    def _step_async_plain(self, emitted, clk, ph, t0):
        self._last_decode_batch = 0
        plan = self._obtain_plan()
        faults.fire("async.plan", "after")
        t1 = clk()
        ph["plan"] = t1 - t0
        if plan is None:
            return t1
        h = self._obs
        sp = (h.tracer.span("serve.decode_async", cat="serve",
                            batch=len(plan.sids), tick=self.tick)
              if h is not None else obs.NULL_SPAN)
        with sp, RecordEvent("serve.decode"):
            pending = self.executor.decode_async(plan.sids)
            t2 = clk()
            ph["dispatch"] = t2 - t1
            self._plan_ahead(plan)
            t3 = clk()
            ph["overlap"] = t3 - t2
            self._inflight = (plan, pending)
            faults.fire("async.commit", "before")
            pending.wait()
            self._inflight = None
            t4 = clk()
            ph["fence"] = t4 - t3
        self._commit_decode(plan, pending, emitted)
        faults.fire("async.commit", "after")
        self.overlapped_s += ph["overlap"]
        self.device_s += ph["dispatch"] + ph["overlap"] + ph["fence"]
        return t4

    def _step_async_spec(self, emitted, clk, ph, t0):
        ex = self.executor
        self._last_decode_batch = 0
        run = [r for r in self.running]
        if not run:
            faults.fire("async.plan", "after")
            t1 = clk()
            ph["plan"] = t1 - t0
            return t1
        faults.fire("spec.draft", "before")
        drafts = {r.rid: self.spec.propose(r) for r in run}
        faults.fire("spec.draft", "after")
        run = self._reserve_decode_batch(
            lambda sids, by_sid: [
                self._spec_limit(by_sid[s], len(drafts[by_sid[s].rid]))
                for s in sids])
        faults.fire("async.plan", "after")
        t1 = clk()
        ph["plan"] = t1 - t0
        if not run:
            return t1
        sids = sorted(r.sid for r in run)
        by_sid = {r.sid: r for r in run}
        lims = [self._spec_limit(by_sid[s], len(drafts[by_sid[s].rid]))
                for s in sids]
        dr = [drafts[by_sid[s].rid][:lim - 1]
              for s, lim in zip(sids, lims)]
        plan = StepPlan(self.tick, sids, by_sid, kind="verify",
                        drafts=dr)
        faults.fire("spec.verify", "before")
        h = self._obs
        sp = (h.tracer.span("serve.verify", cat="serve",
                            batch=len(sids), tick=self.tick,
                            drafted=sum(len(v) for v in dr))
              if h is not None else obs.NULL_SPAN)
        with sp, RecordEvent("serve.decode"):
            pending = ex.verify_async(sids, dr, lims, self.spec.k)
            t2 = clk()
            ph["dispatch"] = t2 - t1
            self._inflight = (plan, pending)
            faults.fire("async.commit", "before")
            pending.wait()
            self._inflight = None
            t3 = clk()
            ph["fence"] = t3 - t2
        self._commit_verify(plan, pending, emitted)
        faults.fire("async.commit", "after")
        self.device_s += ph["dispatch"] + ph["fence"]
        return t3

    def _obtain_plan(self):
        """The parked plan if its prediction survived commit, else a
        fresh one from live state (the replan path — audited)."""
        plan, self._pending = self._pending, None
        if plan is not None and not self._plan_valid(plan):
            faults.fire("async.replan", "before")
            self.replans += 1
            if self._obs is not None:
                self._obs.recorder.record("async.replan",
                                          tick=self.tick)
                self._obs.tracer.instant("async.replan", cat="serve",
                                         tick=self.tick)
            faults.fire("async.replan", "after")
            plan = None
        if plan is None:
            plan = self._build_plan()
        return plan

    def _build_plan(self):
        run = self._reserve_decode_batch(lambda sids, by_sid: 1)
        if not run:
            return None
        return StepPlan(self.tick, sorted(r.sid for r in run),
                        {r.sid: r for r in run})

    def _plan_valid(self, plan) -> bool:
        if plan.tick != self.tick or self.prefilling:
            return False
        actual = tuple(sorted((r.rid, r.sid, len(r.generated))
                              for r in self.running))
        return actual == plan.fingerprint

    def _plan_ahead(self, plan):
        """The overlapped host work: while the dispatched step runs on
        device, reserve the NEXT step's decode pages against the
        predicted post-step state (the executor already advanced
        lengths at dispatch) and fingerprint the prediction.

        Strictly speculative: nothing observable may move — no
        preemption, no failure, and no prefix eviction (the reclaimer
        is disabled so the reserve draws from free pages only; a
        shortfall just abandons the speculation and the next step
        plans live, where the sync-equivalent eviction/preemption
        logic runs).  Page identity never affects numerics (attention
        gathers through the page table), so early reservation cannot
        perturb the stream."""
        self._pending = None
        if self.queue or self.prefilling:
            return  # admissions/prefills this step would shift state
        ex = self.executor
        survivors = []
        for sid in plan.sids:
            r = plan.by_sid[sid]
            cap = min(r.max_new_tokens,
                      ex.max_len - len(r.prompt_ids))
            if len(r.generated) + 1 >= cap:
                continue  # finishes this step on the length cap
            survivors.append(r)
        if not survivors:
            return
        sids = sorted(r.sid for r in survivors)
        cache = ex.cache
        saved, cache.reclaimer = cache.reclaimer, None
        try:
            cache.reserve(sids, extra_tokens=1)
        except RuntimeError as e:
            if _POOL_EXHAUSTED not in str(e):
                raise
            return  # pool too tight to speculate
        finally:
            cache.reclaimer = saved
        fp = tuple(sorted((r.rid, r.sid, len(r.generated) + 1)
                          for r in survivors))
        self._pending = StepPlan(self.tick + 1, sids,
                                 {r.sid: r for r in survivors},
                                 fingerprint=fp)

    def _commit_decode(self, plan, pending, emitted):
        """Apply one async decode's device results — the sync tail of
        :meth:`_decode`, fed from the pending object's fence."""
        toks = pending.wait()
        self._last_decode_batch = len(plan.sids)
        self.metrics.on_decode_tokens(len(plan.sids))
        for sid in plan.sids:
            self._on_token(plan.by_sid[sid], toks[sid], emitted)

    def _commit_verify(self, plan, pending, emitted):
        """Apply one async verify's device results — the sync tail of
        :meth:`_decode_spec` (emission, spec metrics, rollback)."""
        toks, accepted = pending.wait()
        sids, by_sid, dr = plan.sids, plan.by_sid, plan.drafts
        self._last_decode_batch = len(sids)
        self.metrics.on_decode_step(
            slots=len(sids), tokens=sum(len(v) for v in toks.values()))
        self.metrics.on_spec(proposed=sum(len(d) for d in dr),
                             accepted=sum(accepted.values()))
        for i, sid in enumerate(sids):
            req = by_sid[sid]
            req.draft_proposed += len(dr[i])
            req.draft_accepted += accepted[sid]
            for tok in toks[sid]:
                if req.terminal:
                    break   # tokens past eos/cap are dropped
                self._on_token(req, tok, emitted)
        faults.fire("spec.verify", "after")
        faults.fire("spec.rollback", "before")
        self.executor.rollback(
            [r.sid for r in by_sid.values() if r.sid is not None])
        h = self._obs
        if h is not None:
            for i, sid in enumerate(sids):
                rejected = len(dr[i]) - accepted[sid]
                if rejected > 0:
                    h.recorder.record("spec.rollback",
                                      rid=by_sid[sid].rid,
                                      rejected=rejected, tick=self.tick)
                    h.tracer.instant("req.spec_rollback", cat="serve",
                                     trace_id=by_sid[sid].rid,
                                     rejected=rejected)
        faults.fire("spec.rollback", "after")

    def _publish_phases(self, ph):
        """Fold one async step's phase seconds into the totals and,
        when telemetry is on, publish the ``step_phase_seconds`` gauges
        + Perfetto counter track (via StepTimer) and the
        ``serving_host_overlap_ratio`` gauge + counter track."""
        if not ph:
            return
        self.last_phase_seconds = dict(ph)
        for k, v in ph.items():
            self.phase_totals[k] = self.phase_totals.get(k, 0.0) + v
        h = self._obs
        if h is None:
            return
        self._timer._acc = dict(ph)
        self._timer.end_step()
        h.registry.gauge(
            "serving_host_overlap_ratio",
            "Overlapped host seconds / device compute seconds "
            "(async double-buffered executor)"
        ).set(self.host_overlap_ratio)
        h.tracer.counter("perf.host_overlap", cat="perf",
                         ratio=round(self.host_overlap_ratio, 6))

    # -- page-aware admission -------------------------------------------

    def _committed_pages(self) -> int:
        """Pages PROMISED to in-progress prefills but not yet written:
        free_pages only drops when a chunk lands, so admission must
        subtract what already-admitted prompts will still consume."""
        ex = self.executor
        total = 0
        for r in self.prefilling:
            held = int((ex.cache.page_table[r.sid] >= 0).sum())
            total += max(0, ex.pages_for(
                self._token_target(len(r.resume_ids))) - held)
        return total

    def _token_target(self, prompt_tokens: int) -> int:
        """Tokens a request must be able to hold right after prefill:
        prompt + 1 for plain decode, prompt + worst-case ``k + 1``
        window under speculative decode (clamped to the per-seq
        budget, which bounds every sequence anyway)."""
        ex = self.executor
        lookahead = 1 if self.spec is None else self.spec.k + 1
        budget = ex.cache.max_pages_per_seq * ex.cache.page_size
        return min(prompt_tokens + lookahead, budget)

    def _admit(self):
        ex = self.executor
        while self.queue:
            req = self._pick_next()
            hit_tokens, hit_pages = 0, []
            if self.prefix is not None:
                faults.fire("prefix.match", "before")
                hit_tokens, hit_pages = self.prefix.match(req.resume_ids)
                faults.fire("prefix.match", "after")
            # admission pays only for NOVEL pages: matched pages are
            # attached by reference.  A mid-page hit budgets one extra
            # page for the copy-on-write of the partial page, and cold
            # cached pages count as available (eviction frees them).
            need = (ex.pages_for(self._token_target(len(req.resume_ids)))
                    - len(hit_pages))
            if hit_tokens % ex.cache.page_size:
                need += 1
            avail = ex.free_pages - self._committed_pages()
            if self.prefix is not None:
                avail += max(
                    0, self.prefix.evictable_pages() - len(hit_pages))
            if ex.free_slots < 1 or avail < need:
                if self.policy == "priority":
                    victim = self._pick_victim(below=req.priority)
                    if victim is not None:
                        self._preempt(victim)
                        continue
                break  # FIFO: head-of-line blocking keeps arrival order
            faults.fire("serve.admit", "before")
            req.sid = ex.alloc_slot()
            req.prefill_done = 0
            if hit_tokens:
                ex.attach_prefix(req.sid, hit_pages, hit_tokens)
                req.prefill_done = hit_tokens
                req.cached_tokens = hit_tokens
                self.metrics.on_prefix_hit(hit_tokens)
            req.state = RequestState.PREFILLING
            self.queue.remove(req)
            self.prefilling.append(req)
            self.metrics.on_sched(req, self.tick)
            if self._obs is not None:
                self._obs.tracer.instant(
                    "req.admit", cat="serve", trace_id=req.rid,
                    sid=req.sid, tick=self.tick,
                    cached_tokens=int(hit_tokens),
                    resume=int(req.preempt_count > 0))
                self._obs.events.log(
                    "req.admit", rid=req.rid, tick=self.tick,
                    cached_tokens=int(hit_tokens),
                    resume=int(req.preempt_count > 0))
            if self.wal is not None:
                self.wal.append({"t": "admit", "rid": req.rid,
                                 "tick": self.tick})
            faults.fire("serve.admit", "after")

    def _pick_next(self):
        if self.policy == "priority":
            return max(self.queue,
                       key=lambda r: (r.priority, -r.arrival_seq))
        return self.queue[0]

    def _pick_victim(self, below=None):
        """Lowest-priority, latest-arrival slot holder (running or
        prefilling); ``below`` restricts to strictly lower priority."""
        cands = self.running + self.prefilling
        if below is not None:
            cands = [r for r in cands if r.priority < below]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.priority, -r.arrival_seq))

    # -- chunked prefill -------------------------------------------------

    def _prefill(self, emitted):
        # a warmed executor publishes its AOT bucket ladder: chunks are
        # floor-quantized onto the rungs (any prompt decomposes into
        # descending rungs, so every chunk shape is pre-compiled) and
        # whole prompts route through prefill_chunk — serve.prefill's
        # [1, S] shape is unbounded and cannot be warmed
        ladder = getattr(self.executor, "aot_ladder", None)
        for req in list(self.prefilling):
            ids = req.resume_ids
            total = len(ids)
            start = req.prefill_done
            chunk = (total - start if self.prefill_chunk is None
                     else min(self.prefill_chunk, total - start))
            if ladder is not None:
                chunk = ladder.floor(chunk)
            final = start + chunk == total
            try:
                # page work FIRST, outside the per-request bracket: a
                # pool-exhausted raise preempts (not fails) the request,
                # and an injected prefix.cow fault escapes step() with
                # the pool consistent — the next step() retries cleanly
                self.executor.prepare_write(req.sid, start, chunk)
            except RuntimeError as e:
                if _POOL_EXHAUSTED not in str(e):
                    raise
                self._preempt(req)
                continue
            try:
                faults.fire("serve.request", "before")
                h = self._obs
                sp = (h.tracer.span("req.prefill", cat="serve",
                                    trace_id=req.rid, start=start,
                                    tokens=chunk, final=final,
                                    tick=self.tick)
                      if h is not None else obs.NULL_SPAN)
                # long prompts plan sequence-parallel: above the
                # (rung-quantized) length threshold, and only when the
                # chunk stripes evenly with >= 2 rows per rank —
                # everything else is the bit-exact single-device path,
                # so PT_SP_PREFILL=off changes nothing at all
                spn = getattr(self.executor, "sp_degree", 1)
                use_sp = (
                    spn > 1
                    and total >=
                    self.executor.sp_min_tokens_effective()
                    and chunk % spn == 0 and chunk >= 2 * spn)
                with sp, RecordEvent("serve.prefill"):
                    if (start == 0 and final and ladder is None
                            and not use_sp):
                        tok = self.executor.prefill(req.sid, ids)
                    elif use_sp:
                        tok = self.executor.prefill_sp(
                            req.sid, ids[start:start + chunk], start,
                            final)
                    else:
                        tok = self.executor.prefill_chunk(
                            req.sid, ids[start:start + chunk], start,
                            final)
                faults.fire("serve.request", "after")
            except RuntimeError as e:
                if _POOL_EXHAUSTED in str(e):
                    # decodes ate the pages between admission and this
                    # chunk: give the slot back and retry via the queue
                    self._preempt(req)
                    continue
                self._fail(req, e)
                continue
            except Exception as e:  # poisoned request fails ALONE
                self._fail(req, e)
                continue
            req.prefill_done = start + chunk
            self.metrics.on_prefill_tokens(chunk)
            if final:
                self.prefilling.remove(req)
                self.running.append(req)
                req.state = RequestState.RUNNING
                if self.prefix is not None:
                    # publish BEFORE the first token can finish the
                    # request: _finish frees the slot, and the tree's
                    # reference is what keeps the pages alive past it
                    self.prefix.insert(
                        ids, self.executor.cache.page_table[req.sid])
                if self.spec is not None:
                    # seed the draft index from prompt + generated
                    # BEFORE the first token extends it
                    self.spec.on_running(req)
                self._on_token(req, tok, emitted)

    # -- request transitions --------------------------------------------

    def _on_token(self, req, tok, emitted):
        req.emit(tok)
        if self.spec is not None:
            self.spec.on_token(req, tok)
        emitted.setdefault(req.rid, []).append(int(tok))
        if self.wal is not None:
            # "i" is the token's stream index: replay only trusts a
            # contiguous-from-zero prefix, so one bit-rotted token
            # record downgrades everything past it to recompute
            self.wal.append({"t": "token", "rid": req.rid,
                             "tok": int(tok),
                             "i": len(req.generated) - 1})
        if req.first_token_step is None:
            self.metrics.on_first_token(req, self.tick)
            if self._obs is not None:
                self._obs.tracer.instant(
                    "req.first_token", cat="serve", trace_id=req.rid,
                    tick=self.tick)
        if (self.eos_token_id is not None
                and int(tok) == int(self.eos_token_id)):
            self._finish(req, RequestState.FINISHED, "eos")
            return
        cap = min(req.max_new_tokens,
                  self.executor.max_len - len(req.prompt_ids))
        if len(req.generated) >= cap:
            if cap < req.max_new_tokens:
                self._finish(req, RequestState.TRUNCATED, "length")
            else:
                self._finish(req, RequestState.FINISHED, "length")

    def _preempt(self, req):
        """Free the victim's pages and re-queue it for recompute: on
        re-admission the prompt PLUS the already-streamed tokens are
        prefilled again and decoding resumes where it left off."""
        self.metrics.on_preempt(req)
        req.preempt_count += 1
        if self._obs is not None:
            self._obs.recorder.record(
                "serve.preempt", rid=req.rid, tick=self.tick,
                preempt_count=req.preempt_count,
                generated=len(req.generated))
            self._obs.tracer.instant(
                "req.preempt", cat="serve", trace_id=req.rid,
                tick=self.tick, preempt_count=req.preempt_count)
        self._release(req)
        if req.preempt_count > self.max_preemptions:
            self._finish(req, RequestState.EVICTED, "preempt_budget")
            return
        req.resume_ids = np.concatenate(
            [req.prompt_ids,
             np.asarray(req.generated, np.int32)]).astype(np.int32)
        req.prefill_done = 0
        req.state = RequestState.QUEUED
        self.queue.insert(0, req)  # seniority: re-admitted first

    def _release(self, req):
        if self.spec is not None:
            self.spec.on_release(req)
        if req.sid is not None:
            self.executor.free_slot(req.sid)
            req.sid = None
        for pool in (self.queue, self.prefilling, self.running):
            if req in pool:
                pool.remove(req)

    def _fail(self, req, error):
        req.error = error
        self._finish(req, RequestState.FAILED,
                     f"{type(error).__name__}: {error}")

    def _finish(self, req, state, reason, error=None):
        if error is not None:
            req.error = error
        self._release(req)
        req.state = state
        req.finish_reason = reason
        self.metrics.on_terminal(req, self.tick)
        if self.wal is not None:
            # n + crc let replay PROVE a journaled stream is complete
            # before serving it from the log; any mismatch downgrades
            # the request to the bit-identical recompute path
            self.wal.append({
                "t": "finish", "rid": req.rid, "state": state.value,
                "reason": reason, "n": len(req.generated),
                "crc": stream_crc(req.generated)})
        if self._obs is not None:
            self._obs.tracer.instant(
                "req.finish", cat="serve", trace_id=req.rid,
                tick=self.tick, state=state.value, reason=reason,
                tokens=len(req.generated))
            self._obs.events.log(
                "req.finish", rid=req.rid, tick=self.tick,
                state=state.value, reason=reason,
                tokens=len(req.generated))
            if state is RequestState.FAILED:
                self._obs.recorder.record(
                    "serve.request_failed", rid=req.rid,
                    tick=self.tick, reason=reason)
                obs.auto_dump(f"request-failed-{req.rid}",
                              extra={"rid": req.rid, "reason": reason})
