"""Shared-prefix KV cache: radix tree over token-id page runs.

The RadixAttention insight (SGLang) married to vLLM-style block
sharing: at millions-of-users scale most prompts share long common
prefixes (system prompts, few-shot templates, multi-turn history), so
their KV pages should be computed once and attached by reference.

Structure: a radix tree whose nodes own PAGE-ALIGNED token spans (a
run of one or more full pages) plus the page ids holding their KV.
Children are keyed by the full first-page token tuple, so descending
one edge certifies an exact full-page match; divergence *inside* a
page is handled by a partial attach of that page — the consumer's
first write to it copy-on-writes (see ``PagedKVCache.make_writable``).

Ownership: the tree holds ONE refcount on every page it indexes, on
top of whatever slots reference it, so ``PagedKVCache.free`` on a
finished sequence leaves shared pages alive.  Eviction is LRU over
zero-refcount leaves — nodes whose pages nobody but the tree holds
(``page_refs == 1``) and that have no children — and is driven by the
pool's ``reclaimer`` hook whenever an allocation would otherwise
raise pool-exhausted.

Fault points: ``prefix.match`` brackets one admission-time tree walk
(fired by the scheduler), ``prefix.cow`` brackets one copy-on-write
page copy (fired by the cache), ``prefix.evict`` brackets one node
eviction (fired here).  All three leave the pool consistent on an
injected raise at either phase.
"""
from __future__ import annotations

import numpy as np

from ...testing import faults


class _Node:
    """One radix-tree node: a page-aligned token span and its pages.

    ``tokens`` is an int32 array of ``len(pages) * page_size`` token
    ids; ``children`` maps the first-page token tuple of each child
    span to the child node.  The root is a sentinel with an empty span.
    """

    __slots__ = ("tokens", "pages", "children", "parent", "last_access")

    def __init__(self, tokens, pages, parent, last_access):
        self.tokens = tokens
        self.pages = list(pages)
        self.children = {}
        self.parent = parent
        self.last_access = last_access

    def __repr__(self):
        return (f"_Node(pages={self.pages}, "
                f"children={len(self.children)})")


def _common_prefix(a, b) -> int:
    n = min(len(a), len(b))
    if n == 0:
        return 0
    neq = np.asarray(a[:n]) != np.asarray(b[:n])
    idx = int(np.argmax(neq))
    return n if not neq[idx] else idx


class PrefixCache:
    """Radix-tree prefix index over a :class:`PagedKVCache` page pool.

    ``on_evict(n_pages)`` (optional) is called after each eviction —
    the engine wires it to ``EngineMetrics.on_prefix_evict``.
    """

    def __init__(self, cache, on_evict=None):
        self.cache = cache
        self.ps = cache.page_size
        self.on_evict = on_evict
        self._clock = 0
        self.root = _Node(np.zeros((0,), np.int32), [], None, 0)
        # counters (monotonic; surfaced through EngineMetrics)
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.inserted_pages = 0
        self.evicted_pages = 0
        self.evictions = 0           # nodes evicted

    def _key(self, tokens, page_idx=0):
        lo = page_idx * self.ps
        return tuple(int(t) for t in tokens[lo:lo + self.ps])

    # -- lookup ----------------------------------------------------------

    def match(self, token_ids):
        """Longest cached prefix of ``token_ids``: returns
        ``(n_tokens, page_ids)`` where the pages cover exactly
        ``n_tokens`` positions.  Full pages match whole; at the first
        divergence (or when the cap bites) at most one page is matched
        PARTIALLY — its trailing positions belong to another prompt and
        the first write to it will copy-on-write.

        The match is capped at ``len(token_ids) - 1``: the final prompt
        token is always recomputed so prefill still produces the
        first-token logits.
        """
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        limit = len(ids) - 1
        self._clock += 1
        self.lookups += 1
        node = self.root
        node.last_access = self._clock
        pos = 0
        pages: list = []
        while pos < limit:
            child = None
            if pos + self.ps <= len(ids):
                child = node.children.get(
                    tuple(int(t) for t in ids[pos:pos + self.ps]))
            if child is not None:
                child.last_access = self._clock
                done = False
                for j in range(len(child.pages)):
                    span = child.tokens[j * self.ps:(j + 1) * self.ps]
                    rest = ids[pos:]
                    if len(rest) - 1 >= self.ps \
                            and np.array_equal(span, rest[:self.ps]):
                        pages.append(child.pages[j])
                        pos += self.ps
                        continue
                    t = min(_common_prefix(span, rest), limit - pos)
                    if t > 0:
                        pages.append(child.pages[j])
                        pos += t
                    done = True
                    break
                if done:
                    break
                node = child
                continue
            # no exact full-page edge: try a partial first-page match
            best_t, best_child = 0, None
            for c in node.children.values():
                t = min(_common_prefix(c.tokens[:self.ps], ids[pos:]),
                        limit - pos)
                if t > best_t:
                    best_t, best_child = t, c
            if best_child is not None:
                best_child.last_access = self._clock
                pages.append(best_child.pages[0])
                pos += best_t
            break
        if pos:
            self.hits += 1
            self.hit_tokens += pos
        return pos, pages

    def match_len(self, token_ids) -> int:
        """Read-only affinity probe: how many leading tokens of
        ``token_ids`` this tree already holds.  Same walk as
        :meth:`match` but touches NOTHING — no LRU clock bump, no
        ``last_access``, no hit counters — so the cluster router can
        probe every replica per request without perturbing eviction
        order or hit-rate stats on the replicas that lose the vote."""
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        limit = len(ids) - 1
        node = self.root
        pos = 0
        while pos < limit:
            child = None
            if pos + self.ps <= len(ids):
                child = node.children.get(
                    tuple(int(t) for t in ids[pos:pos + self.ps]))
            if child is not None:
                done = False
                for j in range(len(child.pages)):
                    span = child.tokens[j * self.ps:(j + 1) * self.ps]
                    rest = ids[pos:]
                    if len(rest) - 1 >= self.ps \
                            and np.array_equal(span, rest[:self.ps]):
                        pos += self.ps
                        continue
                    t = min(_common_prefix(span, rest), limit - pos)
                    pos += t
                    done = True
                    break
                if done:
                    break
                node = child
                continue
            best_t = 0
            for c in node.children.values():
                t = min(_common_prefix(c.tokens[:self.ps], ids[pos:]),
                        limit - pos)
                if t > best_t:
                    best_t = t
            pos += best_t
            break
        return pos

    # -- insertion -------------------------------------------------------

    def insert(self, token_ids, page_row) -> int:
        """Publish a prefilled sequence's FULL pages into the tree.
        ``page_row`` is the sequence's page-table row (page id per
        slot).  Shares existing prefix nodes, splits a node when the
        new run diverges mid-run (always at a page boundary), and takes
        one tree reference on every newly indexed page.  Returns the
        number of pages added."""
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        n_full = len(ids) // self.ps
        if n_full == 0:
            return 0
        self._clock += 1
        self.root.last_access = self._clock
        node = self.root
        i = 0
        added = 0
        while i < n_full:
            key = tuple(int(t) for t in ids[i * self.ps:
                                            (i + 1) * self.ps])
            child = node.children.get(key)
            if child is None:
                pages = [int(page_row[j]) for j in range(i, n_full)]
                if any(p < 0 for p in pages):
                    raise AssertionError(
                        f"insert: unset page slot in {pages}")
                new = _Node(ids[i * self.ps:n_full * self.ps].copy(),
                            pages, node, self._clock)
                node.children[key] = new
                for pid in pages:
                    self.cache.page_refs[pid] += 1
                added = len(pages)
                break
            child.last_access = self._clock
            j = 1   # page 0 matched via the edge key
            while (j < len(child.pages) and i + j < n_full
                   and np.array_equal(
                       child.tokens[j * self.ps:(j + 1) * self.ps],
                       ids[(i + j) * self.ps:(i + j + 1) * self.ps])):
                j += 1
            i += j
            if j < len(child.pages):
                if i >= n_full:
                    break          # input exhausted mid-run: all shared
                self._split(child, j)
            node = child
        self.inserted_pages += added
        return added

    def _split(self, node, j):
        """Split ``node`` at page boundary ``j``: the node keeps its
        first ``j`` pages, a new child takes the rest (and the old
        children).  Pure restructuring — no refcount changes."""
        suffix = _Node(node.tokens[j * self.ps:], node.pages[j:],
                       node, node.last_access)
        suffix.children = node.children
        for c in suffix.children.values():
            c.parent = suffix
        node.children = {self._key(suffix.tokens): suffix}
        node.tokens = node.tokens[:j * self.ps]
        node.pages = node.pages[:j]

    # -- eviction --------------------------------------------------------

    def _unpinned(self, node) -> bool:
        refs = self.cache.page_refs
        return all(refs[p] == 1 for p in node.pages)

    def _lru_unpinned_leaf(self):
        best = None
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.children or not self._unpinned(n):
                continue
            if best is None or n.last_access < best.last_access:
                best = n
        return best

    def evict(self, need: int) -> int:
        """LRU eviction: repeatedly drop the least-recently-used leaf
        whose pages only the tree holds, until ``need`` pages are freed
        or no candidate remains.  Never touches a page a live sequence
        references (those have refcount > 1).  Returns pages freed."""
        freed = 0
        while freed < need:
            victim = self._lru_unpinned_leaf()
            if victim is None:
                break
            faults.fire("prefix.evict", "before")
            del victim.parent.children[self._key(victim.tokens)]
            for pid in victim.pages:
                self.cache._deref(pid)
            n = len(victim.pages)
            freed += n
            self.evicted_pages += n
            self.evictions += 1
            if self.on_evict is not None:
                self.on_evict(n)
            faults.fire("prefix.evict", "after")
        return freed

    def evictable_pages(self) -> int:
        """Pages eviction COULD free right now: the total over maximal
        fully-unpinned subtrees (a node is only reclaimable once all
        its descendants are).  Admission adds this to the free count —
        cached-but-cold pages are capacity, not commitment."""

        def walk(node):
            total = 0
            sub_full = True
            for c in node.children.values():
                f, t = walk(c)
                total += t
                sub_full = sub_full and f
            if node is self.root:
                return sub_full, total
            if sub_full and self._unpinned(node):
                return True, total + len(node.pages)
            return False, total

        return walk(self.root)[1]

    # -- introspection ---------------------------------------------------

    def pages(self) -> list:
        """Every page id the tree currently indexes (DFS order)."""
        out = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            out.extend(n.pages)
            stack.extend(n.children.values())
        return out

    def stats(self) -> dict:
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_tokens": self.hit_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
            "indexed_pages": len(self.pages()),
        }


def check_pool_invariants(cache, prefix=None):
    """Refcount/COW invariant audit (tests call this after every
    scheduler step):

      * no page is both free and referenced; refcounts never negative
      * pages-with-refs + free pages == pool size (nothing leaked)
      * every page's refcount equals the number of active slot
        page-table rows referencing it, plus one if the prefix tree
        indexes it
      * the tree never indexes a page twice
    """
    refs = cache.page_refs
    free = cache._free
    if len(set(free)) != len(free):
        raise AssertionError(f"duplicate pages in free list: {free}")
    for pid in free:
        if refs[pid] != 0:
            raise AssertionError(
                f"page {pid} is on the free list with refcount "
                f"{refs[pid]} (free AND referenced)")
    if (refs < 0).any():
        bad = np.nonzero(refs < 0)[0]
        raise AssertionError(f"negative refcounts at pages {bad}")
    in_use = int((refs > 0).sum())
    if in_use + len(free) != cache.num_pages:
        raise AssertionError(
            f"page leak: {in_use} referenced + {len(free)} free != "
            f"pool {cache.num_pages}")
    expected = np.zeros((cache.num_pages,), np.int64)
    for s in range(cache.max_seqs):
        if cache._active[s]:
            for pid in cache.page_table[s]:
                if pid >= 0:
                    expected[pid] += 1
    if prefix is not None:
        tree_pages = prefix.pages()
        if len(set(tree_pages)) != len(tree_pages):
            raise AssertionError(
                f"tree indexes a page twice: {sorted(tree_pages)}")
        for pid in tree_pages:
            expected[pid] += 1
    if not (expected == refs).all():
        bad = np.nonzero(expected != refs)[0]
        raise AssertionError(
            f"refcount mismatch at pages {bad.tolist()}: "
            f"expected {expected[bad].tolist()}, "
            f"recorded {refs[bad].tolist()}")
