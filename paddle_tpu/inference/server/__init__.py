"""Continuous-batching serving subsystem over the paged KV cache.

Layering (host control plane / device data plane):

  ServingCluster (cluster.py) N-replica fleet: prefix-affinity
                              Router, elastic drain/join, optional
                              prefill/decode disaggregation, and the
                              ReplicaSupervisor survivability plane
                              (crash/hang detection, request
                              failover, auto-restart + breaker,
                              overload shedding)
  ServingEngine (engine.py)  user API: submit / cancel / step / stats
    Scheduler   (scheduler.py) iteration-level admission, chunked
                               prefill, preemption-with-recompute
    EngineMetrics (metrics.py) TTFT/TPOT/queue-wait/occupancy SLOs
    PagedExecutor (executor.py) jit'd prefill/chunk/decode forwards
                                over paged.PagedKVCache slots
  WriteAheadLog (wal.py)     durable request journal: crc32-framed
                             lifecycle records feeding
                             ServingCluster.recover (whole-process
                             crash recovery, bit-identical streams)
"""
from .cluster import (Replica, ReplicaSupervisor, Router,
                      ServingCluster)
from .engine import ServingEngine
from .executor import PagedExecutor
from .metrics import EngineMetrics
from .prefix_cache import PrefixCache, check_pool_invariants
from .request import (Request, RequestHandle, RequestRejected,
                      RequestState, TERMINAL)
from .scheduler import Scheduler
from .spec_decode import NGramProposer, SpecDecode, spec_mode
from .wal import WriteAheadLog, replay, stream_crc, wal_enabled

__all__ = [
    "ServingEngine", "PagedExecutor", "EngineMetrics", "Request",
    "RequestHandle", "RequestState", "TERMINAL", "Scheduler",
    "PrefixCache", "check_pool_invariants",
    "NGramProposer", "SpecDecode", "spec_mode",
    "ServingCluster", "Router", "Replica", "ReplicaSupervisor",
    "RequestRejected",
    "WriteAheadLog", "replay", "stream_crc", "wal_enabled",
]
