"""Request lifecycle for the continuous-batching server.

State machine::

    QUEUED -> PREFILLING -> RUNNING -> {FINISHED, TRUNCATED}
       ^          |            |
       +----------+------------+   (preemption: pages freed, request
       |                            re-queued for recompute)
    terminal anywhere: CANCELLED (user), EVICTED (policy drop),
                       FAILED (exception confined to this request),
                       REJECTED (shed at the cluster boundary before
                       admission — ``retry_after`` says when to retry)

``finish_reason`` narrows the terminal state: "eos" (FINISHED),
"length"/"deadline" (TRUNCATED), "cancelled", "too_large"/
"preempt_budget" (EVICTED), the exception repr (FAILED), or
"overload"/"deadline_unmeetable" (REJECTED).
"""
from __future__ import annotations

import enum
import time

import numpy as np


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"
    RUNNING = "running"
    FINISHED = "finished"      # hit the eos token
    TRUNCATED = "truncated"    # hit max_new_tokens or its deadline
    CANCELLED = "cancelled"    # user cancellation
    EVICTED = "evicted"        # dropped by admission/preemption policy
    FAILED = "failed"          # an exception confined to this request
    REJECTED = "rejected"      # shed by cluster admission control


#: states from which a request never leaves.
TERMINAL = frozenset({
    RequestState.FINISHED, RequestState.TRUNCATED,
    RequestState.CANCELLED, RequestState.EVICTED, RequestState.FAILED,
    RequestState.REJECTED,
})


class RequestRejected(RuntimeError):
    """Raised by ``result()``/``stream()`` of a shed request: the
    cluster's admission control rejected it BEFORE any scheduler saw
    it.  ``retry_after`` is the suggested back-off in logical steps."""

    def __init__(self, rid, reason, retry_after):
        super().__init__(
            f"request {rid} rejected ({reason}); "
            f"retry after {retry_after} steps")
        self.rid = rid
        self.reason = reason
        self.retry_after = int(retry_after)


class Request:
    """One inference request inside the scheduler.  Host-side control
    state only — the KV lives in the executor's page pool under
    ``sid`` while the request holds a slot."""

    __slots__ = (
        "rid", "prompt_ids", "max_new_tokens", "priority", "deadline",
        "on_token", "arrival_seq", "state", "finish_reason", "error",
        "sid", "prefill_done", "resume_ids", "generated", "cancel_flag",
        "preempt_count", "submit_step", "submit_time", "sched_step",
        "first_token_step", "first_token_time", "finish_step",
        "finish_time", "last_token_time", "decode_time_s",
        "cached_tokens", "draft_proposed", "draft_accepted", "clock",
        "retry_after", "recovered",
    )

    def __init__(self, rid, prompt_ids, max_new_tokens=16, priority=0,
                 deadline=None, on_token=None, arrival_seq=0,
                 clock=None):
        # same injectable clock as EngineMetrics: first/last token
        # timestamps must come off the identical timeline the SLO
        # percentiles are computed on
        self.clock = time.perf_counter if clock is None else clock
        self.rid = rid
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.deadline = None if deadline is None else int(deadline)
        self.on_token = on_token
        self.arrival_seq = int(arrival_seq)

        self.state = RequestState.QUEUED
        self.finish_reason = None
        self.error = None
        self.sid = None            # executor slot while admitted
        self.prefill_done = 0      # tokens of resume_ids already prefilled
        self.resume_ids = self.prompt_ids  # prompt (+ generated on resume)
        self.generated = []        # streamed output tokens
        self.cancel_flag = False
        self.preempt_count = 0
        self.retry_after = None    # set when shed (state REJECTED)
        self.recovered = False     # rebuilt from the WAL after a crash
        self.cached_tokens = 0     # prompt tokens attached from cache
        self.draft_proposed = 0    # speculative draft tokens offered
        self.draft_accepted = 0    # ...committed by verification

        self.submit_step = None
        self.submit_time = None
        self.sched_step = None       # first admitted (queue-wait end)
        self.first_token_step = None
        self.first_token_time = None
        self.finish_step = None
        self.finish_time = None
        self.last_token_time = None
        self.decode_time_s = 0.0

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL

    def emit(self, tok: int) -> None:
        """Record one generated token and stream it to the callback."""
        self.generated.append(int(tok))
        now = self.clock()
        if self.first_token_time is None:
            self.first_token_time = now
        self.last_token_time = now
        if self.on_token is not None:
            self.on_token(self.rid, int(tok))

    def __repr__(self):
        return (f"Request(rid={self.rid}, state={self.state.value}, "
                f"prompt={len(self.prompt_ids)}, "
                f"generated={len(self.generated)})")


class RequestHandle:
    """What ``ServingEngine.submit`` returns: a live view of one
    request plus pull-style streaming.

    The engine is single-threaded — ``stream()`` DRIVES it (each pull
    advances ``engine.step()`` until a new token lands), the analog of
    an async generator without an event loop."""

    def __init__(self, engine, request: Request):
        self._engine = engine
        self._req = request

    @property
    def rid(self):
        return self._req.rid

    @property
    def state(self) -> RequestState:
        return self._req.state

    @property
    def finish_reason(self):
        return self._req.finish_reason

    @property
    def tokens(self):
        return list(self._req.generated)

    @property
    def num_preemptions(self):
        return self._req.preempt_count

    def cancel(self):
        self._engine.cancel(self._req.rid)

    def result(self):
        """Block (by stepping the engine) until terminal; return the
        generated tokens.  Raises the confined exception on FAILED."""
        while not self._req.terminal:
            self._engine.step()
        if self._req.state in (RequestState.FAILED,
                               RequestState.REJECTED):
            raise self._req.error
        return list(self._req.generated)

    def stream(self):
        """Yield tokens as they are produced, stepping the engine while
        this request is alive."""
        sent = 0
        while True:
            while sent < len(self._req.generated):
                yield self._req.generated[sent]
                sent += 1
            if self._req.terminal:
                if self._req.state in (RequestState.FAILED,
                                       RequestState.REJECTED):
                    raise self._req.error
                return
            self._engine.step()

    def metrics(self) -> dict:
        r = self._req
        return {
            "state": r.state.value,
            "finish_reason": r.finish_reason,
            "queue_wait_steps": (None if r.sched_step is None
                                 else r.sched_step - r.submit_step),
            "ttft_steps": (None if r.first_token_step is None
                           else r.first_token_step - r.submit_step),
            "ttft_s": (None if r.first_token_time is None
                       else r.first_token_time - r.submit_time),
            "tpot_s": (None if len(r.generated) < 2
                       or r.last_token_time is None
                       or r.first_token_time is None
                       else (r.last_token_time - r.first_token_time)
                       / (len(r.generated) - 1)),
            "tokens": len(r.generated),
            "preemptions": r.preempt_count,
            "retry_after": r.retry_after,
            "recovered": r.recovered,
            "cached_tokens": r.cached_tokens,
            "draft_proposed": r.draft_proposed,
            "draft_accepted": r.draft_accepted,
        }

    def __repr__(self):
        return f"RequestHandle({self._req!r})"
