"""SLO observability for the serving engine.

Two clocks run side by side: the LOGICAL clock (scheduler iterations —
what deterministic tests assert on) and the wall clock (what the bench
reports as ms percentiles).  Per-request TTFT/TPOT/queue-wait are
recorded in both; engine-level occupancy and page utilization are
step-averaged over the window where any request was in flight, so idle
tails don't dilute them.
"""
from __future__ import annotations

import time

import numpy as np

from ... import obs
from .request import RequestState


def _pct(values, q):
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


#: logical-step buckets for the step-denominated histograms (a tick is
#: an iteration, not a duration — latency buckets would be nonsense).
_STEP_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256)


class EngineMetrics:
    """Accumulates per-request and engine-level serving statistics.

    ``clock`` is injectable (default ``time.perf_counter``) so seeded
    load tests can assert the ms percentiles exactly — pass
    ``obs.LogicalClock()`` and every TTFT/TPOT read is deterministic.
    When telemetry is on and no clock is given, the obs bundle's clock
    is used so the SLO numbers and the trace timestamps share one
    timeline.  When telemetry is on, every hook also publishes into
    the process-wide metric registry (``serve_*`` families); the
    ``stats()`` dict API is unchanged.
    """

    def __init__(self, max_seqs: int, num_pages: int, clock=None):
        self.max_seqs = max_seqs
        self.num_pages = num_pages
        self._obs = obs.handle()
        if clock is None:
            clock = (self._obs.clock if self._obs is not None
                     else time.perf_counter)
        self.clock = clock
        self._declare_metrics()
        self.steps = 0
        self.busy_steps = 0           # steps with >= 1 in-flight request
        self.decode_tokens = 0
        self.decode_slot_steps = 0    # sum of decode batch sizes
        self.prefill_tokens = 0
        self.preemptions = 0
        self.draft_proposed = 0       # speculative draft tokens offered
        self.draft_accepted = 0       # ...committed by verification
        self.spec_steps = 0           # verify dispatches
        self.submitted = 0
        self.prefix_hits = 0          # admissions that attached pages
        self.cached_tokens = 0        # prompt tokens served from cache
        self.evicted_pages = 0        # prefix-tree pages LRU-evicted
        self.occupancy_sum = 0.0      # decode-batch fill over busy steps
        self.page_util_sum = 0.0      # pool occupancy over busy steps
        self.state_counts = {s.value: 0 for s in RequestState
                             if s.value not in ("queued", "prefilling",
                                                "running")}
        self._completed = []          # per-request metric dicts
        self._t_start = self.clock()
        self._t_last = self._t_start

    def _declare_metrics(self):
        """Declare the serve_* registry families once (idempotent —
        several engines in one process share the counters)."""
        h = self._obs
        if h is None:
            return
        r = h.registry
        self._m = {
            "submitted": r.counter(
                "serve_requests_submitted_total",
                "Requests accepted by ServingEngine.submit"),
            "terminal": r.counter(
                "serve_requests_total",
                "Requests reaching a terminal state", labels=("state",)),
            "steps": r.counter(
                "serve_steps_total", "Scheduler iterations"),
            "decode_tokens": r.counter(
                "serve_decode_tokens_total", "Tokens emitted by decode"),
            "prefill_tokens": r.counter(
                "serve_prefill_tokens_total", "Prompt tokens prefilled"),
            "cached_tokens": r.counter(
                "serve_cached_tokens_total",
                "Prompt tokens attached from the prefix cache"),
            "prefix_hits": r.counter(
                "serve_prefix_hits_total",
                "Admissions that attached cached prefix pages"),
            "evicted_pages": r.counter(
                "serve_evicted_pages_total",
                "Prefix-tree pages LRU-evicted"),
            "preemptions": r.counter(
                "serve_preemptions_total",
                "Requests preempted for recompute"),
            "spec_steps": r.counter(
                "serve_spec_steps_total", "Speculative verify steps"),
            "draft_proposed": r.counter(
                "serve_draft_proposed_total",
                "Speculative draft tokens offered"),
            "draft_accepted": r.counter(
                "serve_draft_accepted_total",
                "Speculative draft tokens committed"),
            "occupancy": r.gauge(
                "serve_batch_occupancy",
                "Decode batch fill fraction (last busy step)"),
            "page_util": r.gauge(
                "serve_page_utilization",
                "KV page pool occupancy (last busy step)"),
            "ttft_s": r.histogram(
                "serve_ttft_seconds", "Time to first token"),
            "tpot_s": r.histogram(
                "serve_tpot_seconds", "Time per output token"),
            "queue_wait": r.histogram(
                "serve_queue_wait_steps",
                "Scheduler iterations queued before admission",
                buckets=_STEP_BUCKETS),
            "ttft_steps": r.histogram(
                "serve_ttft_steps",
                "Scheduler iterations from submit to first token",
                buckets=_STEP_BUCKETS),
        }

    # -- event hooks (called by the scheduler) --------------------------

    def on_submit(self, req, step):
        self.submitted += 1
        req.submit_step = step
        req.submit_time = self.clock()
        if self._obs is not None:
            self._m["submitted"].inc()

    def on_sched(self, req, step):
        if req.sched_step is None:
            req.sched_step = step

    def on_first_token(self, req, step):
        if req.first_token_step is None:
            req.first_token_step = step

    def on_decode_tokens(self, n):
        # legacy one-token-per-slot decode: slots == tokens
        self.on_decode_step(slots=n, tokens=n)

    def on_decode_step(self, slots, tokens):
        self.decode_tokens += tokens
        self.decode_slot_steps += slots
        if self._obs is not None:
            self._m["decode_tokens"].inc(tokens)

    def on_spec(self, proposed, accepted):
        self.spec_steps += 1
        self.draft_proposed += int(proposed)
        self.draft_accepted += int(accepted)
        if self._obs is not None:
            self._m["spec_steps"].inc()
            self._m["draft_proposed"].inc(int(proposed))
            self._m["draft_accepted"].inc(int(accepted))

    def on_prefill_tokens(self, n):
        self.prefill_tokens += n
        if self._obs is not None:
            self._m["prefill_tokens"].inc(n)

    def on_preempt(self, req):
        self.preemptions += 1
        if self._obs is not None:
            self._m["preemptions"].inc()

    def on_prefix_hit(self, tokens):
        self.prefix_hits += 1
        self.cached_tokens += int(tokens)
        if self._obs is not None:
            self._m["prefix_hits"].inc()
            self._m["cached_tokens"].inc(int(tokens))

    def on_prefix_evict(self, n_pages):
        self.evicted_pages += int(n_pages)
        if self._obs is not None:
            self._m["evicted_pages"].inc(int(n_pages))
            self._obs.events.log("kv.evict", pages=int(n_pages))

    def on_terminal(self, req, step):
        req.finish_step = step
        req.finish_time = self.clock()
        self.state_counts[req.state.value] += 1
        if self._obs is not None:
            self._m["terminal"].labels(state=req.state.value).inc()
        self._completed.append({
            "queue_wait_steps": (None if req.sched_step is None
                                 or req.submit_step is None
                                 else req.sched_step - req.submit_step),
            "ttft_steps": (None if req.first_token_step is None
                           else req.first_token_step - req.submit_step),
            "ttft_s": (None if req.first_token_time is None
                       else req.first_token_time - req.submit_time),
            "tpot_s": (None if len(req.generated) < 2
                       or req.last_token_time is None
                       else (req.last_token_time - req.first_token_time)
                       / (len(req.generated) - 1)),
            # logical-clock TPOT: scheduler iterations per generated
            # token.  1.0 for plain decode; < 1.0 once speculative
            # steps commit multiple tokens per iteration.
            "tpot_steps": (None if len(req.generated) < 2
                           or req.first_token_step is None
                           else (req.finish_step - req.first_token_step)
                           / (len(req.generated) - 1)),
            "tokens": len(req.generated),
        })
        if self._obs is not None:
            d = self._completed[-1]
            for key, hist in (("ttft_s", "ttft_s"),
                              ("tpot_s", "tpot_s"),
                              ("queue_wait_steps", "queue_wait"),
                              ("ttft_steps", "ttft_steps")):
                if d[key] is not None:
                    self._m[hist].observe(d[key])

    def on_step(self, decode_batch: int, pages_used: int,
                in_flight: int):
        self.steps += 1
        self._t_last = self.clock()
        if self._obs is not None:
            self._m["steps"].inc()
        if in_flight:
            self.busy_steps += 1
            occ = decode_batch / max(self.max_seqs, 1)
            util = pages_used / max(self.num_pages, 1)
            self.occupancy_sum += occ
            self.page_util_sum += util
            if self._obs is not None:
                self._m["occupancy"].set(occ)
                self._m["page_util"].set(util)

    # -- report ---------------------------------------------------------

    def stats(self) -> dict:
        wall = max(self._t_last - self._t_start, 1e-9)
        done = self._completed
        busy = max(self.busy_steps, 1)
        return {
            "steps": self.steps,
            "wall_s": round(wall, 4),
            "requests": dict(self.state_counts,
                             submitted=self.submitted),
            "preemptions": self.preemptions,
            "decode_tokens": self.decode_tokens,
            "prefill_tokens": self.prefill_tokens,
            # prefix-cache effectiveness: what fraction of prompt
            # tokens were served from shared pages instead of prefilled
            "cached_tokens": self.cached_tokens,
            "prefix_hit_rate": round(
                self.cached_tokens
                / max(self.cached_tokens + self.prefill_tokens, 1), 4),
            "evicted_pages": self.evicted_pages,
            "throughput_tok_s": round(self.decode_tokens / wall, 2),
            # speculative decode effectiveness: fraction of drafted
            # tokens committed, and how far each sequence advances per
            # decode slot-step (1.0 = plain greedy; > 1.0 = spec wins)
            "draft_acceptance_rate": round(
                self.draft_accepted / max(self.draft_proposed, 1), 4),
            "tokens_per_decode_step": round(
                self.decode_tokens / max(self.decode_slot_steps, 1), 4),
            "batch_occupancy": round(self.occupancy_sum / busy, 4),
            "page_utilization": round(self.page_util_sum / busy, 4),
            "queue_wait_steps_p50": _pct(
                [d["queue_wait_steps"] for d in done], 50),
            "queue_wait_steps_p99": _pct(
                [d["queue_wait_steps"] for d in done], 99),
            "ttft_steps_p50": _pct([d["ttft_steps"] for d in done], 50),
            "ttft_ms_p50": _ms(_pct([d["ttft_s"] for d in done], 50)),
            "ttft_ms_p99": _ms(_pct([d["ttft_s"] for d in done], 99)),
            "tpot_ms_p50": _ms(_pct([d["tpot_s"] for d in done], 50)),
            "tpot_ms_p99": _ms(_pct([d["tpot_s"] for d in done], 99)),
            "tpot_steps_p50": _pct([d["tpot_steps"] for d in done], 50),
            "tpot_steps_p99": _pct([d["tpot_steps"] for d in done], 99),
        }


def _ms(seconds):
    return None if seconds is None else round(seconds * 1e3, 3)
