"""Write-ahead request journal for durable serving.

An append-only log of request lifecycle records (submit / admit /
token-emission / finish / reject / dedup) that makes an accepted
request survive the loss of the whole serving process: after a crash,
``ServingCluster.recover(wal_dir)`` replays the journal, serves
already-finished streams straight from the log, and re-submits
in-flight requests through the preemption-recompute idiom so recovered
streams are bit-identical to an uninterrupted run.

Layout and framing (references: classic ARIES-style WAL, LevelDB log
format):

- the journal is a directory of numbered **segments**
  (``wal-00000001.jsonl`` ...); a writer always starts a fresh segment
  so a torn tail from a previous incarnation is never appended to;
- each record is one line: ``<crc32 hex8> <compact json>\\n`` — the
  crc32 is over the json bytes, so replay detects both torn tails
  (half-written final lines: physically truncated on replay) and
  interior bit-rot (crc mismatch: the record is skipped and counted;
  a finish record whose token count/crc no longer matches the replayed
  stream downgrades that request to the recompute path, never to a
  wrong answer; token records carry their stream index ``i`` so replay
  trusts only a contiguous-from-zero prefix — a token past a bit-rot
  gap is recomputed, not replayed);
- each append is one raw ``write(2)`` straight to the OS (a SIGKILL
  loses nothing) and ``fsync()`` runs every ``fsync_every`` records —
  the batching keeps the WAL-on throughput tax within the gated ≥0.95×
  budget.  Records past the last fsync can be lost to power failure;
  replay then simply sees a shorter prefix and recomputes the rest
  bit-identically.

Journaling must never take serving down: append/fsync failures
(injected via the ``wal.append``/``wal.fsync`` fault points or real
``OSError``) are absorbed into ``errors`` and serving continues with a
degraded journal.  The gate is ``PT_WAL={off,on}`` (+ ``PT_WAL_DIR``);
off is bit-exact with the WAL-free engine.
"""
from __future__ import annotations

import glob
import json
import os
import time
import zlib

import numpy as np

from ... import obs
from ...testing import faults

__all__ = [
    "WriteAheadLog", "replay", "stream_crc", "wal_enabled",
    "default_wal", "resolve_wal", "segment_paths", "compact",
]

_SEG_FMT = "wal-{:08d}.jsonl"
_SEG_GLOB = "wal-*.jsonl"


def wal_enabled() -> bool:
    mode = os.environ.get("PT_WAL", "off").lower()
    if mode not in ("off", "on"):
        raise ValueError(f"PT_WAL={mode!r}: expected off|on")
    return mode == "on"


def default_wal():
    """WriteAheadLog from PT_WAL / PT_WAL_DIR, or None when off."""
    if not wal_enabled():
        return None
    path = os.environ.get("PT_WAL_DIR")
    if not path:
        raise ValueError("PT_WAL=on requires PT_WAL_DIR=<journal dir>")
    return WriteAheadLog(path)


def resolve_wal(wal):
    """None = follow PT_WAL; False forces off; a path string or a
    WriteAheadLog force on (bench A/B and cluster-owned journals)."""
    if wal is None:
        return default_wal()
    if wal is False:
        return None
    if isinstance(wal, WriteAheadLog):
        return wal
    if isinstance(wal, (str, os.PathLike)):
        return WriteAheadLog(os.fspath(wal))
    raise ValueError(f"wal={wal!r}: expected None|False|path|WriteAheadLog")


def stream_crc(tokens) -> int:
    """crc32 over a token stream; stamped into finish records so replay
    can prove a journaled stream is complete before serving it."""
    return zlib.crc32(np.asarray(list(tokens), np.int32).tobytes())


def segment_paths(path):
    return sorted(glob.glob(os.path.join(path, _SEG_GLOB)))


class WriteAheadLog:
    """Append-only crc32-framed JSON-lines journal with segment
    rotation and batched fsync.  Single writer per directory."""

    def __init__(self, path, fsync_every=None, segment_bytes=256 * 1024,
                 compact_every=None):
        if fsync_every is None:
            fsync_every = int(os.environ.get("PT_WAL_FSYNC_EVERY", "32"))
        if fsync_every < 1:
            raise ValueError("fsync_every must be >= 1")
        if compact_every is None:
            compact_every = int(os.environ.get("PT_WAL_COMPACT_EVERY", "0"))
        if compact_every < 0:
            raise ValueError("compact_every must be >= 0 (0 = never)")
        self.dir = os.fspath(path)
        os.makedirs(self.dir, exist_ok=True)
        self.fsync_every = fsync_every
        self.segment_bytes = segment_bytes
        self.compact_every = compact_every
        self.appended = 0
        self.fsyncs = 0
        self.compactions = 0
        self.errors = 0
        # wall seconds spent inside append/fsync: the journal's true
        # serving-path cost, measured within-run so host drift between
        # bench legs can't fake (or hide) a tax
        self.write_s = 0.0
        self.last_fsync_at = 0      # `appended` watermark at last fsync
        self._since_fsync = 0
        self._since_compact = 0
        self._f = None
        self._seg_path = None
        self._seg_bytes = 0
        self._pub_appended = 0
        self._pub_fsyncs = 0
        self._pub_compactions = 0
        existing = segment_paths(self.dir)
        # never append to an old segment: its tail may be torn, and
        # replay truncates tears — a fresh segment keeps new records
        # safely after any repair point
        self._seg_index = (int(os.path.basename(existing[-1])[4:12])
                           if existing else 0)
        self._obs = obs.handle()

    # -- writing ---------------------------------------------------------

    def _roll(self):
        if self._f is not None:
            # the final fsync of the outgoing segment degrades like any
            # other journal failure, and the fd closes regardless —
            # rotation must complete even on a sick disk, or persistent
            # fsync errors would leak the fd and pin the segment
            try:
                self._do_fsync()
            except (faults.InjectedFault, OSError):
                self.errors += 1
            finally:
                fd, self._f = self._f, None
                os.close(fd)
        self._seg_index += 1
        self._seg_path = os.path.join(
            self.dir, _SEG_FMT.format(self._seg_index))
        # raw fd: each record is exactly one write(2) straight to the
        # OS (SIGKILL-durable) with no buffered-writer bookkeeping on
        # the serving hot path
        self._f = os.open(self._seg_path,
                          os.O_CREAT | os.O_APPEND | os.O_WRONLY, 0o644)
        self._seg_bytes = 0

    def append(self, rec: dict) -> None:
        """Journal one record.  Failures (injected or OSError) degrade
        to ``errors`` — the serving path never pays for a sick disk."""
        t0 = time.perf_counter()
        try:
            faults.fire("wal.append", "before", path=self._seg_path)
            if self._f is None or self._seg_bytes >= self.segment_bytes:
                self._roll()
            body = json.dumps(rec, separators=(",", ":")).encode()
            line = b"%08x " % zlib.crc32(body) + body + b"\n"
            os.write(self._f, line)
            self._seg_bytes += len(line)
            self.appended += 1
            self._since_fsync += 1
            self._since_compact += 1
            faults.fire("wal.append", "after", path=self._seg_path)
        except (faults.InjectedFault, OSError):
            self.errors += 1
            self.write_s += time.perf_counter() - t0
        else:
            # stop the clock before the batched fsync: fsync() keeps
            # its own time, so the barrier is never counted twice
            self.write_s += time.perf_counter() - t0
            if self._since_fsync >= self.fsync_every:
                self.fsync()
            if self.compact_every and self._since_compact >= self.compact_every:
                self.compact()
        self._publish()

    def _do_fsync(self):
        faults.fire("wal.fsync", "before", path=self._seg_path)
        os.fsync(self._f)
        self.fsyncs += 1
        self.last_fsync_at = self.appended
        self._since_fsync = 0
        faults.fire("wal.fsync", "after", path=self._seg_path)

    def fsync(self) -> None:
        if self._f is None:
            return
        t0 = time.perf_counter()
        try:
            self._do_fsync()
        except (faults.InjectedFault, OSError):
            self.errors += 1
        self.write_s += time.perf_counter() - t0
        self._publish()

    def close(self) -> None:
        if self._f is not None:
            self.fsync()
            os.close(self._f)
            self._f = None

    def compact(self):
        """Rewrite the journal's live state into one fresh segment and
        drop the finished history (module :func:`compact`), coordinating
        with this open writer: the current segment is fsynced and closed
        first (so the rewrite sees every appended record and may unlink
        the segment), and the next ``append`` rolls a brand-new segment
        strictly after the compacted one.  Runs inline on the append
        path when ``compact_every``/``PT_WAL_COMPACT_EVERY`` is set, so
        like every other journal operation a failure degrades to
        ``errors`` and serving continues on the uncompacted directory.
        Returns the compaction report, or None on a degraded failure."""
        t0 = time.perf_counter()
        report = None
        try:
            if self._f is not None:
                try:
                    self._do_fsync()
                except (faults.InjectedFault, OSError):
                    self.errors += 1
                finally:
                    fd, self._f = self._f, None
                    os.close(fd)
            report = compact(self.dir)
            self.compactions += 1
        except (faults.InjectedFault, OSError):
            self.errors += 1
        finally:
            # re-anchor the segment counter on what is actually on disk:
            # whether the rewrite landed or died half-way, the next roll
            # must pick an index after every existing segment (reusing a
            # live name would interleave new appends into old history)
            existing = segment_paths(self.dir)
            if existing:
                self._seg_index = int(os.path.basename(existing[-1])[4:12])
            self._since_compact = 0
        self.write_s += time.perf_counter() - t0
        self._publish()
        return report

    # -- telemetry -------------------------------------------------------

    def _publish(self):
        h = self._obs
        if h is None:
            return
        h.registry.counter(
            "wal_appended_total", "WAL records appended",
        ).inc(self.appended - self._pub_appended)
        self._pub_appended = self.appended
        h.registry.counter(
            "wal_fsyncs_total", "WAL fsync barriers",
        ).inc(self.fsyncs - self._pub_fsyncs)
        self._pub_fsyncs = self.fsyncs
        h.registry.counter(
            "wal_compactions_total", "WAL journal compactions",
        ).inc(self.compactions - self._pub_compactions)
        self._pub_compactions = self.compactions
        h.registry.gauge(
            "wal_lag_records",
            "records appended since the last fsync barrier",
        ).set(self._since_fsync)

    def statusz(self) -> dict:
        segs = segment_paths(self.dir)
        return {
            "dir": self.dir,
            "segments": len(segs),
            "bytes": sum(os.path.getsize(p) for p in segs),
            "appended": self.appended,
            "fsyncs": self.fsyncs,
            "compactions": self.compactions,
            "errors": self.errors,
            "lag_records": self._since_fsync,
            "last_fsync_at_record": self.last_fsync_at,
            "write_s": round(self.write_s, 6),
        }


def _decode_line(line: bytes):
    """(record, crc_ok) — (None, False) when the frame/json is
    unparseable (candidate torn tail)."""
    if len(line) < 10 or line[8:9] != b" ":
        return None, False
    body = line[9:]
    try:
        want = int(line[:8], 16)
        rec = json.loads(body)
    except ValueError:
        return None, False
    if not isinstance(rec, dict):
        return None, False
    return rec, zlib.crc32(body) == want


def replay(path, repair=True):
    """Replay a journal directory -> (records, report).

    Torn tails (a trailing run of unparseable lines in a segment — a
    crash mid-append) are physically truncated when ``repair`` so a
    later writer never lands records behind garbage.  Interior corrupt
    records (bit-rot: crc mismatch or garbage followed by valid lines)
    are skipped and counted — recovery downgrades any stream they
    touched to the recompute path.
    """
    faults.fire("wal.replay", "before", path=path)
    records = []
    report = {"segments": 0, "records": 0, "corrupt": 0, "torn_bytes": 0}
    for seg in segment_paths(path):
        report["segments"] += 1
        with open(seg, "rb") as f:
            raw = f.read()
        entries = []         # (start_offset, rec|None, crc_ok)
        pos = 0
        while pos < len(raw):
            nl = raw.find(b"\n", pos)
            end = len(raw) if nl == -1 else nl
            rec, ok = _decode_line(raw[pos:end])
            if nl == -1:     # unterminated final line is always torn
                entries.append((pos, None, False))
                break
            entries.append((pos, rec if ok else None, ok))
            pos = nl + 1
        # split the trailing run of invalid entries: that's the torn
        # tail; invalid entries before any later valid one are bit-rot
        tail = len(entries)
        while tail > 0 and entries[tail - 1][1] is None:
            tail -= 1
        for start, rec, _ok in entries[:tail]:
            if rec is None:
                report["corrupt"] += 1
            else:
                records.append(rec)
                report["records"] += 1
        if tail < len(entries):
            torn_at = entries[tail][0]
            report["torn_bytes"] += len(raw) - torn_at
            if repair:
                with open(seg, "ab") as f:
                    f.truncate(torn_at)
    faults.fire("wal.replay", "after", path=path)
    h = obs.handle()
    if h is not None:
        h.registry.counter(
            "wal_replayed_total", "WAL records replayed during recovery",
        ).inc(report["records"])
        h.events.log("wal.replay", dir=os.fspath(path), **report)
    return records, report


def _terminal_rids(records):
    """rids whose journaled lifecycle is finished business — safe to
    drop under at-least-once delivery.  Mirrors ``recover``'s fold:

    - **finished & proven**: a submit plus a finish whose token count
      and crc match the replayed contiguous-from-zero token prefix.
      Dropping it loses only the serve-from-log dedup fast path; a
      client resubmit recomputes the same stream bit-identically
      (deterministic greedy decode).
    - **rejected & not superseded**: the reject was delivered live when
      it happened (rejects are never deduped), and no later submit
      restarted the rid, so nothing remains to restore.
    - **unrestorable**: a rid with lifecycle records but no surviving
      submit (interior bit-rot ate it).  Recovery could only count it
      corrupt, never restore it; the client's resubmit arrives as a
      fresh stream either way.

    Everything else — unfinished streams, finishes that fail their own
    proof, resubmitted-after-reject rids — is live and must be kept.
    """
    by = {}
    for rec in records:
        rid = rec.get("rid")
        if rid is None:
            continue
        e = by.setdefault(rid, {"tokens": [], "submit": None,
                                "finish": None, "reject": None})
        t = rec.get("t")
        if t == "submit":
            if e["reject"] is not None:
                # post-backoff retry supersedes the shed attempt: the
                # rid is a fresh stream from here (same rule as recover)
                e.update(submit=rec, finish=None, reject=None, tokens=[])
            elif e["submit"] is None:
                e["submit"] = rec
        elif t == "token":
            if int(rec.get("i", -1)) == len(e["tokens"]):
                e["tokens"].append(int(rec.get("tok", -1)))
        elif t == "finish":
            e["finish"] = rec
        elif t == "reject":
            e["reject"] = rec
    out = set()
    for rid, e in by.items():
        if e["submit"] is None:
            out.add(rid)
        elif e["reject"] is not None:
            out.add(rid)
        elif (e["finish"] is not None
              and int(e["finish"].get("n", -1)) == len(e["tokens"])
              and int(e["finish"].get("crc", -1)) == stream_crc(e["tokens"])):
            out.add(rid)
    return out


def compact(path):
    """Rewrite a journal directory's **live** state into one fresh
    segment and unlink the finished history -> report dict.

    The journal is append-only, so a long-lived server accretes
    segments full of finished streams that recovery would only replay
    to dedup.  Compaction replays the directory (repairing torn
    tails), keeps every record of every live rid verbatim (so a
    post-compaction ``recover`` folds them identically), writes them
    crc-framed into a fresh segment numbered after all existing ones,
    fsyncs it, and only then unlinks the old segments.

    Crash safety leans entirely on ``recover``'s duplicate-idempotent
    replay — every window leaves a directory that recovers to the same
    state:

    - **before the new segment is durable**: old segments are intact;
      the partial new segment is at worst a torn tail (truncated on
      replay) holding duplicates of records still present in the old
      segments — submit is first-write-wins and token replay only
      extends a contiguous prefix, so duplicates are no-ops;
    - **mid-unlink**: the new segment is complete and holds all live
      state; surviving old segments add only duplicates and
      already-terminal lifecycles.

    Single writer per directory: callers with an open
    :class:`WriteAheadLog` must use its :meth:`~WriteAheadLog.compact`
    method, which closes the active segment first.
    """
    faults.fire("wal.compact", "before", path=path)
    old = segment_paths(path)
    report = {"segments_dropped": 0, "records_kept": 0,
              "records_dropped": 0, "live_rids": 0, "segment_index": 0}
    if not old:
        faults.fire("wal.compact", "after", path=path)
        return report
    records, _rep = replay(path)
    terminal = _terminal_rids(records)
    keep = [r for r in records
            if r.get("rid") is not None and r["rid"] not in terminal]
    live = {r["rid"] for r in keep}
    new_index = max(int(os.path.basename(p)[4:12]) for p in old) + 1
    new_path = os.path.join(os.fspath(path), _SEG_FMT.format(new_index))
    fd = os.open(new_path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
    try:
        for rec in keep:
            body = json.dumps(rec, separators=(",", ":")).encode()
            os.write(fd, b"%08x " % zlib.crc32(body) + body + b"\n")
        os.fsync(fd)
    finally:
        os.close(fd)
    # the "after" phase sits between the durable rewrite and the
    # unlinks: a crash injected here leaves old+new coexisting, the
    # exact window the docstring's idempotence argument covers
    faults.fire("wal.compact", "after", path=path)
    for p in old:
        os.unlink(p)
    report.update(segments_dropped=len(old), records_kept=len(keep),
                  records_dropped=len(records) - len(keep),
                  live_rids=len(live), segment_index=new_index)
    h = obs.handle()
    if h is not None:
        h.events.log("wal.compact", dir=os.fspath(path), **report)
    return report
