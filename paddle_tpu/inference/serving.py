"""Continuous-batching serving engine over the paged KV cache.

Reference: the Predictor's serving loop driven by
``block_multi_head_attention`` (block-table KV) and
``masked_multihead_attention`` (decode step) — the reference's
continuous-batching inference stack.

TPU-native: prefill computes the prompt's KV in one jitted forward and
writes whole pages; each decode step is one jitted single-token forward
whose attention runs ``paged_decode_attention`` (Pallas kernel on TPU)
over the page pool.  Admission/eviction is a host-side control plane on
the PagedKVCache block table; sequences of different lengths decode in
one batch (per-sequence lengths mask the attention).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.nn_ops import _rms_norm_plain, _rope_plain
from .paged import PagedKVCache, paged_decode_attention


class PagedLlamaEngine:
    """Greedy continuous-batching decoder for a LlamaForCausalLM.

    engine = PagedLlamaEngine(model, max_seqs=4, page_size=16,
                              max_len=256)
    sid = engine.add_request(prompt_ids)           # prefill
    out = engine.step()                            # {sid: next_token}
    engine.finish(sid)                             # free pages
    """

    def __init__(self, model, max_seqs=4, page_size=16, max_len=256,
                 dtype=jnp.float32):
        from ..models.generation import _stack_layer_params
        from ..models.llama import _rope_tables

        cfg = model.config
        self.config = cfg
        state = {k: v._data for k, v in model.state_dict().items()}
        self.layers = _stack_layer_params(state, cfg.num_hidden_layers)
        embed = jnp.asarray(state["llama.embed_tokens.weight"])
        cos, sin = _rope_tables(cfg)
        # non-layer weights travel as jit ARGUMENTS: closed-over arrays
        # are baked into the HLO as literals, and multi-MB constants
        # (embed/head at vocab 32k) choke the remote AOT compiler — the
        # r5 root cause of the serving prefill "hang"
        # tied embeddings: alias the SAME buffer and transpose in-graph
        # (embed.T here would materialize a duplicate vocab x hidden
        # array in HBM); _head() applies the orientation.
        self._tied = bool(cfg.tie_word_embeddings)
        self.tops = {
            "embed": embed,
            "norm_w": jnp.asarray(state["llama.norm.weight"]),
            "head_w": (embed if self._tied
                       else jnp.asarray(state["lm_head.weight"])),
            "cos": jnp.asarray(cos),
            "sin": jnp.asarray(sin),
        }

        pages_per_seq = -(-max_len // page_size)
        self.cache = PagedKVCache(
            n_layers=cfg.num_hidden_layers,
            n_kv_heads=cfg.num_key_value_heads, head_dim=cfg.head_dim,
            num_pages=max_seqs * pages_per_seq, page_size=page_size,
            max_seqs=max_seqs, dtype=dtype)
        self._last_token = {}
        self._jit_prefill = jax.jit(self._prefill_fwd)
        # donate the pools: step() immediately replaces them with the
        # outputs, so XLA updates in place instead of copying GBs of KV
        self._jit_decode = jax.jit(self._decode_fwd,
                                   donate_argnums=(4, 5))

    def _head(self, x, tops):
        w = tops["head_w"]
        return x @ (w.T if self._tied else w)

    # -- pure forwards --------------------------------------------------

    def _prefill_fwd(self, layers, tops, ids):
        """[1, S] prompt -> (last-token logits [V], k [L,KV,S,D],
        v [L,KV,S,D]) — plain causal attention, KV returned for the
        page writer."""
        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        B, S = ids.shape
        x = tops["embed"][ids]
        pos = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        scale = 1.0 / np.sqrt(d)

        def block(x, lp):
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = (h @ lp["self_attn.q_proj.weight"]).reshape(B, S, nh, d)
            k = (h @ lp["self_attn.k_proj.weight"]).reshape(B, S, nkv, d)
            v = (h @ lp["self_attn.v_proj.weight"]).reshape(B, S, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            g = nh // nkv
            qt = jnp.swapaxes(q, 1, 2)              # [B, nh, S, d]
            kt = jnp.swapaxes(k, 1, 2)              # [B, nkv, S, d]
            vt = jnp.swapaxes(v, 1, 2)
            if g > 1:                               # GQA: expand KV heads
                kt = jnp.repeat(kt, g, axis=1)
                vt = jnp.repeat(vt, g, axis=1)
            # standard 4-D attention: the 5-D grouped einsum + rank-5
            # masked-broadcast variant compiled pathologically slowly on
            # the TPU AOT path (95s+ for 2 layers; minutes at vocab 32k)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
            causal = jnp.tril(jnp.ones((S, S), bool))
            logits = jnp.where(causal[None, None], logits,
                               jnp.finfo(logits.dtype).min)
            p = jax.nn.softmax(logits.astype(jnp.float32), -1) \
                .astype(x.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
            o = jnp.swapaxes(o, 1, 2).reshape(B, S, nh * d)
            x = x + o @ lp["self_attn.o_proj.weight"]
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = h2 @ lp["mlp.gate_proj.weight"]
            up = h2 @ lp["mlp.up_proj.weight"]
            x = x + (jax.nn.silu(gate) * up) @ lp["mlp.down_proj.weight"]
            return x, (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))

        x, (ks, vs) = jax.lax.scan(block, x, layers)
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        return self._head(x[:, -1], tops)[0], ks[:, 0], vs[:, 0]

    def _decode_fwd(self, layers, tops, ids, positions, k_pages, v_pages,
                    lengths, page_tables):
        """One token per active sequence: ids [B], positions [B] (the
        token's position).  Each layer writes the new token's KV into
        its page (write-then-attend, so the paged attention over
        lengths+1 includes the self term), then attends over the pool.
        Returns (logits [B, V], k_pages', v_pages')."""
        cfg = self.config
        nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        ps = self.cache.page_size
        B = ids.shape[0]
        x = tops["embed"][ids][:, None]           # [B, 1, h]
        pos = positions[:, None]
        pids = page_tables[jnp.arange(B), positions // ps]  # [B]
        offs = positions % ps

        def block(x, lp_kv):
            lp, kp, vp = lp_kv
            h = _rms_norm_plain(x, lp["input_layernorm.weight"],
                                epsilon=cfg.rms_norm_eps)
            q = (h @ lp["self_attn.q_proj.weight"]).reshape(B, 1, nh, d)
            k = (h @ lp["self_attn.k_proj.weight"]).reshape(B, 1, nkv, d)
            v = (h @ lp["self_attn.v_proj.weight"]).reshape(B, 1, nkv, d)
            q, k = _rope_plain(q, k, tops["cos"], tops["sin"],
                               position_ids=pos)
            kh = jnp.swapaxes(k, 1, 2)[:, :, 0]   # [B, nkv, d]
            vh = jnp.swapaxes(v, 1, 2)[:, :, 0]
            kp = kp.at[:, pids, offs].set(
                jnp.swapaxes(kh, 0, 1).astype(kp.dtype))
            vp = vp.at[:, pids, offs].set(
                jnp.swapaxes(vh, 0, 1).astype(vp.dtype))
            o = paged_decode_attention(
                jnp.swapaxes(q, 1, 2)[:, :, 0], kp, vp, lengths + 1,
                page_tables)                      # [B, nh, d]
            o = o.reshape(B, 1, nh * d).astype(x.dtype)
            x = x + o @ lp["self_attn.o_proj.weight"]
            h2 = _rms_norm_plain(x, lp["post_attention_layernorm.weight"],
                                 epsilon=cfg.rms_norm_eps)
            gate = h2 @ lp["mlp.gate_proj.weight"]
            up = h2 @ lp["mlp.up_proj.weight"]
            x = x + (jax.nn.silu(gate) * up) @ lp["mlp.down_proj.weight"]
            return x, (kp, vp)

        x, (kps, vps) = jax.lax.scan(
            block, x, (layers, k_pages, v_pages))
        x = _rms_norm_plain(x, tops["norm_w"], epsilon=cfg.rms_norm_eps)
        return self._head(x[:, 0], tops), kps, vps

    def _decode_n_fwd(self, layers, tops, ids, positions, k_pages,
                      v_pages, lengths, page_tables, n):
        """``n`` greedy steps in ONE dispatched program: the argmax
        feedback stays on device (greedy needs no host), so the
        per-token tunnel/dispatch cost is amortized n ways — the decode
        analog of CompiledTrainStep.multi_step."""

        def body(carry, _):
            ids, positions, kp, vp, lengths = carry
            logits, kp, vp = self._decode_fwd(
                layers, tops, ids, positions, kp, vp, lengths,
                page_tables)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return (nxt, positions + 1, kp, vp, lengths + 1), nxt

        carry, toks = jax.lax.scan(
            body, (ids, positions, k_pages, v_pages, lengths), None,
            length=n)
        _ids, _pos, kp, vp, _len = carry
        return toks, kp, vp

    # -- control plane --------------------------------------------------

    def add_request(self, prompt_ids) -> int:
        """Prefill one prompt; returns the sequence slot id."""
        sid = self.cache.allocate()
        try:
            ids = jnp.asarray(np.asarray(prompt_ids)[None], jnp.int32)
            logits, k, v = self._jit_prefill(self.layers, self.tops, ids)
            self.cache.prefill(sid, k, v)
        except BaseException:
            self.cache.free(sid)  # don't strand the slot on failure
            raise
        self._last_token[sid] = int(jnp.argmax(logits))
        return sid

    def finish(self, sid: int):
        self.cache.free(sid)
        self._last_token.pop(sid, None)

    def step(self):
        """One greedy decode step over every active sequence."""
        seqs = sorted(self._last_token)
        if not seqs:
            return {}
        # batch-atomic page reservation BEFORE the jitted
        # write-then-attend: a per-sequence loop would strand earlier
        # sequences' fresh pages when a later one exhausts the pool
        self.cache.reserve(seqs, extra_tokens=1)
        ids = jnp.asarray([self._last_token[s] for s in seqs], jnp.int32)
        positions = jnp.asarray([int(self.cache.lengths[s])
                                 for s in seqs], jnp.int32)
        tables = jnp.asarray(np.maximum(self.cache.page_table[seqs], 0))
        lengths = jnp.asarray(self.cache.lengths[seqs])
        logits, kps, vps = self._jit_decode(
            self.layers, self.tops, ids, positions, self.cache.k_pages,
            self.cache.v_pages, lengths, tables)
        self.cache.k_pages = kps
        self.cache.v_pages = vps
        for s in seqs:
            self.cache.lengths[s] += 1
        # single batched argmax + ONE host transfer for the whole step
        toks = np.asarray(jnp.argmax(logits, axis=-1))
        out = {}
        for i, s in enumerate(seqs):
            tok = int(toks[i])
            self._last_token[s] = tok
            out[s] = tok
        return out

    def decode_n(self, n):
        """``n`` greedy tokens per active sequence in one dispatch.
        Returns {sid: [tok_1..tok_n]}.  Pages for all n tokens are
        reserved up front (batch-atomic), so the in-graph page writes
        can never overflow a sequence's table."""
        seqs = sorted(self._last_token)
        if not seqs:
            return {}
        self.cache.reserve(seqs, extra_tokens=n)
        ids = jnp.asarray([self._last_token[s] for s in seqs], jnp.int32)
        positions = jnp.asarray([int(self.cache.lengths[s])
                                 for s in seqs], jnp.int32)
        tables = jnp.asarray(np.maximum(self.cache.page_table[seqs], 0))
        lengths = jnp.asarray(self.cache.lengths[seqs])
        jitted = getattr(self, "_jit_decode_n", None)
        if jitted is None:
            jitted = jax.jit(self._decode_n_fwd,
                             static_argnames=("n",),
                             donate_argnums=(4, 5))
            self._jit_decode_n = jitted
        toks, kps, vps = jitted(self.layers, self.tops, ids, positions,
                                self.cache.k_pages, self.cache.v_pages,
                                lengths, tables, n=int(n))
        self.cache.k_pages = kps
        self.cache.v_pages = vps
        toks = np.asarray(toks)                     # [n, B]
        out = {}
        for i, s in enumerate(seqs):
            self.cache.lengths[s] += n
            self._last_token[s] = int(toks[-1, i])
            out[s] = toks[:, i].tolist()
        return out
