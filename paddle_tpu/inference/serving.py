"""Manual (hand-driven) serving API over the paged KV cache.

Reference: the Predictor's serving loop driven by
``block_multi_head_attention`` (block-table KV) and
``masked_multihead_attention`` (decode step) — the reference's
continuous-batching inference stack.

The model execution now lives in
:class:`~paddle_tpu.inference.server.executor.PagedExecutor` (shared
with the continuous-batching :class:`ServingEngine` scheduler), so the
hand-driven and the scheduled paths run byte-identical jitted programs.
This class is the legacy thin shim: explicit ``add_request`` /
``step`` / ``decode_n`` / ``finish`` with no queueing, admission or
preemption — the caller is the scheduler.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .server.executor import PagedExecutor


class PagedLlamaEngine:
    """Greedy continuous-batching decoder for a LlamaForCausalLM.

    engine = PagedLlamaEngine(model, max_seqs=4, page_size=16,
                              max_len=256)
    sid = engine.add_request(prompt_ids)           # prefill
    out = engine.step()                            # {sid: next_token}
    engine.finish(sid)                             # free pages
    """

    def __init__(self, model, max_seqs=4, page_size=16, max_len=256,
                 dtype=jnp.float32):
        self._ex = PagedExecutor(model, max_seqs=max_seqs,
                                 page_size=page_size, max_len=max_len,
                                 dtype=dtype)

    # the shim exposes the executor's state under the historical names
    @property
    def config(self):
        return self._ex.config

    @property
    def cache(self):
        return self._ex.cache

    @property
    def layers(self):
        return self._ex.layers

    @property
    def tops(self):
        return self._ex.tops

    @property
    def _last_token(self):
        return self._ex.last_token

    def add_request(self, prompt_ids) -> int:
        """Prefill one prompt; returns the sequence slot id."""
        sid = self._ex.alloc_slot()
        try:
            self._ex.prefill(sid, np.asarray(prompt_ids))
        except BaseException:
            self._ex.free_slot(sid)  # don't strand the slot on failure
            raise
        return sid

    def finish(self, sid: int):
        self._ex.free_slot(sid)

    def step(self):
        """One greedy decode step over every active sequence."""
        return self._ex.decode(sorted(self._ex.last_token))

    def decode_n(self, n):
        """``n`` greedy tokens per active sequence in one dispatch.
        Returns {sid: [tok_1..tok_n]}."""
        return self._ex.decode_n(sorted(self._ex.last_token), n)
