"""TransformedDistribution + Independent distribution wrappers.

Reference: ``python/paddle/distribution/transformed_distribution.py:27``
and ``independent.py:25``.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import registry as _registry

_op = _registry.cached_apply


class TransformedDistribution:
    """Distribution of y = f_k(...f_1(x)) for x ~ base.

    log_prob(y) = base.log_prob(f^-1(y)) - log|det J_f(f^-1(y))|,
    summed over transform-introduced event dims.
    """

    def __init__(self, base, transforms):
        from .transform import ChainTransform, Transform

        if not isinstance(transforms, (list, tuple)):
            raise TypeError("transforms must be a list/tuple of "
                            "Transform")
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"not a Transform: {t!r}")
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        base_event = tuple(base.event_shape)
        shape = tuple(base.batch_shape) + base_event
        out_shape = chain.forward_shape(shape)
        extra = chain._codomain.event_rank - len(base_event)
        event_rank = max(len(base_event) + max(extra, 0),
                         chain._codomain.event_rank)
        cut = len(out_shape) - event_rank
        self._batch_shape = tuple(out_shape[:cut])
        self._event_shape = tuple(out_shape[cut:])
        # a broadcasting transform (e.g. vector loc over a scalar base)
        # widens the output; base draws must carry those extra leading
        # dims so sample shapes compose (code-review r4).
        base_own = tuple(base.batch_shape) + base_event
        inv = tuple(chain.inverse_shape(out_shape))
        self._base_extra = inv[:len(inv) - len(base_own)]
        self._chain = chain

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        x = self.base.sample(tuple(shape) + self._base_extra)
        for t in self.transforms:
            x = t.forward(x)
        return x.detach() if hasattr(x, "detach") else x

    def rsample(self, shape=()):
        x = self.base.rsample(tuple(shape) + self._base_extra)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        from . import _t

        y = _t(value)
        lp = None
        event_rank = (len(self._event_shape)
                      or self._chain._codomain.event_rank)
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ldj = t.forward_log_det_jacobian(x)
            extra = event_rank - t._codomain.event_rank
            if extra > 0:
                axes = tuple(range(-extra, 0))
                ldj = _op("tdist_sum",
                          lambda v, axes: jnp.sum(v, axis=axes),
                          ldj, axes=axes)
            lp = (-ldj) if lp is None else lp - ldj
            event_rank += t._domain.event_rank - t._codomain.event_rank
            y = x
        base_lp = self.base.log_prob(y)
        extra = event_rank - len(tuple(self.base.event_shape))
        if extra > 0:
            axes = tuple(range(-extra, 0))
            base_lp = _op("tdist_sum",
                          lambda v, axes: jnp.sum(v, axis=axes),
                          base_lp, axes=axes)
        return base_lp if lp is None else base_lp + lp

    def prob(self, value):
        from .. import ops

        return ops.exp(self.log_prob(value))


class Independent:
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims
    of a base distribution as event dims (reference independent.py:25):
    log_prob sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        rank = int(reinterpreted_batch_rank)
        if not 0 < rank <= len(tuple(base.batch_shape)):
            raise ValueError(
                f"reinterpreted_batch_rank must be in (0, "
                f"{len(tuple(base.batch_shape))}], got {rank}")
        self.base = base
        self._rank = rank
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        cut = len(tuple(base.batch_shape)) - rank
        self._batch_shape = shape[:cut]
        self._event_shape = shape[cut:]

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        axes = tuple(range(-self._rank, 0))
        return _op("indep_lp_sum",
                   lambda v, axes: jnp.sum(v, axis=axes), lp, axes=axes)

    def prob(self, value):
        from .. import ops

        return ops.exp(self.log_prob(value))

    def entropy(self):
        ent = self.base.entropy()
        axes = tuple(range(-self._rank, 0))
        return _op("indep_ent_sum",
                   lambda v, axes: jnp.sum(v, axis=axes), ent, axes=axes)
