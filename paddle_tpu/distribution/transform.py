"""Transform machinery + TransformedDistribution + Independent.

Reference: ``python/paddle/distribution/transform.py`` (Transform and
the 13 concrete transforms), ``transformed_distribution.py:27``,
``independent.py:25``, ``variable.py`` (domain/codomain descriptors).

jax-native: forward/inverse/log-det are closed-form jnp expressions
dispatched through the op registry (same pattern as the distributions
module), so they are differentiable under the eager tape and traceable
under jit.
"""
from __future__ import annotations

import enum
import math
import operator
from functools import reduce

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry

_op = _registry.cached_apply


def _t(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, jnp.float32))


# -- variable.py: domain/codomain descriptors --------------------------------


class Variable:
    """Reference variable.py:27 — domain descriptor of a transform."""

    def __init__(self, is_discrete=False, event_rank=0):
        self._is_discrete = is_discrete
        self._event_rank = event_rank

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, x):
        raise NotImplementedError


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank)

    def constraint(self, x):
        return _op("variable_real", lambda v: jnp.isfinite(v), _t(x))


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank)

    def constraint(self, x):
        return _op("variable_positive", lambda v: v > 0, _t(x))


class Independent(Variable):
    """Reinterprets the rightmost ``reinterpreted_batch_rank`` dims of a
    base variable as event dims (variable.py:70)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(base.is_discrete,
                         base.event_rank + reinterpreted_batch_rank)

    def constraint(self, x):
        ok = self._base.constraint(x)
        axes = tuple(range(-self._reinterpreted_batch_rank, 0))
        return _op("variable_independent",
                   lambda v, axes: jnp.all(v, axis=axes), ok, axes=axes)


class Stack(Variable):
    def __init__(self, vars, axis=0):
        self._vars = list(vars)
        self._axis = axis
        super().__init__(any(v.is_discrete for v in self._vars),
                         max(v.event_rank for v in self._vars))


real = Real()
positive = Positive()


# -- Transform base ----------------------------------------------------------


class _Type(enum.Enum):
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    r"""Base class for invertible transforms y = f(x) with log|det J|
    (reference transform.py:70)."""

    _type = _Type.INJECTION

    @classmethod
    def _is_injective(cls):
        return _Type.is_injective(cls._type)

    def __call__(self, input):
        if isinstance(input, Transform):
            return ChainTransform([self, input])
        from . import Distribution

        if isinstance(input, Distribution):
            from .transformed_distribution import TransformedDistribution

            return TransformedDistribution(input, [self])
        return self.forward(input)

    def forward(self, x):
        return self._forward(_t(x))

    def inverse(self, y):
        return self._inverse(_t(y))

    def forward_log_det_jacobian(self, x):
        if hasattr(self, "_forward_log_det_jacobian"):
            return self._forward_log_det_jacobian(_t(x))
        if hasattr(self, "_inverse_log_det_jacobian"):
            return -self._inverse_log_det_jacobian(self.forward(_t(x)))
        raise NotImplementedError(
            f"{type(self).__name__} has no log-det-jacobian")

    def inverse_log_det_jacobian(self, y):
        if hasattr(self, "_inverse_log_det_jacobian"):
            return self._inverse_log_det_jacobian(_t(y))
        if hasattr(self, "_forward_log_det_jacobian"):
            return -self._forward_log_det_jacobian(self.inverse(_t(y)))
        raise NotImplementedError(
            f"{type(self).__name__} has no log-det-jacobian")

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    @property
    def _domain(self):
        return real

    @property
    def _codomain(self):
        return real


# -- concrete transforms -----------------------------------------------------


class AbsTransform(Transform):
    """y = |x| (surjective; inverse picks the positive branch).
    Reference transform.py:374."""

    _type = _Type.SURJECTION

    def _forward(self, x):
        return _op("abs_t_fwd", lambda v: jnp.abs(v), x)

    def _inverse(self, y):
        return _op("abs_t_inv", lambda v: v, y)

    @property
    def _codomain(self):
        return positive


class AffineTransform(Transform):
    """y = loc + scale * x.  Reference transform.py:447."""

    _type = _Type.BIJECTION

    def __init__(self, loc, scale):
        self._loc = _t(loc)
        self._scale = _t(scale)

    @property
    def loc(self):
        return self._loc

    @property
    def scale(self):
        return self._scale

    def _forward(self, x):
        return _op("affine_t_fwd", lambda l, s, v: l + s * v,
                   self._loc, self._scale, x)

    def _inverse(self, y):
        return _op("affine_t_inv", lambda l, s, v: (v - l) / s,
                   self._loc, self._scale, y)

    def _forward_log_det_jacobian(self, x):
        return _op("affine_t_ldj",
                   lambda s, v: jnp.broadcast_to(
                       jnp.log(jnp.abs(s)), jnp.broadcast_shapes(
                           jnp.shape(s), jnp.shape(v))),
                   self._scale, x)

    def forward_shape(self, shape):
        return tuple(jnp.broadcast_shapes(
            tuple(shape), tuple(self._loc.shape),
            tuple(self._scale.shape)))

    inverse_shape = forward_shape


class ExpTransform(Transform):
    """y = exp(x).  Reference transform.py:659."""

    _type = _Type.BIJECTION

    def _forward(self, x):
        return _op("exp_t_fwd", lambda v: jnp.exp(v), x)

    def _inverse(self, y):
        return _op("exp_t_inv", lambda v: jnp.log(v), y)

    def _forward_log_det_jacobian(self, x):
        return _op("exp_t_ldj", lambda v: v, x)

    @property
    def _codomain(self):
        return positive


class PowerTransform(Transform):
    """y = x ** power (x > 0).  Reference transform.py:804."""

    _type = _Type.BIJECTION

    def __init__(self, power):
        self._power = _t(power)

    @property
    def power(self):
        return self._power

    def _forward(self, x):
        return _op("power_t_fwd", lambda p, v: jnp.power(v, p),
                   self._power, x)

    def _inverse(self, y):
        return _op("power_t_inv", lambda p, v: jnp.power(v, 1.0 / p),
                   self._power, y)

    def _forward_log_det_jacobian(self, x):
        return _op("power_t_ldj",
                   lambda p, v: jnp.log(jnp.abs(p * jnp.power(v, p - 1))),
                   self._power, x)

    def forward_shape(self, shape):
        return tuple(jnp.broadcast_shapes(tuple(shape),
                                          tuple(self._power.shape)))

    inverse_shape = forward_shape

    @property
    def _domain(self):
        return positive

    @property
    def _codomain(self):
        return positive


class SigmoidTransform(Transform):
    """y = sigmoid(x).  Reference transform.py:997."""

    _type = _Type.BIJECTION

    def _forward(self, x):
        return _op("sigmoid_t_fwd", lambda v: jax.nn.sigmoid(v), x)

    def _inverse(self, y):
        return _op("sigmoid_t_inv",
                   lambda v: jnp.log(v) - jnp.log1p(-v), y)

    def _forward_log_det_jacobian(self, x):
        return _op("sigmoid_t_ldj",
                   lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v),
                   x)

    @property
    def _codomain(self):
        return Variable(False, 0)


class TanhTransform(Transform):
    """y = tanh(x).  Reference transform.py:1283."""

    _type = _Type.BIJECTION

    def _forward(self, x):
        return _op("tanh_t_fwd", lambda v: jnp.tanh(v), x)

    def _inverse(self, y):
        return _op("tanh_t_inv", lambda v: jnp.arctanh(v), y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log 2 - x - softplus(-2x)), the
        # numerically-stable form the reference uses.
        return _op("tanh_t_ldj",
                   lambda v: 2.0 * (math.log(2.0) - v
                                    - jax.nn.softplus(-2.0 * v)), x)


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (not a bijection; inverse is
    log up to an additive constant).  Reference transform.py:1040."""

    _type = _Type.OTHER

    def _forward(self, x):
        return _op("softmax_t_fwd",
                   lambda v: jax.nn.softmax(v, axis=-1), x)

    def _inverse(self, y):
        return _op("softmax_t_inv", lambda v: jnp.log(v), y)

    @property
    def _domain(self):
        return Independent(real, 1)

    @property
    def _codomain(self):
        return Independent(Variable(False, 0), 1)


class StickBreakingTransform(Transform):
    """Unconstrained R^{K-1} -> K-simplex via stick-breaking.
    Reference transform.py:1217."""

    _type = _Type.BIJECTION

    def _forward(self, x):
        def fn(v):
            offset = v.shape[-1] - jnp.arange(v.shape[-1], dtype=v.dtype)
            z = jax.nn.sigmoid(v - jnp.log(offset))
            zp = jnp.concatenate(
                [jnp.zeros_like(z[..., :1]), z], -1)
            cum = jnp.cumprod(1 - zp, -1)
            z1 = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
            return z1 * cum

        return _op("stickbreak_t_fwd", fn, x)

    def _inverse(self, y):
        def fn(v):
            cum = jnp.cumsum(v[..., :-1], -1)
            rem = 1.0 - jnp.concatenate(
                [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], -1)
            z = v[..., :-1] / rem
            offset = (v.shape[-1] - 1
                      - jnp.arange(v.shape[-1] - 1, dtype=v.dtype))
            return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

        return _op("stickbreak_t_inv", fn, y)

    def _forward_log_det_jacobian(self, x):
        def fn(v):
            offset = v.shape[-1] - jnp.arange(v.shape[-1], dtype=v.dtype)
            z = jax.nn.sigmoid(v - jnp.log(offset))
            # log|det J| = sum_i log(sigmoid'(.) * remaining stick)
            return jnp.sum(jnp.log(z * (1 - z)) + jnp.log(
                jnp.cumprod(jnp.concatenate(
                    [jnp.ones_like(z[..., :1]), 1 - z[..., :-1]], -1),
                    -1)), -1)

        return _op("stickbreak_t_ldj", fn, x)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)

    @property
    def _domain(self):
        return Independent(real, 1)

    @property
    def _codomain(self):
        return Independent(Variable(False, 0), 1)


class ReshapeTransform(Transform):
    """Reshape trailing event dims.  Reference transform.py:871."""

    _type = _Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self._in = tuple(int(d) for d in in_event_shape)
        self._out = tuple(int(d) for d in out_event_shape)
        if reduce(operator.mul, self._in, 1) != \
                reduce(operator.mul, self._out, 1):
            raise ValueError("in_event_shape and out_event_shape must "
                             "have the same number of elements")

    @property
    def in_event_shape(self):
        return self._in

    @property
    def out_event_shape(self):
        return self._out

    def _forward(self, x):
        out = self._out

        def fn(v):
            batch = v.shape[:v.ndim - len(self._in)]
            return v.reshape(batch + out)

        return _op("reshape_t_fwd_%s_%s" % (self._in, self._out), fn, x)

    def _inverse(self, y):
        inn = self._in

        def fn(v):
            batch = v.shape[:v.ndim - len(self._out)]
            return v.reshape(batch + inn)

        return _op("reshape_t_inv_%s_%s" % (self._in, self._out), fn, y)

    def _forward_log_det_jacobian(self, x):
        n = len(self._in)

        def fn(v):
            return jnp.zeros(v.shape[:v.ndim - n], v.dtype)

        return _op("reshape_t_ldj_%d" % n, fn, x)

    def forward_shape(self, shape):
        if tuple(shape[len(shape) - len(self._in):]) != self._in:
            raise ValueError(f"shape {shape} does not end in {self._in}")
        return tuple(shape[:len(shape) - len(self._in)]) + self._out

    def inverse_shape(self, shape):
        if tuple(shape[len(shape) - len(self._out):]) != self._out:
            raise ValueError(f"shape {shape} does not end in {self._out}")
        return tuple(shape[:len(shape) - len(self._out)]) + self._in

    @property
    def _domain(self):
        return Independent(real, len(self._in))

    @property
    def _codomain(self):
        return Independent(real, len(self._out))


class IndependentTransform(Transform):
    """Promote the rightmost ``reinterpreted_batch_rank`` batch dims of
    a base transform to event dims (sums the log-det over them).
    Reference transform.py:709."""

    def __init__(self, base, reinterpreted_batch_rank):
        if reinterpreted_batch_rank <= 0:
            raise ValueError("reinterpreted_batch_rank must be positive")
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    @classmethod
    def _is_injective(cls):
        return True

    def _forward(self, x):
        return self._base.forward(x)

    def _inverse(self, y):
        return self._base.inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self._base.forward_log_det_jacobian(x)
        axes = tuple(range(-self._rank, 0))
        return _op("indep_t_sum", lambda v, axes: jnp.sum(v, axis=axes),
                   ldj, axes=axes)

    def forward_shape(self, shape):
        return self._base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self._base.inverse_shape(shape)

    @property
    def _domain(self):
        return Independent(self._base._domain, self._rank)

    @property
    def _codomain(self):
        return Independent(self._base._codomain, self._rank)


class ChainTransform(Transform):
    """Composition f_n ∘ ... ∘ f_1 (applied left to right on forward).
    Reference transform.py:534."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    @classmethod
    def _is_injective(cls):
        return True

    def _forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        event_rank = self._domain.event_rank
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            extra = event_rank - t._domain.event_rank
            if extra > 0:
                axes = tuple(range(-extra, 0))
                ldj = _op("chain_t_sum",
                          lambda v, axes: jnp.sum(v, axis=axes),
                          ldj, axes=axes)
            total = ldj if total is None else total + ldj
            x = t.forward(x)
            event_rank += (t._codomain.event_rank
                           - t._domain.event_rank)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)

    @property
    def _domain(self):
        rank = max((t._domain.event_rank for t in self.transforms),
                   default=0)
        return Independent(real, rank) if rank else real

    @property
    def _codomain(self):
        rank = max((t._codomain.event_rank for t in self.transforms),
                   default=0)
        return Independent(real, rank) if rank else real


class StackTransform(Transform):
    """Apply a list of transforms to slices along ``axis``.
    Reference transform.py:1097."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self._axis = int(axis)

    @property
    def axis(self):
        return self._axis

    def _map(self, value, method):
        from .. import ops

        parts = []
        for i, t in enumerate(self.transforms):
            sl = ops.squeeze(
                ops.slice(value, [self._axis], [i], [i + 1]),
                axis=self._axis)
            parts.append(getattr(t, method)(sl))
        return ops.stack(parts, axis=self._axis)

    def _forward(self, x):
        return self._map(x, "forward")

    def _inverse(self, y):
        return self._map(y, "inverse")

    def _forward_log_det_jacobian(self, x):
        return self._map(x, "forward_log_det_jacobian")

    @property
    def _domain(self):
        return Stack([t._domain for t in self.transforms], self._axis)

    @property
    def _codomain(self):
        return Stack([t._codomain for t in self.transforms], self._axis)
