"""Probability distributions.

Reference: ``python/paddle/distribution/`` — Distribution base
(distribution.py), Normal, Uniform, Bernoulli, Categorical, Beta,
Dirichlet, Exponential, Gamma, Laplace, Gumbel, LogNormal, and the
``kl_divergence`` dispatch (kl.py).  Densities/entropies are closed-form
jax expressions; sampling draws from the global Generator's key stream
(ops/random.py), so ``paddle.seed`` governs reproducibility exactly like
the tensor random ops.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.random import default_generator


def _d(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jnp.ndarray) \
        else x


def _shape(s):
    if s is None:
        return ()
    return tuple(int(v) for v in s)


class Distribution:
    """Reference distribution/distribution.py Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops

        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _d(loc)
        self.scale = _d(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        eps = jax.random.normal(key, s, jnp.float32)
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        v = _d(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(out, self.batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _d(loc)
        self.scale = _d(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    def sample(self, shape=()):
        return Tensor(jnp.exp(self._base.sample(shape)._data))

    rsample = sample

    def log_prob(self, value):
        v = _d(value)
        return Tensor(self._base.log_prob(Tensor(jnp.log(v)))._data
                      - jnp.log(v))

    def entropy(self):
        return Tensor(self._base.entropy()._data + self.loc)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _d(low)
        self.high = _d(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(key, s, jnp.float32)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _d(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _d(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            key, self.probs, s).astype(jnp.float32))

    def log_prob(self, value):
        v = _d(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _d(logits)
            self._log_p = jax.nn.log_softmax(self.logits, -1)
        else:
            p = _d(probs)
            p = p / jnp.sum(p, -1, keepdims=True)
            self._log_p = jnp.log(jnp.clip(p, 1e-12))
            self.logits = self._log_p
        super().__init__(self._log_p.shape[:-1])

    @property
    def probs(self):
        return Tensor(jnp.exp(self._log_p))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.categorical(key, self.logits, -1, s))

    def log_prob(self, value):
        v = _d(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            self._log_p, v[..., None], -1)[..., 0])

    def entropy(self):
        p = jnp.exp(self._log_p)
        return Tensor(-jnp.sum(p * self._log_p, -1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _d(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.exponential(key, s, jnp.float32)
                      / self.rate)

    rsample = sample

    def log_prob(self, value):
        v = _d(value)
        return Tensor(jnp.where(v >= 0, jnp.log(self.rate)
                                - self.rate * v, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(1.0 - jnp.log(self.rate),
                                       self.batch_shape))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _d(loc)
        self.scale = _d(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.laplace(key, s, jnp.float32))

    rsample = sample

    def log_prob(self, value):
        v = _d(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(1.0 + jnp.log(2 * self.scale),
                                       self.batch_shape))


class Gumbel(Distribution):
    _euler = 0.5772156649015329

    def __init__(self, loc, scale, name=None):
        self.loc = _d(loc)
        self.scale = _d(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.gumbel(key, s, jnp.float32))

    rsample = sample

    def log_prob(self, value):
        z = (_d(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(self.scale) + 1.0 + self._euler, self.batch_shape))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _d(alpha)
        self.beta = _d(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(key, self.alpha, self.beta, s))

    def log_prob(self, value):
        v = _d(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a)
                 + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                      - lbeta)

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a)
                 + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _d(concentration)
        self.rate = _d(rate)
        super().__init__(jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape))

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.gamma(key, self.concentration, s)
                      / self.rate)

    def log_prob(self, value):
        v = _d(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        dg = jax.scipy.special.digamma
        return Tensor(a - jnp.log(b) + jax.scipy.special.gammaln(a)
                      + (1 - a) * dg(a))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _d(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        key = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(key, self.concentration, s))

    def log_prob(self, value):
        v = _d(value)
        a = self.concentration
        lnorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                 - jax.scipy.special.gammaln(jnp.sum(a, -1)))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - lnorm)


# -- KL divergence dispatch (reference distribution/kl.py) -------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    # support(p) must lie inside support(q); else +inf
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return Tensor(jnp.where(inside, kl, jnp.inf))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return Tensor(a * (jnp.log(a) - jnp.log(b))
                  + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    pp = jnp.exp(p._log_p)
    return Tensor(jnp.sum(pp * (p._log_p - q._log_p), -1))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    g = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    t = (g(a1 + b1) - g(a1) - g(b1)
         - (g(a2 + b2) - g(a2) - g(b2)))
    return Tensor(t + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                  + (a2 - a1 + b2 - b1) * dg(a1 + b1))
