"""Probability distributions.

Reference: ``python/paddle/distribution/`` — Distribution base
(distribution.py), Normal, Uniform, Bernoulli, Categorical, Beta,
Dirichlet, Exponential, Gamma, Laplace, Gumbel, LogNormal, and the
``kl_divergence`` dispatch (kl.py).  Densities/entropies are closed-form
jax expressions; sampling draws from the global Generator's key stream
(ops/random.py), so ``paddle.seed`` governs reproducibility exactly like
the tensor random ops.

All math is dispatched through the op registry (``_op`` below), so the
eager tape records it: ``dist.log_prob(x)`` in a loss back-propagates to
Tensor parameters, and ``rsample`` is reparameterized (gradients flow to
loc/scale through the sampled value) — matching the reference's
differentiable distributions.  ``sample`` is ``rsample`` detached (or a
genuinely non-reparameterizable draw).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry
from ..ops.random import default_generator

_EULER = 0.5772156649015329


def _t(x):
    """Keep Tensor identity (the tape links through it); wrap others."""
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, jnp.float32))


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _shape(s):
    if s is None:
        return ()
    return tuple(int(v) for v in s)


# Dispatch closed-form distribution math through the op registry
# (jit-cached, tape-recorded — the jax.vjp fallback supplies the
# backward), which is what makes it differentiable through the eager
# engine (round-2 advisor finding).
_op = _registry.cached_apply


class Distribution:
    """Reference distribution/distribution.py Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(int(d) for d in batch_shape)
        self._event_shape = tuple(int(d) for d in event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops

        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    @property
    def mean(self):
        return _op("dist_broadcast",
                   lambda x, shape: jnp.broadcast_to(x, shape),
                   self.loc, shape=self.batch_shape)

    @property
    def variance(self):
        return _op("normal_variance",
                   lambda s, shape: jnp.broadcast_to(s * s, shape),
                   self.scale, shape=self.batch_shape)

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        eps = jax.random.normal(default_generator.next_key(), s,
                                jnp.float32)
        return _op("normal_rsample",
                   lambda loc, scale, e: loc + scale * e,
                   self.loc, self.scale, Tensor(eps))

    def log_prob(self, value):
        def fn(loc, scale, v):
            return (-jnp.square(v - loc) / (2 * scale * scale)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))

        return _op("normal_log_prob", fn, self.loc, self.scale, _t(value))

    def entropy(self):
        def fn(scale, shape):
            out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
            return jnp.broadcast_to(out, shape)

        return _op("normal_entropy", fn, self.scale,
                   shape=self.batch_shape)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(self._base.batch_shape)

    def rsample(self, shape=()):
        from .. import ops

        return ops.exp(self._base.rsample(shape))

    def log_prob(self, value):
        def fn(loc, scale, v):
            lv = jnp.log(v)
            return (-jnp.square(lv - loc) / (2 * scale * scale)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi) - lv)

        return _op("lognormal_log_prob", fn, self.loc, self.scale,
                   _t(value))

    def entropy(self):
        def fn(loc, scale, shape):
            out = (0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
                   + loc)
            return jnp.broadcast_to(out, shape)

        return _op("lognormal_entropy", fn, self.loc, self.scale,
                   shape=self.batch_shape)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(tuple(self.low.shape),
                                              tuple(self.high.shape)))

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(default_generator.next_key(), s,
                               jnp.float32)
        return _op("uniform_rsample",
                   lambda lo, hi, u: lo + (hi - lo) * u,
                   self.low, self.high, Tensor(u))

    def log_prob(self, value):
        def fn(lo, hi, v):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return _op("uniform_log_prob", fn, self.low, self.high, _t(value))

    def entropy(self):
        def fn(lo, hi, shape):
            return jnp.broadcast_to(jnp.log(hi - lo), shape)

        return _op("uniform_entropy", fn, self.low, self.high,
                   shape=self.batch_shape)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return _op("bernoulli_variance", lambda p: p * (1 - p), self.probs)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        out = jax.random.bernoulli(default_generator.next_key(),
                                   _raw(self.probs), s)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def fn(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return _op("bernoulli_log_prob", fn, self.probs, _t(value))

    def entropy(self):
        def fn(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

        return _op("bernoulli_entropy", fn, self.probs)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _t(logits)
            self._from_logits = True
        else:
            self.logits = _t(probs)
            self._from_logits = False
        super().__init__(tuple(self.logits.shape)[:-1])

    def _log_p_fn(self):
        if self._from_logits:
            return lambda lg: jax.nn.log_softmax(lg, -1)

        def fn(p):
            p = p / jnp.sum(p, -1, keepdims=True)
            return jnp.log(jnp.clip(p, 1e-12))

        return fn

    @property
    def _log_p(self):
        # Raw array view (used by sampling and tooling) — computed once
        # per instance; logits are immutable after construction.
        cached = getattr(self, "_log_p_cache", None)
        if cached is None:
            cached = self._log_p_fn()(_raw(self.logits))
            self._log_p_cache = cached
        return cached

    @property
    def probs(self):
        fn = self._log_p_fn()
        return _op("categorical_probs_%d" % self._from_logits,
                   lambda lg: jnp.exp(fn(lg)), self.logits)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        out = jax.random.categorical(default_generator.next_key(),
                                     self._log_p, -1, s)
        return Tensor(out)

    def log_prob(self, value):
        fn = self._log_p_fn()

        def lp(lg, v):
            v = v.astype(jnp.int32)
            return jnp.take_along_axis(fn(lg), v[..., None], -1)[..., 0]

        return _op("categorical_log_prob_%d" % self._from_logits, lp,
                   self.logits, _t(value))

    def entropy(self):
        fn = self._log_p_fn()

        def ent(lg):
            logp = fn(lg)
            return -jnp.sum(jnp.exp(logp) * logp, -1)

        return _op("categorical_entropy_%d" % self._from_logits, ent,
                   self.logits)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return _op("exponential_mean", lambda r: 1.0 / r, self.rate)

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        e = jax.random.exponential(default_generator.next_key(), s,
                                   jnp.float32)
        return _op("exponential_rsample", lambda r, e: e / r,
                   self.rate, Tensor(e))

    def log_prob(self, value):
        def fn(r, v):
            return jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf)

        return _op("exponential_log_prob", fn, self.rate, _t(value))

    def entropy(self):
        def fn(r, shape):
            return jnp.broadcast_to(1.0 - jnp.log(r), shape)

        return _op("exponential_entropy", fn, self.rate,
                   shape=self.batch_shape)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        e = jax.random.laplace(default_generator.next_key(), s,
                               jnp.float32)
        return _op("laplace_rsample", lambda l, sc, e: l + sc * e,
                   self.loc, self.scale, Tensor(e))

    def log_prob(self, value):
        def fn(l, sc, v):
            return -jnp.abs(v - l) / sc - jnp.log(2 * sc)

        return _op("laplace_log_prob", fn, self.loc, self.scale, _t(value))

    def entropy(self):
        def fn(sc, shape):
            return jnp.broadcast_to(1.0 + jnp.log(2 * sc), shape)

        return _op("laplace_entropy", fn, self.scale,
                   shape=self.batch_shape)


class Gumbel(Distribution):
    _euler = _EULER

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        e = jax.random.gumbel(default_generator.next_key(), s, jnp.float32)
        return _op("gumbel_rsample", lambda l, sc, e: l + sc * e,
                   self.loc, self.scale, Tensor(e))

    def log_prob(self, value):
        def fn(l, sc, v):
            z = (v - l) / sc
            return -(z + jnp.exp(-z)) - jnp.log(sc)

        return _op("gumbel_log_prob", fn, self.loc, self.scale, _t(value))

    def entropy(self):
        def fn(sc, shape):
            return jnp.broadcast_to(jnp.log(sc) + 1.0 + _EULER, shape)

        return _op("gumbel_entropy", fn, self.scale,
                   shape=self.batch_shape)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(jnp.broadcast_shapes(tuple(self.alpha.shape),
                                              tuple(self.beta.shape)))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        out = jax.random.beta(default_generator.next_key(),
                              _raw(self.alpha), _raw(self.beta), s)
        return Tensor(out)

    def log_prob(self, value):
        def fn(a, b, v):
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta

        return _op("beta_log_prob", fn, self.alpha, self.beta, _t(value))

    def entropy(self):
        def fn(a, b):
            dg = jax.scipy.special.digamma
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))

        return _op("beta_entropy", fn, self.alpha, self.beta)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(jnp.broadcast_shapes(
            tuple(self.concentration.shape), tuple(self.rate.shape)))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        out = jax.random.gamma(default_generator.next_key(),
                               _raw(self.concentration), s)
        return Tensor(out / _raw(self.rate))

    def log_prob(self, value):
        def fn(a, b, v):
            return (a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                    - jax.scipy.special.gammaln(a))

        return _op("gamma_log_prob", fn, self.concentration, self.rate,
                   _t(value))

    def entropy(self):
        def fn(a, b):
            dg = jax.scipy.special.digamma
            return (a - jnp.log(b) + jax.scipy.special.gammaln(a)
                    + (1 - a) * dg(a))

        return _op("gamma_entropy", fn, self.concentration, self.rate)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shp = tuple(self.concentration.shape)
        super().__init__(shp[:-1], shp[-1:])

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        out = jax.random.dirichlet(default_generator.next_key(),
                                   _raw(self.concentration), s)
        return Tensor(out)

    def log_prob(self, value):
        def fn(a, v):
            lnorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                     - jax.scipy.special.gammaln(jnp.sum(a, -1)))
            return jnp.sum((a - 1) * jnp.log(v), -1) - lnorm

        return _op("dirichlet_log_prob", fn, self.concentration, _t(value))


# -- KL divergence dispatch (reference distribution/kl.py) -------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    # Most-specific-superclass dispatch (reference kl.py dispatch): an
    # exact match wins; otherwise the closest registered (P, Q) pair in
    # MRO order — so Chi2 resolves to the (Gamma, Gamma) rule.
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        best = None
        for pc in type(p).__mro__:
            for qc in type(q).__mro__:
                cand = _KL_REGISTRY.get((pc, qc))
                if cand is not None:
                    rank = (type(p).__mro__.index(pc),
                            type(q).__mro__.index(qc))
                    if best is None or rank < best[0]:
                        best = (rank, cand)
        if best is not None:
            fn = best[1]
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def fn(pl, ps, ql, qs):
        var_ratio = jnp.square(ps / qs)
        t1 = jnp.square((pl - ql) / qs)
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    return _op("kl_normal_normal", fn, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def fn(pl, ph, ql, qh):
        inside = (ql <= pl) & (ph <= qh)
        kl = jnp.log((qh - ql) / (ph - pl))
        return jnp.where(inside, kl, jnp.inf)

    return _op("kl_uniform_uniform", fn, p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pp, qp):
        a = jnp.clip(pp, 1e-7, 1 - 1e-7)
        b = jnp.clip(qp, 1e-7, 1 - 1e-7)
        return (a * (jnp.log(a) - jnp.log(b))
                + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))

    return _op("kl_bernoulli_bernoulli", fn, p.probs, q.probs)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    pfn, qfn = p._log_p_fn(), q._log_p_fn()

    def fn(plg, qlg):
        plp, qlp = pfn(plg), qfn(qlg)
        return jnp.sum(jnp.exp(plp) * (plp - qlp), -1)

    return _op("kl_categorical_%d%d" % (p._from_logits, q._from_logits),
               fn, p.logits, q.logits)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    def fn(pr, qr):
        return jnp.log(pr) - jnp.log(qr) + qr / pr - 1

    return _op("kl_exponential_exponential", fn, p.rate, q.rate)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def fn(a1, b1, a2, b2):
        g = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        t = (g(a1 + b1) - g(a1) - g(b1)
             - (g(a2 + b2) - g(a2) - g(b2)))
        return (t + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                + (a2 - a1 + b2 - b1) * dg(a1 + b1))

    return _op("kl_beta_beta", fn, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def fn(a1, b1, a2, b2):
        g = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        return ((a1 - a2) * dg(a1) - g(a1) + g(a2)
                + a2 * (jnp.log(b1) - jnp.log(b2))
                + a1 * (b2 / b1 - 1.0))

    return _op("kl_gamma_gamma", fn, p.concentration, p.rate,
               q.concentration, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def fn(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2) - jnp.log(s1)
                + (s1 * jnp.exp(-d / s1) + d) / s2 - 1.0)

    return _op("kl_laplace_laplace", fn, p.loc, p.scale, q.loc, q.scale)


# -- long tail: transforms + wrappers + extra distributions ------------------
# (imported last: they subclass Distribution/Gamma defined above)
from .transform import (  # noqa: E402,F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, Transform,
)
from .transformed_distribution import (  # noqa: E402,F401
    Independent, TransformedDistribution,
)
from .more import (  # noqa: E402,F401
    Binomial, Cauchy, Chi2, ContinuousBernoulli, ExponentialFamily,
    Geometric, LKJCholesky, Multinomial, MultivariateNormal, Poisson,
    StudentT,
)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def fn(l1, lt1, l2, lt2):
        d = l1.shape[-1]
        # broadcast every operand to the common batch shape first —
        # solve_triangular requires matching batch ranks.
        batch = jnp.broadcast_shapes(l1.shape[:-1], l2.shape[:-1],
                                     lt1.shape[:-2], lt2.shape[:-2])
        l1 = jnp.broadcast_to(l1, batch + (d,))
        l2 = jnp.broadcast_to(l2, batch + (d,))
        lt1 = jnp.broadcast_to(lt1, batch + (d, d))
        lt2 = jnp.broadcast_to(lt2, batch + (d, d))
        diff = l2 - l1
        sol_mean = jax.scipy.linalg.solve_triangular(
            lt2, diff[..., None], lower=True)[..., 0]
        sol_cov = jax.scipy.linalg.solve_triangular(
            lt2, lt1, lower=True)
        tr = jnp.sum(sol_cov * sol_cov, axis=(-2, -1))
        logdet1 = jnp.sum(jnp.log(jnp.diagonal(lt1, axis1=-2,
                                               axis2=-1)), -1)
        logdet2 = jnp.sum(jnp.log(jnp.diagonal(lt2, axis1=-2,
                                               axis2=-1)), -1)
        return 0.5 * (tr + jnp.sum(sol_mean * sol_mean, -1) - d) \
            + logdet2 - logdet1

    return _op("kl_mvn_mvn", fn, p.loc, p.scale_tril, q.loc,
               q.scale_tril)


@register_kl(Poisson, Poisson)
def _kl_poisson_reg(p, q):
    return p.kl_divergence(q)


@register_kl(Binomial, Binomial)
def _kl_binomial_reg(p, q):
    return p.kl_divergence(q)


@register_kl(Geometric, Geometric)
def _kl_geometric_reg(p, q):
    return p.kl_divergence(q)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_reg(p, q):
    return p.kl_divergence(q)


@register_kl(ContinuousBernoulli, ContinuousBernoulli)
def _kl_cb_reg(p, q):
    return p.kl_divergence(q)
