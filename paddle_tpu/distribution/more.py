"""Distribution long tail: StudentT, MultivariateNormal, Poisson,
Binomial, Multinomial, Geometric, Cauchy, Chi2, ContinuousBernoulli,
ExponentialFamily.

Reference: ``python/paddle/distribution/{student_t,multivariate_normal,
poisson,binomial,multinomial,geometric,cauchy,chi2,
continuous_bernoulli,exponential_family}.py``.  Densities are
closed-form jnp expressions through the op registry (differentiable on
the eager tape); sampling draws from the global Generator key stream.
Discrete entropies enumerate bounded support exactly like the
reference (poisson.py:146, binomial.py:157).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry
from ..ops.random import default_generator

_op = _registry.cached_apply
_gammaln = jax.scipy.special.gammaln
_digamma = jax.scipy.special.digamma


def _host(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


from . import Distribution, Gamma, _raw, _shape, _t  # noqa: E402


class StudentT(Distribution):
    """Student's t (reference student_t.py)."""

    def __init__(self, df, loc, scale, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(
            tuple(self.df.shape), tuple(self.loc.shape),
            tuple(self.scale.shape)))

    @property
    def mean(self):
        def fn(df, loc, shape):
            return jnp.broadcast_to(
                jnp.where(df > 1, loc, jnp.nan), shape)

        return _op("student_t_mean", fn, self.df, self.loc,
                   shape=self.batch_shape)

    @property
    def variance(self):
        def fn(df, sc, shape):
            var = jnp.where(
                df > 2, sc * sc * df / (df - 2),
                jnp.where(df > 1, jnp.inf, jnp.nan))
            return jnp.broadcast_to(var, shape)

        return _op("student_t_variance", fn, self.df, self.scale,
                   shape=self.batch_shape)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        e = jax.random.t(default_generator.next_key(), _raw(self.df),
                         s, jnp.float32)
        return Tensor(e * _raw(self.scale) + _raw(self.loc))

    def log_prob(self, value):
        def fn(df, loc, sc, v):
            z = (v - loc) / sc
            return (_gammaln((df + 1) / 2) - _gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(sc)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return _op("student_t_log_prob", fn, self.df, self.loc,
                   self.scale, _t(value))

    def entropy(self):
        def fn(df, sc, shape):
            h = (jnp.log(sc) + (df + 1) / 2
                 * (_digamma((df + 1) / 2) - _digamma(df / 2))
                 + 0.5 * jnp.log(df) + _gammaln(df / 2)
                 + _gammaln(0.5) - _gammaln((df + 1) / 2))
            return jnp.broadcast_to(h, shape)

        return _op("student_t_entropy", fn, self.df, self.scale,
                   shape=self.batch_shape)


class MultivariateNormal(Distribution):
    """Multivariate normal over the last axis (reference
    multivariate_normal.py) parameterized by exactly one of
    covariance_matrix / precision_matrix / scale_tril."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        given = [m is not None for m in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError(
                "exactly one of covariance_matrix, precision_matrix, "
                "scale_tril must be specified")
        self.loc = _t(loc)
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        elif covariance_matrix is not None:
            self.covariance_matrix = _t(covariance_matrix)
            self.scale_tril = _op(
                "mvn_chol", lambda c: jnp.linalg.cholesky(c),
                self.covariance_matrix)
        else:
            self.precision_matrix = _t(precision_matrix)

            def fn(p):
                # cov = P^-1; stable via cholesky of the flipped matrix
                # (torch/paddle trick): chol(P^-1) from chol(P).
                lp = jnp.linalg.cholesky(p)
                eye = jnp.broadcast_to(
                    jnp.eye(p.shape[-1], dtype=p.dtype), p.shape)
                linv = jax.scipy.linalg.solve_triangular(
                    lp, eye, lower=True)
                return jnp.linalg.cholesky(
                    jnp.swapaxes(linv, -1, -2) @ linv)

            self.scale_tril = _op("mvn_prec_chol", fn,
                                  self.precision_matrix)
        event = tuple(self.loc.shape)[-1:]
        batch = jnp.broadcast_shapes(tuple(self.loc.shape)[:-1],
                                     tuple(self.scale_tril.shape)[:-2])
        super().__init__(batch, event)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _op("mvn_variance",
                   lambda lt: jnp.sum(lt * lt, axis=-1),
                   self.scale_tril)

    def rsample(self, shape=()):
        s = (_shape(shape) + self.batch_shape + self.event_shape)
        eps = jax.random.normal(default_generator.next_key(), s,
                                jnp.float32)

        def fn(loc, lt, e):
            return loc + jnp.einsum("...ij,...j->...i", lt, e)

        return _op("mvn_rsample", fn, self.loc, self.scale_tril,
                   Tensor(eps))

    def log_prob(self, value):
        def fn(loc, lt, v):
            diff = v - loc
            sol = jax.scipy.linalg.solve_triangular(
                jnp.broadcast_to(
                    lt, diff.shape[:-1] + lt.shape[-2:]),
                diff[..., None], lower=True)[..., 0]
            m = jnp.sum(sol * sol, -1)
            half_logdet = jnp.sum(jnp.log(
                jnp.diagonal(lt, axis1=-2, axis2=-1)), -1)
            d = v.shape[-1]
            return (-0.5 * (m + d * math.log(2 * math.pi))
                    - half_logdet)

        return _op("mvn_log_prob", fn, self.loc, self.scale_tril,
                   _t(value))

    def entropy(self):
        def fn(lt, shape):
            d = lt.shape[-1]
            half_logdet = jnp.sum(jnp.log(
                jnp.diagonal(lt, axis1=-2, axis2=-1)), -1)
            h = 0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet
            return jnp.broadcast_to(h, shape)

        return _op("mvn_entropy", fn, self.scale_tril,
                   shape=self.batch_shape)

    def kl_divergence(self, other):
        from . import kl_divergence as _kl

        return _kl(self, other)


class Poisson(Distribution):
    """Poisson(rate) (reference poisson.py)."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        out = jax.random.poisson(default_generator.next_key(),
                                 _raw(self.rate), s)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def fn(r, v):
            return v * jnp.log(r) - r - _gammaln(v + 1)

        return _op("poisson_log_prob", fn, self.rate, _t(value))

    def _support_upper(self):
        # reference poisson.py _enumerate_bounded_support: rate + 30
        # stddevs covers the mass to fp32 precision.
        r = float(np.max(_host(self.rate)))
        return max(int(r + 30 * math.sqrt(max(r, 1.0))), 30)

    def entropy(self):
        upper = self._support_upper()

        def fn(r, upper):
            v = jnp.arange(upper, dtype=r.dtype).reshape(
                (-1,) + (1,) * r.ndim)
            lp = v * jnp.log(r) - r - _gammaln(v + 1)
            ent = -jnp.sum(jnp.exp(lp) * lp, 0)
            return jnp.where(r != 0, ent, 0.0)

        return _op("poisson_entropy", fn, self.rate, upper=upper)

    def kl_divergence(self, other):
        def fn(r1, r2):
            return r1 * (jnp.log(r1) - jnp.log(r2)) - r1 + r2

        return _op("kl_poisson_poisson", fn, self.rate, other.rate)


class Binomial(Distribution):
    """Binomial(total_count, probs) (reference binomial.py)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(jnp.broadcast_shapes(
            tuple(self.total_count.shape), tuple(self.probs.shape)))

    @property
    def mean(self):
        return _op("binomial_mean", lambda n, p: n * p,
                   self.total_count, self.probs)

    @property
    def variance(self):
        return _op("binomial_variance", lambda n, p: n * p * (1 - p),
                   self.total_count, self.probs)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        # jax's binomial sampler clamps with bare float literals, which
        # lower as f64 under global x64 and trip lax.clamp's strict dtype
        # check against its f32 intermediates.  Trace it with x64 off
        # (the sample is returned as f32 regardless).
        n = _raw(self.total_count).astype(jnp.float32)
        p = _raw(self.probs).astype(jnp.float32)
        with jax.enable_x64(False):
            out = jax.random.binomial(default_generator.next_key(), n, p, s)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def fn(n, p, v):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return (_gammaln(n + 1) - _gammaln(v + 1)
                    - _gammaln(n - v + 1) + v * jnp.log(pc)
                    + (n - v) * jnp.log1p(-pc))

        return _op("binomial_log_prob", fn, self.total_count,
                   self.probs, _t(value))

    def entropy(self):
        upper = int(np.max(_host(self.total_count))) + 1

        def fn(n, p, upper):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            v = jnp.arange(upper, dtype=p.dtype).reshape(
                (-1,) + (1,) * jnp.broadcast_shapes(
                    jnp.shape(n), jnp.shape(p)).__len__())
            lp = (_gammaln(n + 1) - _gammaln(v + 1)
                  - _gammaln(n - v + 1) + v * jnp.log(pc)
                  + (n - v) * jnp.log1p(-pc))
            lp = jnp.where(v <= n, lp, -jnp.inf)
            pmf = jnp.exp(lp)
            return -jnp.sum(pmf * jnp.where(jnp.isfinite(lp), lp, 0.0),
                            0)

        return _op("binomial_entropy", fn, self.total_count, self.probs,
                   upper=upper)

    def kl_divergence(self, other):
        def fn(n, p1, p2):
            eps = 1e-7
            a = jnp.clip(p1, eps, 1 - eps)
            b = jnp.clip(p2, eps, 1 - eps)
            return n * (a * (jnp.log(a) - jnp.log(b))
                        + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))

        return _op("kl_binomial_binomial", fn, self.total_count,
                   self.probs, other.probs)


class Multinomial(Distribution):
    """Multinomial(total_count, probs) over the last axis (reference
    multinomial.py)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shp = tuple(self.probs.shape)
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        n = self.total_count

        return _op("multinomial_mean",
                   lambda p, n: n * (p / jnp.sum(p, -1, keepdims=True)),
                   self.probs, n=n)

    @property
    def variance(self):
        n = self.total_count

        def fn(p, n):
            pn = p / jnp.sum(p, -1, keepdims=True)
            return n * pn * (1 - pn)

        return _op("multinomial_variance", fn, self.probs, n=n)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        p = _raw(self.probs)
        p = p / jnp.sum(p, -1, keepdims=True)
        k = p.shape[-1]
        draws = jax.random.categorical(
            default_generator.next_key(), jnp.log(p),
            shape=(self.total_count,) + s)
        counts = jax.nn.one_hot(draws, k, dtype=jnp.float32).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        n = self.total_count

        def fn(p, v, n):
            pn = p / jnp.sum(p, -1, keepdims=True)
            logits = jnp.log(jnp.clip(pn, 1e-12))
            return (_gammaln(jnp.asarray(n + 1.0))
                    - jnp.sum(_gammaln(v + 1), -1)
                    + jnp.sum(v * logits, -1))

        return _op("multinomial_log_prob", fn, self.probs, _t(value),
                   n=n)

    def entropy(self):
        n = self.total_count

        def fn(p, n):
            pn = p / jnp.sum(p, -1, keepdims=True)
            logits = jnp.log(jnp.clip(pn, 1e-12))
            cat_ent = -jnp.sum(pn * logits, -1)
            # reference multinomial.py:173 — n*H(cat) - lgamma(n+1)
            # + sum_k E[lgamma(x_k + 1)] via binomial marginals.
            support = jnp.arange(1, n + 1, dtype=p.dtype).reshape(
                (-1,) + (1,) * pn.ndim)
            nn = jnp.asarray(float(n), p.dtype)
            lp = (_gammaln(nn + 1) - _gammaln(support + 1)
                  - _gammaln(nn - support + 1)
                  + support * logits
                  + (nn - support) * jnp.log1p(-jnp.clip(pn, 0, 1 - 1e-7)))
            binom_pmf = jnp.exp(lp)
            return (nn * cat_ent - _gammaln(nn + 1)
                    + jnp.sum(binom_pmf * _gammaln(support + 1),
                              axis=(0, -1)))

        return _op("multinomial_entropy", fn, self.probs, n=n)


class Geometric(Distribution):
    """Geometric(probs): pmf (1-p)^k p on k = 0, 1, ... (reference
    geometric.py)."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return _op("geometric_mean", lambda p: 1.0 / p - 1.0,
                   self.probs)

    @property
    def variance(self):
        return _op("geometric_variance",
                   lambda p: (1.0 / p - 1.0) / p, self.probs)

    @property
    def stddev(self):
        from .. import ops

        return ops.sqrt(self.variance)

    def pmf(self, k):
        from .. import ops

        return ops.exp(self.log_pmf(k))

    def log_pmf(self, k):
        def fn(p, k):
            return k * jnp.log1p(-p) + jnp.log(p)

        return _op("geometric_log_pmf", fn, self.probs, _t(k))

    log_prob = log_pmf

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(default_generator.next_key(), s,
                               jnp.float32, 1e-7, 1.0)
        return _op("geometric_rsample",
                   lambda p, u: jnp.floor(jnp.log(u) / jnp.log1p(-p)),
                   self.probs, Tensor(u))

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def entropy(self):
        def fn(p):
            return -((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p

        return _op("geometric_entropy", fn, self.probs)

    def cdf(self, k):
        def fn(p, k):
            return 1 - jnp.power(1 - p, k + 1)

        return _op("geometric_cdf", fn, self.probs, _t(k))

    def kl_divergence(self, other):
        def fn(p1, p2):
            return (jnp.log(p1) - jnp.log(p2)
                    + (1 - p1) / p1
                    * (jnp.log1p(-p1) - jnp.log1p(-p2)))

        return _op("kl_geometric_geometric", fn, self.probs,
                   other.probs)


class Cauchy(Distribution):
    """Cauchy(loc, scale) (reference cauchy.py).  mean/variance are
    undefined and raise, matching the reference."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(tuple(self.loc.shape),
                                              tuple(self.scale.shape)))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean.")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance.")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev.")

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(default_generator.next_key(), s,
                               jnp.float32, 1e-7, 1.0 - 1e-7)
        return _op("cauchy_rsample",
                   lambda l, sc, u: l + sc * jnp.tan(
                       math.pi * (u - 0.5)),
                   self.loc, self.scale, Tensor(u))

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def log_prob(self, value):
        def fn(l, sc, v):
            z = (v - l) / sc
            return (-math.log(math.pi) - jnp.log(sc)
                    - jnp.log1p(z * z))

        return _op("cauchy_log_prob", fn, self.loc, self.scale,
                   _t(value))

    def cdf(self, value):
        def fn(l, sc, v):
            return jnp.arctan((v - l) / sc) / math.pi + 0.5

        return _op("cauchy_cdf", fn, self.loc, self.scale, _t(value))

    def entropy(self):
        def fn(sc, shape):
            return jnp.broadcast_to(
                jnp.log(4 * math.pi * sc), shape)

        return _op("cauchy_entropy", fn, self.scale,
                   shape=self.batch_shape)

    def kl_divergence(self, other):
        # closed form (Chyzak & Nielsen 2019), as the reference cites.
        def fn(l1, s1, l2, s2):
            t1 = jnp.square(s1 + s2) + jnp.square(l1 - l2)
            return jnp.log(t1 / (4 * s1 * s2))

        return _op("kl_cauchy_cauchy", fn, self.loc, self.scale,
                   other.loc, other.scale)


class Chi2(Gamma):
    """Chi-squared = Gamma(df/2, rate=1/2) (reference chi2.py)."""

    def __init__(self, df, name=None):
        df_t = _t(df)
        from .. import ops

        half = Tensor(jnp.full(tuple(df_t.shape) or (), 0.5,
                               jnp.float32))
        super().__init__(ops.scale(df_t, 0.5), half)
        self.df = df_t


def _cb_cut(p, lims):
    return (p < lims[0]) | (p > lims[1])


def _cb_log_norm(p, lims):
    # log C(p); taylor-expand near p=0.5 like the reference.
    cut = _cb_cut(p, lims)
    safe = jnp.where(cut, p, 0.499)
    log_c = jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * safe))
                    / jnp.abs(1 - 2 * safe))
    x = p - 0.5
    taylor = math.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x * x) * x * x
    return jnp.where(cut, log_c, taylor)


class ContinuousBernoulli(Distribution):
    """Continuous Bernoulli (reference continuous_bernoulli.py):
    density p^x (1-p)^(1-x) C(p) on [0, 1].  The lims window selects
    the taylor expansion of the normalizer near p=0.5.

    (``cached_apply`` shares one OpDef per code object, so the math
    helpers take ``lims`` as a static attr instead of closing over
    ``self`` — a closure would bake the first instance's lims into the
    shared op.)"""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = tuple(float(v) for v in lims)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        def fn(p, lims):
            cut = _cb_cut(p, lims)
            safe = jnp.where(cut, p, 0.499)
            m = safe / (2 * safe - 1) + 1 / (
                2 * jnp.arctanh(1 - 2 * safe))
            x = p - 0.5
            taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x * x) * x
            return jnp.where(cut, m, taylor)

        return _op("cb_mean", fn, self.probs, lims=self._lims)

    @property
    def variance(self):
        def fn(p, lims):
            cut = _cb_cut(p, lims)
            safe = jnp.where(cut, p, 0.499)
            t = jnp.square((1 - 2 * safe) * jnp.arctanh(1 - 2 * safe))
            v = safe * (safe - 1) / jnp.square(1 - 2 * safe) + 1 / t
            x = p - 0.5
            taylor = 1.0 / 12.0 - (1.0 / 15.0 - 128.0 / 945.0 * x * x) \
                * x * x
            return jnp.where(cut, v, taylor)

        return _op("cb_variance", fn, self.probs, lims=self._lims)

    def rsample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(default_generator.next_key(), s,
                               jnp.float32, 1e-6, 1.0 - 1e-6)

        def fn(p, u, lims):
            cut = _cb_cut(p, lims)
            safe = jnp.where(cut, p, 0.499)
            smp = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                   / (jnp.log(safe) - jnp.log1p(-safe)))
            return jnp.where(cut, smp, u)

        return _op("cb_rsample", fn, self.probs, Tensor(u),
                   lims=self._lims)

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def log_prob(self, value):
        def fn(p, v, lims):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return (v * jnp.log(pc) + (1 - v) * jnp.log1p(-pc)
                    + _cb_log_norm(pc, lims))

        return _op("cb_log_prob", fn, self.probs, _t(value),
                   lims=self._lims)

    def cdf(self, value):
        def fn(p, v, lims):
            cut = _cb_cut(p, lims)
            safe = jnp.where(cut, p, 0.499)
            c = ((jnp.power(safe, v) * jnp.power(1 - safe, 1 - v)
                  + safe - 1) / (2 * safe - 1))
            out = jnp.where(cut, c, v)
            return jnp.clip(out, 0.0, 1.0)

        return _op("cb_cdf", fn, self.probs, _t(value),
                   lims=self._lims)

    def entropy(self):
        # H = -E[log p(X)] = -(E[X] log p + (1-E[X]) log(1-p) + log C)
        def fn(p, m, lims):
            eps = 1e-7
            pc = jnp.clip(p, eps, 1 - eps)
            return -(m * jnp.log(pc) + (1 - m) * jnp.log1p(-pc)
                     + _cb_log_norm(pc, lims))

        return _op("cb_entropy", fn, self.probs, self.mean,
                   lims=self._lims)

    def kl_divergence(self, other):
        def fn(p1, p2, m, lims):
            eps = 1e-7
            a = jnp.clip(p1, eps, 1 - eps)
            b = jnp.clip(p2, eps, 1 - eps)
            return (m * (jnp.log(a) - jnp.log(b))
                    + (1 - m) * (jnp.log1p(-a) - jnp.log1p(-b))
                    + _cb_log_norm(a, lims) - _cb_log_norm(b, lims))

        return _op("kl_cb_cb", fn, self.probs, other.probs, self.mean,
                   lims=self._lims)


class ExponentialFamily(Distribution):
    """Base class marking exponential-family distributions (reference
    exponential_family.py); entropy via the Bregman divergence of the
    log-normalizer is provided by subclasses' closed forms here."""


class LKJCholesky(Distribution):
    """LKJ distribution over Cholesky factors of correlation matrices
    (reference lkj_cholesky.py; onion-method sampling)."""

    def __init__(self, dim, concentration=1.0, sample_method="onion",
                 name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError("sample_method must be 'onion' or 'cvine'")
        self.dim = int(dim)
        self.concentration = _t(concentration)
        self.sample_method = sample_method
        super().__init__(tuple(self.concentration.shape),
                         (self.dim, self.dim))

    def sample(self, shape=()):
        if self.sample_method == "cvine":
            return self._sample_cvine(shape)
        return self._sample_onion(shape)

    def _sample_onion(self, shape):
        """Onion method (Ghosh & Henderson 2003): row i+1's direction
        is uniform on the sphere with Beta-distributed radius."""
        d = self.dim
        s = _shape(shape) + self.batch_shape
        eta = _raw(self.concentration)
        key = default_generator.next_key()
        k1, k2 = jax.random.split(key)
        # beta samples: r_i^2 ~ Beta((i+1)/2, eta + (d - 2 - i)/2)
        L = jnp.zeros(s + (d, d), jnp.float32).at[..., 0, 0].set(1.0)
        for i in range(1, d):
            ki = jax.random.fold_in(k1, i)
            a = i / 2.0
            b = eta + (d - 1 - i) / 2.0
            r2 = jax.random.beta(ki, a, jnp.broadcast_to(b, s))
            u = jax.random.normal(jax.random.fold_in(k2, i),
                                  s + (i,), jnp.float32)
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(r2)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.maximum(1.0 - r2,
                                                         1e-12)))
        return Tensor(L)

    def _sample_cvine(self, shape):
        """C-vine method (LKJ 2009): partial correlations
        p_ij ~ 2 Beta(b_j, b_j) - 1 with b_j = eta + (d - 2 - j) / 2,
        mapped to the Cholesky factor row-wise."""
        d = self.dim
        s = _shape(shape) + self.batch_shape
        eta = _raw(self.concentration)
        key = default_generator.next_key()
        L = jnp.zeros(s + (d, d), jnp.float32).at[..., 0, 0].set(1.0)
        for i in range(1, d):
            rem = jnp.ones(s, jnp.float32)  # prod sqrt(1 - p^2) so far
            for j in range(i):
                kij = jax.random.fold_in(key, i * d + j)
                b = eta + (d - 2 - j) / 2.0
                bb = jnp.broadcast_to(b, s)
                p = 2.0 * jax.random.beta(kij, bb, bb) - 1.0
                L = L.at[..., i, j].set(p * rem)
                rem = rem * jnp.sqrt(jnp.maximum(1.0 - p * p, 1e-12))
            L = L.at[..., i, i].set(rem)
        return Tensor(L)

    def log_prob(self, value):
        """Density over the diagonal (reference lkj_cholesky
        log_prob): sum_i (d - i - 1 + 2(eta - 1)) log L_ii minus the
        log normalizer (product of Beta functions).  ``dim`` rides as
        a static attr — cached_apply shares one OpDef per code object,
        so a closure over self.dim would bake the first instance's
        dimension into the shared op."""

        def fn(eta, L, d):
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            order = jnp.arange(2, d + 1, dtype=jnp.float32)
            unnorm = jnp.sum(
                (d - order + 2.0 * eta[..., None] - 2.0)
                * jnp.log(diag), -1)
            # log normalizer (Stan reference formulation)
            i = jnp.arange(1, d, dtype=jnp.float32)
            alpha = eta[..., None] + (d - 1 - i) / 2.0
            lnorm = jnp.sum(
                0.5 * i * jnp.log(jnp.pi)
                + _gammaln(alpha)
                - _gammaln(alpha + i / 2.0), -1)
            return unnorm - lnorm

        return _op("lkj_log_prob", fn, self.concentration, _t(value),
                   d=self.dim)
