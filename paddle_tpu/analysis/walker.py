"""Recursive jaxpr traversal + inventory primitives.

One walker for the whole subsystem: every check (and the tests that
migrated off their private copies) goes through :func:`iter_eqns`, which
descends into sub-jaxprs wherever they hide in ``eqn.params`` —
``ClosedJaxpr`` values (pjit/scan/custom_vjp/shard_map/remat), raw
``Jaxpr`` values, and lists/tuples of either (cond branches).
"""
from __future__ import annotations

import numpy as np

# Collective primitives audited per shard_map body.  jax lowers pmean
# to psum+div and names the bound-axis psum "psum2" in recent versions;
# the inventory normalizes both spellings to "psum" so contracts stay
# version-stable.
COLLECTIVE_PRIMS = {
    "psum", "psum2", "all_to_all", "all_gather", "all_gather_invariant",
    "reduce_scatter", "ppermute", "pmax", "pmin",
    # NB: shard_map's `pbroadcast` is a replication-annotation cast, not
    # a wire collective — deliberately excluded.
}
_NORMALIZE = {"psum2": "psum"}

# Primitives that force a host round-trip inside a device program.
HOST_SYNC_PRIMS = {
    "debug_callback", "pure_callback", "io_callback", "callback",
    "outside_call", "host_callback", "infeed", "outfeed", "debug_print",
}


def _as_jaxpr(obj):
    """Jaxpr-or-None from a params value (ClosedJaxpr has .jaxpr.eqns,
    raw Jaxpr has .eqns directly)."""
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    if hasattr(obj, "eqns"):
        return obj
    return None


def sub_jaxprs(eqn):
    """Yield every sub-jaxpr reachable from one equation's params."""
    for v in eqn.params.values():
        for cand in (v if isinstance(v, (list, tuple)) else [v]):
            j = _as_jaxpr(cand)
            if j is not None:
                yield j


def iter_eqns(jaxpr):
    """Depth-first over ALL equations, descending into sub-jaxprs."""
    j = _as_jaxpr(jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _aval_elems(aval):
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape or (1,)))


def _aval_nbytes(aval):
    dt = getattr(aval, "dtype", None)
    itemsize = np.dtype(dt).itemsize if dt is not None else 1
    return _aval_elems(aval) * itemsize


def iter_vars(jaxpr):
    """(eqn, var, aval) for every in/out variable of every equation."""
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                yield eqn, v, aval


def max_intermediate_elems(jaxpr):
    """Largest array (element count) anywhere in the jaxpr tree — the
    generalization of the old test-local ``_max_var_size`` walkers."""
    best = 0
    for _, _, aval in iter_vars(jaxpr):
        best = max(best, _aval_elems(aval))
    return best


def max_intermediate_bytes(jaxpr):
    """(nbytes, shape, dtype, primitive_name) of the largest array."""
    best = (0, (), None, None)
    for eqn, _, aval in iter_vars(jaxpr):
        nb = _aval_nbytes(aval)
        if nb > best[0]:
            best = (nb, tuple(aval.shape), aval.dtype, eqn.primitive.name)
    return best


def primitive_inventory(jaxpr):
    """{primitive_name: count} over the whole tree."""
    inv: dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        n = eqn.primitive.name
        inv[n] = inv.get(n, 0) + 1
    return inv


def collective_inventory(jaxpr):
    """{collective: count} with version normalization (psum2 -> psum)."""
    inv: dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        n = eqn.primitive.name
        if n in COLLECTIVE_PRIMS:
            n = _NORMALIZE.get(n, n)
            inv[n] = inv.get(n, 0) + 1
    return inv


def name_inventory(jaxpr):
    """Set of name-ish strings in the tree: primitive names, ``name``
    params (pjit bodies), and pallas kernel src markers — the structured
    replacement for ``assert "..." in str(jaxpr)``."""
    names: set[str] = set()
    for eqn in iter_eqns(jaxpr):
        names.add(eqn.primitive.name)
        for key in ("name", "name_and_src_info"):
            v = eqn.params.get(key)
            if v is not None:
                names.add(v if isinstance(v, str) else str(v))
    return names
