"""Pluggable jaxpr-level checks evaluated against a ProgramContract.

Each check is stateless: ``run(contract, closed_jaxpr) -> [Violation]``.
A check that the contract does not configure (ceiling unset, no
expected collectives, ...) returns no violations — contracts opt into
exactly the invariants they can promise.  The sixth check of the suite,
the retrace/dispatch audit, is runtime-side and lives in ``audit.py``.
"""
from __future__ import annotations

import numpy as np

from .contract import ProgramContract, Violation
from . import walker


class Check:
    name = "check"

    def run(self, contract: ProgramContract, jaxpr) -> list[Violation]:
        raise NotImplementedError

    def _v(self, contract, msg):
        return Violation(contract.name, self.name, msg)


class DenseMaterializationCheck(Check):
    """No intermediate at or above the contract's byte ceiling — the
    generalization of the MoE dense-[T,E,C]-mask assertion."""

    name = "dense-materialization"

    def run(self, contract, jaxpr):
        ceil = contract.max_intermediate_bytes
        if ceil is None:
            return []
        nb, shape, dtype, prim = walker.max_intermediate_bytes(jaxpr)
        if nb >= ceil:
            return [self._v(
                contract,
                f"intermediate {list(shape)} {dtype} ({nb} bytes, from "
                f"'{prim}') reaches the declared ceiling of {ceil} "
                f"bytes")]
        return []


class HostSyncCheck(Check):
    """No callback/infeed primitive inside a step program: every one is
    a device->host round-trip serialized into the step."""

    name = "host-sync"

    def run(self, contract, jaxpr):
        if contract.allow_host_sync:
            return []
        inv = walker.primitive_inventory(jaxpr)
        out = []
        for prim in sorted(set(inv) & walker.HOST_SYNC_PRIMS):
            out.append(self._v(
                contract,
                f"{inv[prim]} '{prim}' equation(s) force a host sync "
                f"inside the program (set allow_host_sync=True only "
                f"for debug programs)"))
        return out


class DonationMissCheck(Check):
    """A large input whose (shape, dtype) is re-emitted as an output
    should be donated — XLA then updates it in place instead of holding
    both copies live (the KV-pool / optimizer-state pattern)."""

    name = "donation-miss"

    def run(self, contract, jaxpr):
        if contract.donation_floor_bytes is None:
            return []  # donation N/A (eager-dispatched op: inputs are
            # live Tensor buffers, aliasing would corrupt them)
        avals, donated = contract.flat_input_layout()
        if avals is None:
            return []
        # Claim one output per donated input first, so an aliasable
        # output can't be double-counted against an undonated input.
        outs = []
        for aval in jaxpr.out_avals:
            if hasattr(aval, "shape") and hasattr(aval, "dtype"):
                outs.append((tuple(aval.shape), np.dtype(aval.dtype)))
        for aval, don in zip(avals, donated):
            if don and hasattr(aval, "shape"):
                key = (tuple(aval.shape), np.dtype(aval.dtype))
                if key in outs:
                    outs.remove(key)
        viols = []
        for idx, (aval, don) in enumerate(zip(avals, donated)):
            if don or not hasattr(aval, "shape"):
                continue
            key = (tuple(aval.shape), np.dtype(aval.dtype))
            nbytes = int(np.prod(key[0] or (1,))) * key[1].itemsize
            if nbytes < contract.donation_floor_bytes:
                continue
            if key in outs:
                outs.remove(key)
                viols.append(self._v(
                    contract,
                    f"input leaf #{idx} {list(key[0])} {key[1]} "
                    f"({nbytes} bytes) is re-emitted as a same-shaped "
                    f"output but not donated — add it to "
                    f"donate_argnums so XLA can alias the buffer"))
        return viols


class DtypeUpcastCheck(Check):
    """In a bf16/f16 program, f32 intermediates above the size floor
    are unintended upcasts (the floor exempts scalar losses, norms and
    softmax statistics, which upcast on purpose)."""

    name = "dtype-upcast"

    def run(self, contract, jaxpr):
        cd = contract.compute_dtype
        if cd is None:
            return []
        cd = np.dtype(cd)
        if cd.itemsize >= 4:
            return []
        viols = []
        seen = set()
        for eqn, v, aval in walker.iter_vars(jaxpr):
            if v in eqn.invars:
                continue  # flag the producing equation once
            dt = np.dtype(aval.dtype) if hasattr(aval, "dtype") else None
            if dt is None or dt.kind != "f" or dt.itemsize < 4:
                continue
            nb = int(np.prod(aval.shape or (1,))) * dt.itemsize
            key = (tuple(aval.shape), dt, eqn.primitive.name)
            if nb >= contract.f32_floor_bytes and key not in seen:
                seen.add(key)
                viols.append(self._v(
                    contract,
                    f"{dt} intermediate {list(aval.shape)} ({nb} bytes, "
                    f"from '{eqn.primitive.name}') in a {cd} program — "
                    f"unintended upcast above the "
                    f"{contract.f32_floor_bytes}-byte floor"))
        return viols


class CollectiveAuditCheck(Check):
    """Exact collective inventory per program: a refactor that silently
    adds (or drops) an all-to-all/psum fails lint until the contract is
    updated on purpose."""

    name = "collective-audit"

    def run(self, contract, jaxpr):
        expected = contract.expected_collectives
        if expected is None:
            return []
        expected = {k: int(v) for k, v in expected.items() if int(v)}
        actual = walker.collective_inventory(jaxpr)
        if actual != expected:
            return [self._v(
                contract,
                f"collective inventory drifted: expected {expected!r}, "
                f"traced {actual!r}")]
        return []


DEFAULT_CHECKS: tuple = (
    DenseMaterializationCheck(),
    HostSyncCheck(),
    DonationMissCheck(),
    DtypeUpcastCheck(),
    CollectiveAuditCheck(),
)
