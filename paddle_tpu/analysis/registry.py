"""ProgramRegistry — every hot program's contract, in one table.

The train step, the five serving executor programs and the fused-MoE
shard_map body register here at build time.  ``PT_LINT`` gates what
registration does:

* ``off``  (default) — store only; ``make lint-graph`` /
  ``lint_all()`` lint on demand.
* ``warn`` — lint at registration, report violations as warnings.
* ``error`` — lint at registration, raise GraphContractError.

Registration is replace-by-name (rebuilding an engine re-registers its
programs) and entries hold their program weakly — a dead owner's entry
is dropped at the next lint sweep, so the registry never pins model
state.
"""
from __future__ import annotations

import os
import warnings

from .checks import DEFAULT_CHECKS
from .contract import GraphContractError, LintReport, ProgramContract

_REGISTRY: dict[str, ProgramContract] = {}

_MODES = ("off", "warn", "error")


def lint_mode() -> str:
    mode = os.environ.get("PT_LINT", "off").strip().lower()
    if mode not in _MODES:
        raise ValueError(
            f"PT_LINT must be one of {_MODES}, got {mode!r}")
    return mode


def lint_contract(contract: ProgramContract, *, checks=None,
                  hlo=False) -> LintReport:
    """Lint one contract (registered or not).  ``hlo=True`` adds the
    lowered-HLO host-sync scan on top of the jaxpr checks."""
    report = LintReport()
    jaxpr = contract.make_jaxpr()
    if jaxpr is None:
        report.skipped.append(contract.name)
        return report
    report.linted.append(contract.name)
    for check in (checks if checks is not None else DEFAULT_CHECKS):
        report.violations.extend(check.run(contract, jaxpr))
    if hlo and not contract.allow_host_sync:
        # Callbacks lower to custom_call @xla_python_*_callback (and
        # host transfers to send/recv-to-host ops) — scanning the
        # lowered text catches a host sync even if a future jax version
        # renames the jaxpr-level primitive.
        text = contract.lower_text()
        if text is not None:
            from .contract import Violation

            for marker in ("_callback", "send_to_host",
                           "recv_from_host"):
                if marker in text:
                    report.violations.append(Violation(
                        contract.name, "host-sync",
                        f"lowered HLO contains a '{marker}' call — "
                        f"host round-trip survives lowering"))
    return report


def register_program(contract: ProgramContract, *, replace=True):
    """Register (or replace) a program contract; under PT_LINT=warn/
    error the program is linted immediately (skipped silently when its
    lazy args are not captured yet)."""
    if not replace and contract.name in _REGISTRY:
        raise ValueError(f"program {contract.name!r} already registered")
    _REGISTRY[contract.name] = contract
    mode = lint_mode()
    if mode == "off":
        return contract
    report = lint_contract(contract)
    if report.violations:
        if mode == "error":
            raise GraphContractError(str(report))
        warnings.warn(str(report), stacklevel=2)
    return contract


def unregister_program(name: str):
    _REGISTRY.pop(name, None)


def registered() -> dict:
    return dict(_REGISTRY)


def lint_program(name: str, *, hlo=False) -> LintReport:
    return lint_contract(_REGISTRY[name], hlo=hlo)


def aot_warmup() -> dict:
    """Sweep every registered contract's ``aot_hook`` — checkpoint
    restore calls this so a rolled-back replica resumes with warmed
    executables.  Hooks are deduplicated by resolved callable (the six
    serving programs all point at one ``PagedExecutor.aot_warmup``
    bound method) and a dead owner's entry is skipped, not failed.
    Returns {contract name: hook result} for the hooks that ran."""
    out, ran = {}, set()
    for name, contract in list(_REGISTRY.items()):
        hook = contract.resolve_aot_hook()
        if hook is None:
            continue
        ident = (id(getattr(hook, "__self__", hook)),
                 id(getattr(hook, "__func__", hook)))
        if ident in ran:
            continue
        ran.add(ident)
        out[name] = hook()
    return out


def lint_all(*, hlo=False) -> LintReport:
    """Lint every registered program; entries whose program has been
    garbage-collected are dropped, not failed."""
    report = LintReport()
    for name, contract in list(_REGISTRY.items()):
        if contract.resolve_fn() is None:
            del _REGISTRY[name]
            continue
        report.merge(lint_contract(contract, hlo=hlo))
    return report
