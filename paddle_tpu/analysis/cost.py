"""Analytical jaxpr cost model — FLOPs, HBM bytes, arithmetic intensity.

The reference profiler ships op-level FLOP/memory statistics; the
jax_graft analog walks a program's jaxpr (through ``pjit``/``scan``/
``custom_vjp``/``shard_map`` sub-jaxprs, same recursion contract as the
linter checks) and prices every equation:

* ``dot_general`` — ``2 · batch · lhs_free · rhs_free · contract`` from
  ``dimension_numbers`` and the operand avals;
* ``conv_general_dilated`` — ``2 · out_elems · kernel_elems / C_out``
  (each output element contracts one kernel's worth of inputs per
  output channel);
* elementwise arithmetic / reductions — one FLOP per element (output
  elements for maps, input elements for reductions), over an explicit
  primitive set so the count is deterministic across refactors;
* ``scan`` bodies are priced once and multiplied by the trip count
  (``length``); ``while`` bodies are priced for a single iteration (the
  trip count is not static); ``cond`` takes the most expensive branch.

Byte accounting is the roofline numerator: program inputs + outputs
(every train/serve step streams its operands through HBM once) plus the
largest intermediate as a working-set estimate — all via the walker's
``_aval_nbytes``.  ``shard_map`` bodies carry per-shard shapes, so every
figure is per chip, matching the per-chip MFU convention in bench.py.

``transformer_flops_per_token`` hosts the closed-form 6N + attention
estimate that bench.py and the hapi models previously re-derived inline;
keeping one copy here is what lets tests assert bench-vs-cost-model
agreement to the digit.
"""
from __future__ import annotations

import dataclasses

from .walker import _as_jaxpr, _aval_nbytes, sub_jaxprs

#: One FLOP per OUTPUT element.
ELEMENTWISE_FLOP_PRIMS = {
    "add", "sub", "mul", "div", "rem", "max", "min", "neg", "abs",
    "sign", "floor", "ceil", "round", "exp", "exp2", "expm1", "log",
    "log1p", "tanh", "logistic", "sqrt", "rsqrt", "cbrt", "square",
    "pow", "integer_pow", "erf", "erfc", "erf_inv", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "atanh",
    "asinh", "acosh", "nextafter", "clamp", "select_n",
}

#: One FLOP per INPUT element (an n-ary tree reduce is n-1 ops ~= n).
REDUCTION_FLOP_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "argmax", "argmin", "cumsum", "cumprod", "cumlogsumexp", "cummax",
    "cummin", "reduce_precision", "psum", "psum2",
}


def transformer_flops_per_token(num_params, num_layers, hidden_size,
                                seq_len):
    """Megatron-style fwd+bwd FLOPs per token: ``6·N`` for the parameter
    GEMMs plus ``12·L·H·S`` for attention score/value matmuls.  This is
    the single home of the estimate bench.py's MFU legs and the hapi
    models' ``flops_per_token`` share (remat's extra forward is hardware
    overhead, deliberately not counted as useful FLOPs)."""
    return (6 * int(num_params)
            + 12 * int(num_layers) * int(hidden_size) * int(seq_len))


@dataclasses.dataclass
class CostReport:
    """Analytical cost of one program at fixed shapes.

    ``flops`` decomposes into matmul/conv/elementwise; ``hbm_bytes`` is
    inputs + outputs + the largest-intermediate working-set estimate.
    """

    flops: int = 0
    matmul_flops: int = 0
    conv_flops: int = 0
    elementwise_flops: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    bytes_peak_intermediate: int = 0
    eqns: int = 0
    by_primitive: dict = dataclasses.field(default_factory=dict)

    @property
    def hbm_bytes(self) -> int:
        return self.bytes_in + self.bytes_out + self.bytes_peak_intermediate

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per HBM byte — against the machine ridge point this
        classifies the program compute- vs bandwidth-bound."""
        return self.flops / max(self.hbm_bytes, 1)

    def asdict(self) -> dict:
        d = dataclasses.asdict(self)
        d["hbm_bytes"] = self.hbm_bytes
        d["arithmetic_intensity"] = round(self.arithmetic_intensity, 4)
        return d

    def __str__(self):
        return (f"CostReport(flops={self.flops:.3e}, "
                f"hbm_bytes={self.hbm_bytes:.3e}, "
                f"intensity={self.arithmetic_intensity:.1f} flop/B, "
                f"eqns={self.eqns})")


def _prod(it):
    out = 1
    for v in it:
        out *= int(v)
    return out


def _out_elems(eqn):
    aval = getattr(eqn.outvars[0], "aval", None)
    shape = getattr(aval, "shape", None)
    return _prod(shape) if shape is not None else 0


def _in_elems(eqn):
    aval = getattr(eqn.invars[0], "aval", None)
    shape = getattr(aval, "shape", None)
    return _prod(shape) if shape is not None else 0


def _dot_general_flops(eqn):
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = _prod(lhs[i] for i in lb)
    contract = _prod(lhs[i] for i in lc)
    lhs_free = _prod(lhs[i] for i in range(len(lhs))
                     if i not in set(lb) | set(lc))
    rhs_free = _prod(rhs[i] for i in range(len(rhs))
                     if i not in set(_rb) | set(rc))
    return 2 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn):
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    c_out = rhs[dn.rhs_spec[0]]
    kernel_elems = _prod(rhs)
    # Each output element contracts C_in/groups · prod(kernel_spatial)
    # inputs = kernel_elems / C_out (feature_group_count already shrinks
    # the kernel's in-channel dim).
    return 2 * _out_elems(eqn) * (kernel_elems // max(c_out, 1))


class _Acc:
    __slots__ = ("matmul", "conv", "elem", "eqns", "by_prim")

    def __init__(self):
        self.matmul = 0
        self.conv = 0
        self.elem = 0
        self.eqns = 0
        self.by_prim = {}

    def add(self, prim, kind, flops, mult):
        flops = int(flops) * mult
        if kind == "matmul":
            self.matmul += flops
        elif kind == "conv":
            self.conv += flops
        else:
            self.elem += flops
        if flops:
            self.by_prim[prim] = self.by_prim.get(prim, 0) + flops

    @property
    def total(self):
        return self.matmul + self.conv + self.elem

    def merge(self, other):
        self.matmul += other.matmul
        self.conv += other.conv
        self.elem += other.elem
        self.eqns += other.eqns
        for k, v in other.by_prim.items():
            self.by_prim[k] = self.by_prim.get(k, 0) + v


def _walk(jaxpr, mult, acc):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        acc.eqns += 1
        if name == "scan":
            trip = int(eqn.params.get("length", 1))
            for sub in sub_jaxprs(eqn):
                _walk(sub, mult * trip, acc)
            continue
        if name == "cond":
            # Worst-case branch: price each standalone, keep the max.
            best = None
            for sub in sub_jaxprs(eqn):
                branch = _Acc()
                _walk(sub, mult, branch)
                if best is None or branch.total > best.total:
                    best = branch
            if best is not None:
                acc.merge(best)
            continue
        if name == "dot_general":
            acc.add(name, "matmul", _dot_general_flops(eqn), mult)
        elif name == "conv_general_dilated":
            acc.add(name, "conv", _conv_flops(eqn), mult)
        elif name in ELEMENTWISE_FLOP_PRIMS:
            acc.add(name, "elem", _out_elems(eqn), mult)
        elif name in REDUCTION_FLOP_PRIMS:
            acc.add(name, "elem", _in_elems(eqn), mult)
        # pjit / custom_vjp / shard_map / remat / while bodies: same
        # multiplier (a while trip count is not static — priced once).
        for sub in sub_jaxprs(eqn):
            _walk(sub, mult, acc)


def estimate_cost(jaxpr) -> CostReport:
    """Price a ClosedJaxpr (or raw Jaxpr) into a :class:`CostReport`."""
    from .walker import max_intermediate_bytes

    j = _as_jaxpr(jaxpr)
    if j is None:
        raise TypeError(f"not a jaxpr: {type(jaxpr)!r}")
    acc = _Acc()
    _walk(j, 1, acc)
    bytes_in = sum(_aval_nbytes(v.aval)
                   for v in list(j.invars) + list(j.constvars))
    bytes_out = sum(_aval_nbytes(v.aval) for v in j.outvars)
    peak = int(max_intermediate_bytes(jaxpr)[0])
    return CostReport(
        flops=acc.total, matmul_flops=acc.matmul, conv_flops=acc.conv,
        elementwise_flops=acc.elem, bytes_in=int(bytes_in),
        bytes_out=int(bytes_out), bytes_peak_intermediate=peak,
        eqns=acc.eqns, by_primitive=dict(sorted(acc.by_prim.items())))


def estimate_fn_cost(fn, *args, **kwargs) -> CostReport:
    """Convenience: trace ``fn`` at the given example args (arrays or
    ShapeDtypeStructs) and price the resulting jaxpr."""
    import functools

    import jax

    if kwargs:
        fn = functools.partial(fn, **kwargs)
    return estimate_cost(jax.make_jaxpr(fn)(*args))
