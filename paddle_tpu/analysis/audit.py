"""Runtime retrace/dispatch audit — the sixth check.

``CountedJit`` is a drop-in ``jax.jit`` replacement that counts how
many times the wrapped function was TRACED (re-traces mean shape churn)
and how many times it was DISPATCHED — replacing the hand-rolled
``verify_traces``/``verify_dispatches`` counters the serving executor
carried.  ``DispatchAuditor`` is the context manager that asserts the
counts over a block: an extra dispatch (a hidden host loop) or an extra
trace (a shape leak) raises :class:`GraphContractError`.

``CountedJit.aot_compile`` is the AOT plane's entry point: it compiles
the program at an abstract signature (``jax.ShapeDtypeStruct`` leaves —
no real buffers) via ``lower().compile()``, consults the persistent
:class:`~paddle_tpu.core.aot.CompileCache` first, and installs the
executable in a per-program table that ``__call__`` checks before
falling back to the normal jit path.  A table hit NEVER traces; a
``seal()``-ed program (PT_AOT=strict) raises
:class:`~paddle_tpu.core.aot.AotMissError` on a miss instead of
silently compiling mid-traffic.
"""
from __future__ import annotations

import functools
import time
import warnings

import jax

from .. import obs
from .contract import GraphContractError


class CountedJit:
    """jax.jit wrapper with trace/dispatch counters.

    The trace counter is bumped by a host-side effect INSIDE the traced
    body (it runs once per trace, never per dispatch — the same trick
    the executor's verify program used); the dispatch counter is bumped
    per call.  ``fn`` exposes the unjitted callable for ProgramContract
    registration, so the lint path and the execution path share one
    function object.
    """

    def __init__(self, fn, *, name=None, donate_argnums=(),
                 static_argnames=(), **jit_kwargs):
        self.name = name or getattr(fn, "__name__", "program")
        self.traces = 0
        self.dispatches = 0
        self._fn = fn
        self.donate_argnums = tuple(donate_argnums)
        self._obs = obs.handle()
        # AOT executable table: abstract signature -> jax.stages.Compiled
        self._exe = {}
        self._sealed = False
        self.aot_hits = 0
        self.aot_misses = 0

        @functools.wraps(fn)
        def counted(*args, **kwargs):
            self.traces += 1
            h = self._obs
            if h is not None:
                # a (re)trace is the compile event production debugging
                # cares about: journal it and count per program
                h.recorder.record("jit.trace", program=self.name,
                                  traces=self.traces)
                h.registry.counter(
                    "jit_traces_total",
                    "XLA traces (compiles/retraces) per program",
                    labels=("program",)).labels(program=self.name).inc()
            return fn(*args, **kwargs)

        self._jit = jax.jit(counted,
                            donate_argnums=self.donate_argnums,
                            static_argnames=tuple(static_argnames),
                            **jit_kwargs)

    @property
    def fn(self):
        return self._fn

    def __call__(self, *args, **kwargs):
        self.dispatches += 1
        h = self._obs
        if h is not None:
            h.registry.counter(
                "jit_dispatches_total",
                "Jitted program dispatches per program",
                labels=("program",)).labels(program=self.name).inc()
        if self._exe:
            from ..core import aot

            exe = self._exe.get(aot.signature(args, kwargs))
            if exe is not None:
                self.aot_hits += 1
                return exe(*args, **kwargs)
            self.aot_misses += 1
            if self._sealed:
                raise aot.AotMissError(
                    f"[{self.name}] PT_AOT=strict: dispatch at an "
                    f"un-warmed signature after seal() — the shape "
                    f"ladder must cover every runtime shape "
                    f"({aot.signature(args, kwargs)})")
        return self._jit(*args, **kwargs)

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def aot_compile(self, args, kwargs=None, cache=None):
        """AOT-compile at an abstract signature and install the
        executable; returns how it was satisfied.

        ``args``/``kwargs`` follow the call convention of ``__call__``
        with arrays replaced by ``jax.ShapeDtypeStruct`` leaves (static
        kwargs stay concrete python values).  Resolution order:

        * ``'warm'``    — already in this process's table
        * ``'disk'``    — deserialized from the persistent ``cache``
          (zero traces: the compile happened in an earlier process)
        * ``'compile'`` — lowered and compiled now (this traces the
          body ONCE, bumping ``traces`` — warmup cost, paid off-path)
        """
        from ..core import aot
        from ..testing import faults

        kwargs = dict(kwargs or {})
        sig = aot.signature(args, kwargs)
        if sig in self._exe:
            return "warm"
        key = cache.key(self.name, sig) if cache is not None else None
        if cache is not None:
            exe = cache.load(key, program=self.name)
            if exe is not None:
                self._exe[sig] = exe
                return "disk"
        t0 = time.perf_counter()
        faults.fire("aot.lower", "before")
        with warnings.catch_warnings():
            # AOT lowering of a donating program at SDS avals warns
            # that donated buffers are unused — expected: there are no
            # real buffers to donate at lowering time
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            lowered = self._jit.lower(*args, **kwargs)
            faults.fire("aot.lower", "after")
            faults.fire("aot.compile", "before")
            exe = lowered.compile()
        faults.fire("aot.compile", "after")
        secs = time.perf_counter() - t0
        self._exe[sig] = exe
        h = self._obs
        if h is not None:
            h.registry.histogram(
                "aot_compile_seconds",
                "AOT lower+compile wall seconds per program",
                labels=("program",)).labels(program=self.name).observe(
                secs)
            h.recorder.record("aot.compile", program=self.name,
                              seconds=round(secs, 4))
        if cache is not None:
            cache.store(key, exe, program=self.name, sig=sig)
        return "compile"

    def seal(self):
        """Forbid post-warmup misses (PT_AOT=strict): once sealed, a
        dispatch whose signature is not in the table raises AotMissError
        instead of tracing."""
        if not self._exe:
            raise ValueError(
                f"[{self.name}] seal() before any aot_compile(): a "
                f"sealed empty table would reject every dispatch")
        self._sealed = True

    def __repr__(self):
        return (f"CountedJit({self.name}, traces={self.traces}, "
                f"dispatches={self.dispatches})")


class DispatchAuditor:
    """Assert trace/dispatch counts of CountedJit programs over a block.

    ::

        with DispatchAuditor(ex.programs["verify"],
                             max_traces=max_seqs) as aud:
            eng.run()
        assert aud.dispatches == eng.metrics.spec_steps

    Exact expectations (``dispatches=``, ``traces=``) and ceilings
    (``max_dispatches=``, ``max_traces=``) are checked at block exit;
    a mismatch raises GraphContractError naming the program set.  The
    live ``dispatches``/``traces`` properties report the block's deltas
    for assertions that need runtime quantities (e.g. scheduler-step
    counts only known after the run).
    """

    def __init__(self, *programs, dispatches=None, max_dispatches=None,
                 traces=None, max_traces=None):
        if not programs:
            raise ValueError("DispatchAuditor needs at least one "
                             "CountedJit program")
        self.programs = programs
        self._expect = dict(dispatches=dispatches,
                            max_dispatches=max_dispatches,
                            traces=traces, max_traces=max_traces)
        self._t0 = self._d0 = 0

    def _sums(self):
        return (sum(p.traces for p in self.programs),
                sum(p.dispatches for p in self.programs))

    @property
    def traces(self):
        return self._sums()[0] - self._t0

    @property
    def dispatches(self):
        return self._sums()[1] - self._d0

    def expect(self, **kwargs):
        """Set/override expectations mid-block, for quantities only
        known after the audited work ran (they are enforced at exit)."""
        for k, v in kwargs.items():
            if k not in self._expect:
                raise TypeError(f"unknown expectation {k!r}")
            self._expect[k] = v

    def __enter__(self):
        self._t0, self._d0 = self._sums()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        names = ", ".join(p.name for p in self.programs)
        t, d = self.traces, self.dispatches
        e = self._expect
        if e["dispatches"] is not None and d != e["dispatches"]:
            raise GraphContractError(
                f"[{names}] dispatch audit: {d} dispatches in block, "
                f"expected exactly {e['dispatches']}")
        if e["max_dispatches"] is not None and d > e["max_dispatches"]:
            raise GraphContractError(
                f"[{names}] dispatch audit: {d} dispatches in block "
                f"exceed the ceiling {e['max_dispatches']} — a hidden "
                f"host loop is dispatching per item")
        if e["traces"] is not None and t != e["traces"]:
            raise GraphContractError(
                f"[{names}] retrace audit: {t} traces in block, "
                f"expected exactly {e['traces']}")
        if e["max_traces"] is not None and t > e["max_traces"]:
            raise GraphContractError(
                f"[{names}] retrace audit: {t} traces in block exceed "
                f"the ceiling {e['max_traces']} — shapes are churning "
                f"and every change recompiles")
        return False
