"""Runtime retrace/dispatch audit — the sixth check.

``CountedJit`` is a drop-in ``jax.jit`` replacement that counts how
many times the wrapped function was TRACED (re-traces mean shape churn)
and how many times it was DISPATCHED — replacing the hand-rolled
``verify_traces``/``verify_dispatches`` counters the serving executor
carried.  ``DispatchAuditor`` is the context manager that asserts the
counts over a block: an extra dispatch (a hidden host loop) or an extra
trace (a shape leak) raises :class:`GraphContractError`.
"""
from __future__ import annotations

import functools

import jax

from .. import obs
from .contract import GraphContractError


class CountedJit:
    """jax.jit wrapper with trace/dispatch counters.

    The trace counter is bumped by a host-side effect INSIDE the traced
    body (it runs once per trace, never per dispatch — the same trick
    the executor's verify program used); the dispatch counter is bumped
    per call.  ``fn`` exposes the unjitted callable for ProgramContract
    registration, so the lint path and the execution path share one
    function object.
    """

    def __init__(self, fn, *, name=None, donate_argnums=(),
                 static_argnames=(), **jit_kwargs):
        self.name = name or getattr(fn, "__name__", "program")
        self.traces = 0
        self.dispatches = 0
        self._fn = fn
        self.donate_argnums = tuple(donate_argnums)
        self._obs = obs.handle()

        @functools.wraps(fn)
        def counted(*args, **kwargs):
            self.traces += 1
            h = self._obs
            if h is not None:
                # a (re)trace is the compile event production debugging
                # cares about: journal it and count per program
                h.recorder.record("jit.trace", program=self.name,
                                  traces=self.traces)
                h.registry.counter(
                    "jit_traces_total",
                    "XLA traces (compiles/retraces) per program",
                    labels=("program",)).labels(program=self.name).inc()
            return fn(*args, **kwargs)

        self._jit = jax.jit(counted,
                            donate_argnums=self.donate_argnums,
                            static_argnames=tuple(static_argnames),
                            **jit_kwargs)

    @property
    def fn(self):
        return self._fn

    def __call__(self, *args, **kwargs):
        self.dispatches += 1
        h = self._obs
        if h is not None:
            h.registry.counter(
                "jit_dispatches_total",
                "Jitted program dispatches per program",
                labels=("program",)).labels(program=self.name).inc()
        return self._jit(*args, **kwargs)

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def __repr__(self):
        return (f"CountedJit({self.name}, traces={self.traces}, "
                f"dispatches={self.dispatches})")


class DispatchAuditor:
    """Assert trace/dispatch counts of CountedJit programs over a block.

    ::

        with DispatchAuditor(ex.programs["verify"],
                             max_traces=max_seqs) as aud:
            eng.run()
        assert aud.dispatches == eng.metrics.spec_steps

    Exact expectations (``dispatches=``, ``traces=``) and ceilings
    (``max_dispatches=``, ``max_traces=``) are checked at block exit;
    a mismatch raises GraphContractError naming the program set.  The
    live ``dispatches``/``traces`` properties report the block's deltas
    for assertions that need runtime quantities (e.g. scheduler-step
    counts only known after the run).
    """

    def __init__(self, *programs, dispatches=None, max_dispatches=None,
                 traces=None, max_traces=None):
        if not programs:
            raise ValueError("DispatchAuditor needs at least one "
                             "CountedJit program")
        self.programs = programs
        self._expect = dict(dispatches=dispatches,
                            max_dispatches=max_dispatches,
                            traces=traces, max_traces=max_traces)
        self._t0 = self._d0 = 0

    def _sums(self):
        return (sum(p.traces for p in self.programs),
                sum(p.dispatches for p in self.programs))

    @property
    def traces(self):
        return self._sums()[0] - self._t0

    @property
    def dispatches(self):
        return self._sums()[1] - self._d0

    def expect(self, **kwargs):
        """Set/override expectations mid-block, for quantities only
        known after the audited work ran (they are enforced at exit)."""
        for k, v in kwargs.items():
            if k not in self._expect:
                raise TypeError(f"unknown expectation {k!r}")
            self._expect[k] = v

    def __enter__(self):
        self._t0, self._d0 = self._sums()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        names = ", ".join(p.name for p in self.programs)
        t, d = self.traces, self.dispatches
        e = self._expect
        if e["dispatches"] is not None and d != e["dispatches"]:
            raise GraphContractError(
                f"[{names}] dispatch audit: {d} dispatches in block, "
                f"expected exactly {e['dispatches']}")
        if e["max_dispatches"] is not None and d > e["max_dispatches"]:
            raise GraphContractError(
                f"[{names}] dispatch audit: {d} dispatches in block "
                f"exceed the ceiling {e['max_dispatches']} — a hidden "
                f"host loop is dispatching per item")
        if e["traces"] is not None and t != e["traces"]:
            raise GraphContractError(
                f"[{names}] retrace audit: {t} traces in block, "
                f"expected exactly {e['traces']}")
        if e["max_traces"] is not None and t > e["max_traces"]:
            raise GraphContractError(
                f"[{names}] retrace audit: {t} traces in block exceed "
                f"the ceiling {e['max_traces']} — shapes are churning "
                f"and every change recompiles")
        return False
