"""Graph-contract linter — static analysis over jaxprs and lowered HLO.

The reference framework's static-graph stack runs IR passes and
verifiers over every program before execution (PIR pass infrastructure,
memory-optim passes).  The jax_graft analog: every hot program (the
compiled train step, the five serving executor programs, the fused-MoE
shard_map body) registers a :class:`ProgramContract` at build time, and
the linter walks the program's jaxpr — through ``pjit``/``scan``/
``custom_vjp``/``shard_map`` sub-jaxprs — evaluating pluggable
:class:`Check`s:

* **dense-materialization** — no intermediate larger than the
  contract's byte ceiling (generalizes the MoE dense-mask assertion);
* **host-sync** — no ``debug_callback``/``pure_callback``/infeed inside
  a step program;
* **donation-miss** — large inputs re-emitted as same-shaped outputs
  must be donated;
* **dtype-upcast** — no big f32 intermediates in bf16 programs;
* **collective audit** — exact all-to-all/psum equation inventory, so a
  refactor that silently adds a collective fails lint;
* **retrace/dispatch audit** — :class:`DispatchAuditor` over
  :class:`CountedJit` programs (the runtime-side sixth check).

``PT_LINT={off,warn,error}`` gates lint at registration time;
``make lint-graph`` (tools/lint_graph.py) lints every registered
program on CPU regardless of the gate.
"""
from .audit import CountedJit, DispatchAuditor  # noqa: F401
from .checks import (  # noqa: F401
    DEFAULT_CHECKS, Check, CollectiveAuditCheck, DenseMaterializationCheck,
    DonationMissCheck, DtypeUpcastCheck, HostSyncCheck,
)
from .contract import (  # noqa: F401
    GraphContractError, LintReport, ProgramContract, Violation,
)
from .cost import (  # noqa: F401
    CostReport, estimate_cost, estimate_fn_cost, transformer_flops_per_token,
)
from .registry import (  # noqa: F401
    aot_warmup, lint_all, lint_contract, lint_mode, lint_program,
    register_program, registered, unregister_program,
)
from . import walker  # noqa: F401
