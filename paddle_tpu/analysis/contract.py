"""ProgramContract — the declared invariants of one hot program.

A contract names a traceable callable, its example arguments (shapes
only — everything is reduced to ``jax.ShapeDtypeStruct`` before
tracing, so linting never touches device memory), and the invariants
the checks enforce.  Contracts hold their program WEAKLY: registering
the train step must not keep a dead trainer (and its parameter trees)
alive, so the registry drops entries whose program has been collected.
"""
from __future__ import annotations

import dataclasses
import functools
import inspect
import weakref
from typing import Any, Callable, Optional

import numpy as np


class GraphContractError(AssertionError):
    """A lint violation escalated to an error (PT_LINT=error, a failed
    DispatchAuditor block, or tools/lint_graph.py)."""


@dataclasses.dataclass
class Violation:
    program: str
    check: str
    message: str

    def __str__(self):
        return f"[{self.program}] {self.check}: {self.message}"


class LintReport:
    """Violations (and skipped programs) from one lint run."""

    def __init__(self):
        self.violations: list[Violation] = []
        self.linted: list[str] = []
        self.skipped: list[str] = []   # args not captured yet / fn dead

    @property
    def ok(self) -> bool:
        return not self.violations

    def merge(self, other: "LintReport"):
        self.violations.extend(other.violations)
        self.linted.extend(other.linted)
        self.skipped.extend(other.skipped)
        return self

    def __str__(self):
        lines = [f"graph lint: {len(self.linted)} program(s), "
                 f"{len(self.violations)} violation(s)"
                 + (f", {len(self.skipped)} skipped" if self.skipped
                    else "")]
        lines += [f"  {v}" for v in self.violations]
        return "\n".join(lines)


def _weak(fn):
    """Weak handle on a program callable; call it to resolve (None when
    the owner died)."""
    if inspect.ismethod(fn):
        return weakref.WeakMethod(fn)
    try:
        return weakref.ref(fn)
    except TypeError:  # builtins / partials: keep a strong ref
        return lambda: fn


def _to_sds(leaf):
    import jax

    if isinstance(leaf, jax.ShapeDtypeStruct):
        return leaf
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))
    return leaf  # python scalar: traced as a weak-typed constant


@dataclasses.dataclass
class ProgramContract:
    """Invariants for one program.

    ``args`` is a tuple of example arguments (arrays / ShapeDtypeStructs
    / pytrees of either) or a zero-arg callable returning one — the lazy
    form lets the train step register at build time and capture its
    batch shapes on the first real step (``None`` from the thunk means
    "not ready yet"; the program is reported as skipped).  ``kwargs``
    are static keywords (e.g. ``n=2`` for the multi-token decode).

    Check knobs (``None`` disables the corresponding check):

    * ``donate_argnums`` + ``donation_floor_bytes``: inputs >= the floor
      whose (shape, dtype) reappears as an output must be listed in
      ``donate_argnums``.
    * ``max_intermediate_bytes``: byte ceiling; any array in the jaxpr
      of at least this size is a dense-materialization violation.
    * ``compute_dtype``: when bf16/f16, f32 intermediates of at least
      ``f32_floor_bytes`` are dtype-upcast violations.
    * ``allow_host_sync``: permit callback/infeed primitives.
    * ``expected_collectives``: exact {collective: count} inventory
      ({} asserts a collective-free program).

    ``aot_hook`` is an optional zero-arg callable (held weakly, like
    ``fn``) that re-runs the owner's AOT warmup — checkpoint restore
    sweeps every registered hook via ``registry.aot_warmup()`` so a
    rolled-back replica resumes with warmed executables.
    """

    name: str
    fn: Callable
    args: Any = ()
    kwargs: Optional[dict] = None
    donate_argnums: tuple = ()
    donation_floor_bytes: int = 1024
    max_intermediate_bytes: Optional[int] = None
    compute_dtype: Any = None
    f32_floor_bytes: int = 1 << 20
    allow_host_sync: bool = False
    expected_collectives: Optional[dict] = None
    aot_hook: Any = None

    def __post_init__(self):
        self.donate_argnums = tuple(int(i) for i in self.donate_argnums)
        self._fn_ref = _weak(self.fn)
        self.fn = None  # weak only: the contract must not pin the owner
        self._aot_ref = (_weak(self.aot_hook)
                         if self.aot_hook is not None else None)
        self.aot_hook = None
        self._cost = None

    def resolve_fn(self):
        return self._fn_ref()

    def resolve_aot_hook(self):
        return self._aot_ref() if self._aot_ref is not None else None

    def example_args(self):
        """Concrete args -> ShapeDtypeStruct pytrees, or None when the
        lazy thunk has not captured shapes yet."""
        import jax

        args = self.args() if callable(self.args) else self.args
        if args is None:
            return None
        return tuple(jax.tree.map(_to_sds, a) for a in args)

    def make_jaxpr(self):
        """ClosedJaxpr of the program at the contract's shapes, or None
        when the fn is dead / args unavailable."""
        import jax

        fn = self.resolve_fn()
        if fn is None:
            return None
        args = self.example_args()
        if args is None:
            return None
        if self.kwargs:
            fn = functools.partial(fn, **self.kwargs)
        return jax.make_jaxpr(fn)(*args)

    def cost(self, refresh: bool = False):
        """Analytical :class:`~paddle_tpu.analysis.cost.CostReport` at
        the contract's shapes, cached after the first trace; None while
        the lazy args thunk has not captured shapes (ask again after the
        first real step) or once the program is dead."""
        if self._cost is not None and not refresh:
            return self._cost
        from .cost import estimate_cost

        jaxpr = self.make_jaxpr()
        if jaxpr is None:
            return None
        self._cost = estimate_cost(jaxpr)
        return self._cost

    def lower_text(self):
        """Lowered (StableHLO) text at the contract's shapes, for the
        HLO-level host-sync scan; None when unavailable."""
        import jax

        fn = self.resolve_fn()
        if fn is None:
            return None
        args = self.example_args()
        if args is None:
            return None
        if self.kwargs:
            fn = functools.partial(fn, **self.kwargs)
        return jax.jit(fn).lower(*args).as_text()

    def flat_input_layout(self):
        """(flat_avals, donated_flags): the jaxpr's flat input avals and
        which of them fall inside a donated top-level argument."""
        import jax

        args = self.example_args()
        if args is None:
            return None, None
        donated = set(self.donate_argnums)
        avals, flags = [], []
        for i, a in enumerate(args):
            leaves = jax.tree.leaves(a)
            avals.extend(leaves)
            flags.extend([i in donated] * len(leaves))
        return avals, flags
