from . import dtype, enforce, flags, place  # noqa: F401
from .dtype import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, convert_dtype, float16, float32,
    float64, float8_e4m3fn, float8_e5m2, get_default_dtype, int8, int16,
    int32, int64, set_default_dtype, uint8,
)
from .place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, Place, TPUPlace,
    XPUPlace, device_count, get_device, is_compiled_with_cuda, set_device,
)
from .tensor import EagerParamBase, Parameter, Tensor, to_tensor  # noqa: F401
