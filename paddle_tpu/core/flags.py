"""Global flag registry.

Reference: ``paddle/common/flags.cc`` (172 ``PHI_DEFINE_EXPORTED_*`` flags,
gflags-backed) exported to Python as ``paddle.set_flags/get_flags``
(``python/paddle/base/framework.py:111,136``), overridable by ``FLAGS_*``
environment variables.  Here the registry is pure Python: a typed flag table
with env-var pickup at definition time.
"""
from __future__ import annotations

import os
from typing import Any, Callable


class _Flag:
    __slots__ = ("name", "value", "default", "type", "doc")

    def __init__(self, name, default, type_, doc):
        self.name = name
        self.default = default
        self.type = type_
        self.doc = doc
        self.value = self._from_env(default)

    def _from_env(self, default):
        env = os.environ.get(self.name)
        if env is None:
            return default
        return _parse(env, self.type)


def _parse(text: str, type_: Callable):
    if type_ is bool:
        return text.strip().lower() in ("1", "true", "yes", "on")
    return type_(text)


_registry: dict[str, _Flag] = {}


def define_flag(name: str, default, doc: str = "", type_=None):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    if type_ is None:
        type_ = type(default)
    flag = _Flag(name, default, type_, doc)
    _registry[name] = flag
    return flag


def get_flags(flags) -> dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name if name.startswith("FLAGS_") else "FLAGS_" + name
        if key not in _registry:
            raise ValueError(f"Unknown flag {name!r}")
        out[name] = _registry[key].value
    return out


def set_flags(flags: dict):
    for name, value in flags.items():
        key = name if name.startswith("FLAGS_") else "FLAGS_" + name
        if key not in _registry:
            raise ValueError(f"Unknown flag {name!r}")
        f = _registry[key]
        f.value = _parse(value, f.type) if isinstance(value, str) else f.type(value)


def flag(name: str):
    """Fast read of a single flag value."""
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    return _registry[key].value


# --- Core flags mirrored from the reference flag table -----------------------
define_flag("FLAGS_check_nan_inf", False,
            "Check every op output for NaN/Inf (reference: common/flags.cc:72)")
define_flag("FLAGS_check_nan_inf_level", 0,
            "0: abort on nan/inf; 1: log only (reference: common/flags.cc:86)")
define_flag("FLAGS_benchmark", False, "Benchmark mode: sync after each op")
define_flag("FLAGS_eager_jit_ops", True,
            "Cache per-op jitted executables for eager dispatch")
define_flag("FLAGS_use_bf16_matmul", False,
            "Force bfloat16 accumulation inputs on matmul (AMP fast path)")
define_flag("FLAGS_log_level", 0, "VLOG-style verbosity for the framework")
define_flag("FLAGS_allocator_strategy", "auto_growth",
            "Kept for API parity; XLA/PJRT owns HBM allocation on TPU")
define_flag("FLAGS_embedding_deterministic", 0,
            "Deterministic embedding grad accumulation")
define_flag("FLAGS_cudnn_deterministic", False, "API parity; no-op on TPU")
define_flag("FLAGS_use_fused_rms_norm", False,
            "Route nn.functional.rms_norm through the fused Pallas kernel "
            "(ops/pallas_kernels/rms_norm.py) instead of the stock jnp op")
