"""Device / Place abstraction.

Reference: ``paddle/phi/common/place.h`` (Place/CPUPlace/GPUPlace/XPUPlace)
and ``python/paddle/device/__init__.py`` (set_device/get_device).  Here the
first-class accelerator is the TPU: ``TPUPlace(i)`` maps to ``jax.devices()[i]``.
XLA's CPU backend backs ``CPUPlace`` so every test can run device-free.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place. Equality is by (kind, device id)."""

    kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.kind, self._device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self._device_id})"

    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # Fall back to the default backend (e.g. CPUPlace when only TPU
            # or only CPU is present).
            devs = jax.devices()
        return devs[self._device_id % len(devs)]


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    kind = "tpu"


class CustomPlace(Place):
    """Custom-device plugin analog (reference: phi/backends/custom/)."""

    def __init__(self, dev_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.kind = dev_type


# GPU alias kept for API compatibility; resolves to whatever accelerator
# backend jax exposes (on this stack: TPU).
class CUDAPlace(TPUPlace):
    pass


CUDAPinnedPlace = CPUPlace
XPUPlace = TPUPlace


def _kind_of(dev) -> str:
    plat = dev.platform
    if plat in ("tpu", "axon"):
        return "tpu"
    return "cpu" if plat == "cpu" else plat


@functools.lru_cache(None)
def _accel_available() -> bool:
    return any(_kind_of(d) == "tpu" for d in jax.devices())


_current_place: Place | None = None


def set_device(device) -> Place:
    """paddle.device.set_device — accepts 'cpu', 'tpu', 'tpu:0', 'gpu:0', a Place."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return device
    name, _, idx = str(device).partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name == "cpu":
        _current_place = CPUPlace(idx)
    elif name in ("tpu", "gpu", "xpu", "cuda"):
        _current_place = TPUPlace(idx)
    else:
        _current_place = CustomPlace(name, idx)
    return _current_place


def get_device() -> str:
    p = _get_current_place()
    return f"{p.kind}:{p.get_device_id()}"


def _get_current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = TPUPlace(0) if _accel_available() else CPUPlace(0)
    return _current_place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return jax.device_count()
