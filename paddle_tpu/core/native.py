"""ctypes binding for the native core (csrc/common/paddle_tpu_native.cc).

Reference analog: the pybind layer (``fluid/pybind/pybind.cc:1091``) over
``paddle/common``.  pybind11 is not in this image, so the C ABI is loaded
with ctypes; the library builds on demand with g++ (cached next to csrc)
and every entry point has a pure-python fallback, so the package works on
machines without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_lib = None
_lock = threading.Lock()
_tried = False


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _build_and_load():
    src = os.path.join(_repo_root(), "csrc", "common",
                       "paddle_tpu_native.cc")
    if not os.path.exists(src):
        return None
    out_dir = os.path.join(_repo_root(), "csrc", "build")
    so = os.path.join(out_dir, "libpaddle_tpu_native.so")
    if not os.path.exists(so) or \
            os.path.getmtime(so) < os.path.getmtime(src):
        os.makedirs(out_dir, exist_ok=True)
        # Compile to a temp path + atomic rename: an interrupted or
        # concurrent build must never leave a corrupt .so at the final
        # path (the mtime check would then trust it forever).
        tmp = so + f".tmp.{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-Wall",
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, so)
        except FileNotFoundError:
            return None  # no toolchain: silent fallback is the contract
        except subprocess.CalledProcessError as e:
            import warnings

            # A broken build must not be silent — surface the compiler
            # diagnostics (fallbacks still engage).
            warnings.warn("paddle_tpu native build failed:\n"
                          + e.stderr.decode(errors="replace"))
            return None
        except Exception as e:
            import warnings

            warnings.warn(f"paddle_tpu native build failed: {e}")
            return None
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.ptn_version.restype = ctypes.c_int64
    if lib.ptn_version() < 2:
        return None
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.ptn_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.ptn_flag_get.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int)]
    lib.ptn_flag_get.restype = ctypes.c_double
    lib.ptn_ddim_product.argtypes = [i64p, ctypes.c_int64]
    lib.ptn_ddim_product.restype = ctypes.c_int64
    lib.ptn_ddim_strides.argtypes = [i64p, ctypes.c_int64, i64p]
    lib.ptn_ddim_strides.restype = ctypes.c_int64
    lib.ptn_ddim_slice.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64,
                                   ctypes.c_int64, i64p]
    lib.ptn_ddim_slice.restype = ctypes.c_int64
    lib.ptn_shuffle.argtypes = [i64p, ctypes.c_int64, ctypes.c_uint64]
    lib.ptn_pack_greedy.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64,
                                    i64p]
    lib.ptn_pack_greedy.restype = ctypes.c_int64
    lib.ptn_pack_ffd.argtypes = [i64p, i64p, ctypes.c_int64,
                                 ctypes.c_int64, i64p]
    lib.ptn_pack_ffd.restype = ctypes.c_int64
    lib.ptn_gather_rows.argtypes = [ctypes.c_char_p, ctypes.c_int64, i64p,
                                    ctypes.c_int64, ctypes.c_char_p]
    lib.ptn_fill_windows.argtypes = [i64p, i64p, i64p, ctypes.c_int64,
                                     ctypes.c_int64, ctypes.c_int64,
                                     ctypes.c_int64, i64p, i64p]
    lib.ptn_fill_windows.restype = ctypes.c_int64
    if lib.ptn_version() >= 3:
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.ptn_bpe_create.argtypes = [i32p, ctypes.c_int64, u8p, i64p,
                                       ctypes.c_int64]
        lib.ptn_bpe_create.restype = ctypes.c_void_p
        lib.ptn_bpe_free.argtypes = [ctypes.c_void_p]
        lib.ptn_bpe_encode_word.argtypes = [ctypes.c_void_p, u8p,
                                            ctypes.c_int64, i32p,
                                            ctypes.c_int64]
        lib.ptn_bpe_encode_word.restype = ctypes.c_int64
        lib.ptn_bpe_decode.argtypes = [ctypes.c_void_p, i32p,
                                       ctypes.c_int64, u8p,
                                       ctypes.c_int64]
        lib.ptn_bpe_decode.restype = ctypes.c_int64
    return lib


def get_lib():
    """The loaded native library, or None (fallbacks engage)."""
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _build_and_load()
    return _lib


def available() -> bool:
    return get_lib() is not None


# -- wrapped entry points (native when possible, numpy fallback) ------------

_py_flags: dict = {}


def flag_set(key, value):
    lib = get_lib()
    if lib is not None:
        lib.ptn_flag_set(key.encode(), float(value))
    else:
        _py_flags[key] = float(value)


def flag_get(key, default=None):
    lib = get_lib()
    if lib is not None:
        found = ctypes.c_int(0)
        v = lib.ptn_flag_get(key.encode(), ctypes.byref(found))
        return v if found.value else default
    return _py_flags.get(key, default)


def ddim_product(dims):
    dims = np.ascontiguousarray(dims, np.int64)
    lib = get_lib()
    if lib is not None:
        return int(lib.ptn_ddim_product(dims, len(dims)))
    return int(np.prod(dims, dtype=np.int64)) if len(dims) else 1


def ddim_strides(dims):
    dims = np.ascontiguousarray(dims, np.int64)
    lib = get_lib()
    if lib is not None:
        out = np.empty(len(dims), np.int64)
        if lib.ptn_ddim_strides(dims, len(dims), out) != 0:
            raise ValueError(f"rank {len(dims)} exceeds DDim::kMaxRank 9")
        return out
    if len(dims) > 9:
        raise ValueError(f"rank {len(dims)} exceeds DDim::kMaxRank 9")
    out = np.ones(len(dims), np.int64)
    for i in range(len(dims) - 2, -1, -1):
        out[i] = out[i + 1] * dims[i + 1]
    return out

def shuffle_indices(n, seed):
    idx = np.arange(n, dtype=np.int64)
    lib = get_lib()
    if lib is not None:
        lib.ptn_shuffle(idx, n, int(seed) & 0xFFFFFFFFFFFFFFFF)
        return idx
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    rng.shuffle(idx)
    return idx


def pack_greedy(lens, capacity):
    """bin id per doc (greedy sequential packing) + number of bins."""
    lens = np.ascontiguousarray(lens, np.int64)
    bins = np.empty(len(lens), np.int64)
    lib = get_lib()
    if lib is not None:
        n_bins = lib.ptn_pack_greedy(lens, len(lens), int(capacity), bins)
        if n_bins < 0:
            raise ValueError(f"bad capacity {capacity}")
        return bins, int(n_bins)
    if capacity <= 0:
        raise ValueError(f"bad capacity {capacity}")
    b, used = 0, 0
    for i, l in enumerate(lens):
        l = min(int(l), capacity)
        if used > 0 and used + l > capacity:
            b, used = b + 1, 0
        bins[i] = b
        used += l
    return bins, (b + 1 if len(lens) else 0)


def pack_ffd(lens, capacity):
    """First-fit-decreasing packing: bin id per doc + number of bins."""
    lens = np.ascontiguousarray(lens, np.int64)
    order = np.argsort(-lens, kind="stable").astype(np.int64)
    bins = np.empty(len(lens), np.int64)
    lib = get_lib()
    if lib is not None:
        n_bins = lib.ptn_pack_ffd(lens, order, len(lens), int(capacity),
                                  bins)
        if n_bins < 0:
            raise ValueError(f"bad capacity {capacity}")
        return bins, int(n_bins)
    if capacity <= 0:
        raise ValueError(f"bad capacity {capacity}")
    space = []
    for i in order:
        l = min(int(lens[i]), capacity)
        placed = next((b for b, s in enumerate(space) if s >= l), None)
        if placed is None:
            space.append(capacity)
            placed = len(space) - 1
        space[placed] -= l
        bins[i] = placed
    return bins, len(space)


def gather_rows(src, indices):
    """out[r] = src[indices[r]] — native memcpy collation when available."""
    src = np.ascontiguousarray(src)
    indices = np.ascontiguousarray(indices, np.int64)
    if len(indices) and (indices.min() < 0 or indices.max() >= len(src)):
        # The native loop is a raw memcpy — bounds-check here so native
        # and numpy paths fail identically.
        raise IndexError(
            f"gather_rows indices out of range [0, {len(src)})")
    lib = get_lib()
    if lib is None:
        return src[indices]
    out = np.empty((len(indices),) + src.shape[1:], src.dtype)
    row_bytes = src.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.ptn_gather_rows(src.ctypes.data_as(ctypes.c_char_p), row_bytes,
                        indices, len(indices),
                        out.ctypes.data_as(ctypes.c_char_p))
    return out


def fill_windows(tokens, offsets, bin_ids, n_bins, capacity, pad=0):
    """Pack concatenated docs into [n_bins, capacity] padded windows;
    returns (windows, used_per_bin)."""
    tokens = np.ascontiguousarray(tokens, np.int64)
    offsets = np.ascontiguousarray(offsets, np.int64)
    bin_ids = np.ascontiguousarray(bin_ids, np.int64)
    n = len(offsets) - 1
    out = np.empty((n_bins, capacity), np.int64)
    used = np.empty(n_bins, np.int64)
    lib = get_lib()
    if lib is not None:
        rc = lib.ptn_fill_windows(tokens, offsets, bin_ids, n, n_bins,
                                  capacity, pad, out, used)
        if rc != 0:
            raise ValueError("window overflow: bin assignment inconsistent")
        return out, used
    out[:] = pad
    used[:] = 0
    for i in range(n):
        b = int(bin_ids[i])
        seg = tokens[offsets[i]:offsets[i + 1]][:capacity]
        if used[b] + len(seg) > capacity:
            raise ValueError("window overflow: bin assignment inconsistent")
        out[b, used[b]:used[b] + len(seg)] = seg
        used[b] += len(seg)
    return out, used
