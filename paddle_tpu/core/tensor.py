"""The eager Tensor.

TPU-native re-design of the reference's Python-visible eager tensor:
``paddle::Tensor`` (``paddle/phi/api/include/tensor.h``) + the pybind method
surface (``paddle/fluid/pybind/eager_method.cc``) + the Python monkey-patch
layer (``python/paddle/base/dygraph/tensor_patch_methods.py``).

A Tensor wraps a ``jax.Array`` (HBM-resident PJRT buffer on TPU — the
DenseTensor analog) plus autograd metadata (``stop_gradient``, ``grad``,
creator ``GradNode``).  Under ``jax.jit`` tracing ``_data`` is a jax Tracer,
which is what lets the whole eager API be traced into one XLA program by
``paddle_tpu.jit.to_static``.

Most computational methods (``__add__``, ``sum``, ``reshape``...) are
installed by ``paddle_tpu.ops`` at import time — the same monkey-patch
pattern the reference uses (``tensor_patch_methods.py:262``).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .place import CPUPlace, Place, TPUPlace, _get_current_place


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_out_slot",
                 "name", "persistable", "_hooks", "trainable", "_dist_attr",
                 "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            data = jnp.asarray(data, dtype=dtype_mod.convert_dtype(dtype))
        elif dtype is not None and data.dtype != dtype_mod.convert_dtype(dtype):
            data = data.astype(dtype_mod.convert_dtype(dtype))
        if place is not None and isinstance(data, jax.Array):
            data = jax.device_put(data, place.jax_device())
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_slot = 0
        self.name = name or ""
        self.persistable = False
        self.trainable = True
        self._hooks = []
        self._dist_attr = None

    # -- metadata ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self) -> Place:
        if _is_tracer(self._data):
            return _get_current_place()
        dev = list(self._data.devices())[0]
        return TPUPlace(dev.id) if dev.platform in ("tpu", "axon") \
            else CPUPlace(dev.id)

    @property
    def is_leaf(self):
        return self._grad_node is None

    # -- conversion -------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __index__(self):
        return int(self.item())

    # -- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd import engine

        engine.run_backward([self],
                            [grad_tensor] if grad_tensor is not None else None,
                            retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(handle_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        # Differentiable copy (reference: assign op).
        from .. import ops

        return ops.assign(self)

    # -- sparse conversions (reference Tensor.to_sparse_coo/csr) ----------

    def to_sparse_coo(self, sparse_dim=None):
        from .. import sparse as _sp

        return _sp.dense_to_coo(self, sparse_dim)

    def to_sparse_csr(self):
        return self.to_sparse_coo().to_sparse_csr()

    # -- device movement --------------------------------------------------
    def to(self, *args, device=None, dtype=None, blocking=None, place=None):
        """Reference signature: Tensor.to(device=None, dtype=None,
        blocking=None) — positional args are classified; bools/None are
        ``blocking`` and never mistaken for a dtype."""
        for a in list(args) + [device]:
            if a is None or isinstance(a, bool):
                continue  # blocking flag or absent
            if isinstance(a, Place):
                place = a
            elif isinstance(a, str) and a.split(":")[0] in (
                    "cpu", "tpu", "gpu", "xpu", "cuda"):
                name, _, idx = a.partition(":")
                idx = int(idx) if idx else 0
                place = CPUPlace(idx) if name == "cpu" else TPUPlace(idx)
            elif dtype is None:
                dtype = a
        data = self._data
        if dtype is not None:
            data = data.astype(dtype_mod.convert_dtype(dtype))
        if place is not None:
            data = jax.device_put(data, place.jax_device())
        t = Tensor(data, stop_gradient=self.stop_gradient)
        return t

    def cpu(self):
        return self.to(CPUPlace(0))

    def cuda(self, device_id=0):
        return self.to(TPUPlace(device_id))

    def tpu(self, device_id=0):
        return self.to(TPUPlace(device_id))

    def pin_memory(self):
        return self

    # -- in-place value update (used by optimizers / load) ----------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, dtype=self.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}")
        self._data = value

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def _clear_data(self):
        self._data = None

    # -- repr -------------------------------------------------------------
    def __repr__(self):
        if _is_tracer(self._data):
            return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                    f"<traced>)")
        prefix = "Parameter" if isinstance(self, EagerParamBase) else "Tensor"
        return (f"{prefix}(shape={self.shape}, dtype={self.dtype}, "
                f"place={self.place}, stop_gradient={self.stop_gradient},\n"
                f"       {np.asarray(self._data)})")

    __str__ = __repr__

    # jax pytree interop: Tensors flatten to their data.
    def __jax_array__(self):
        return self._data


class EagerParamBase(Tensor):
    """Trainable parameter (reference: python/paddle/base/framework.py
    EagerParamBase; created by Layer.create_parameter)."""

    # __dict__ slot: parameters accept arbitrary user attributes
    # (is_sequence_parallel, is_firstly_shared, ... — paddle allows this).
    __slots__ = ("optimize_attr", "regularizer", "do_model_average",
                 "need_clip", "is_distributed", "__dict__")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False


Parameter = EagerParamBase


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py:673)."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, place=place,
                   stop_gradient=stop_gradient)
        return t
    if dtype is None and not isinstance(data, (jax.Array, np.ndarray)):
        # Match paddle: python floats default to the default dtype.
        probe = np.asarray(data)
        if probe.dtype == np.float64:
            dtype = dtype_mod.get_default_dtype()
        elif probe.dtype == np.int64:
            dtype = dtype_mod.int64
    if isinstance(data, np.ndarray) and data.dtype == np.float64 \
            and dtype is None:
        dtype = dtype_mod.get_default_dtype()
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
