"""Dtype system.

TPU-native re-design of the reference's ``phi::DataType`` enum
(``/root/reference/paddle/phi/common/data_type.h``) and the Python-level
dtype surface (``python/paddle/framework/dtype.py``).  We alias paddle-style
dtype names onto ``jax.numpy`` dtypes so everything interops with XLA with
zero conversion cost, and keep the reference's type-promotion semantics
(``paddle/phi/common/type_promotion.h``) via jax's numpy-compatible rules.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Paddle semantics require true int64 (labels, indices). jax truncates to
# int32 unless x64 is on; float defaults remain float32 because every
# creation path in this framework passes an explicit dtype.
jax.config.update("jax_enable_x64", True)

# Every Pallas call site traces under `with jax.enable_x64(False):`
# (Mosaic rejects i64 grid constants).  Newer jax removed the top-level
# alias, keeping only jax.experimental.enable_x64 — restore it so the
# kernel package works across the versions we run against.  This module
# is imported before any kernel module can be.
if not hasattr(jax, "enable_x64"):
    from jax.experimental import enable_x64 as _enable_x64

    jax.enable_x64 = _enable_x64

# Same story for shard_map: promoted to the jax namespace in newer
# releases, only jax.experimental.shard_map here — and the replication
# check kwarg is the old ``check_rep`` spelling, not ``check_vma``.
# The whole distributed stack (spmd.py, pipeline.py, ring_attention.py,
# mpu.py, moe_layer.py, cpp_extension.py) calls ``jax.shard_map`` with
# the new spelling.
if not hasattr(jax, "shard_map"):
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    jax.shard_map = _shard_map_compat

# Canonical dtype objects (numpy dtype instances — what jax uses natively).
bool_ = jnp.dtype("bool")
uint8 = jnp.dtype("uint8")
int8 = jnp.dtype("int8")
int16 = jnp.dtype("int16")
int32 = jnp.dtype("int32")
int64 = jnp.dtype("int64")
float16 = jnp.dtype("float16")
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype("float32")
float64 = jnp.dtype("float64")
complex64 = jnp.dtype("complex64")
complex128 = jnp.dtype("complex128")
float8_e4m3fn = jnp.dtype(jnp.float8_e4m3fn)
float8_e5m2 = jnp.dtype(jnp.float8_e5m2)

_STR_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalize any dtype spec (str / np / jnp / Tensor dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _STR_TO_DTYPE[dtype]
        except KeyError:
            raise TypeError(f"Unsupported dtype string: {dtype!r}")
    if isinstance(dtype, np.dtype):
        return dtype
    # python builtins / numpy scalar types / jnp types
    try:
        return jnp.dtype(dtype)
    except TypeError:
        raise TypeError(f"Cannot convert {dtype!r} to a dtype")


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return str(d)


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in _INTEGER or d == bool_


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX


# Default dtype handling (reference: paddle.set_default_dtype,
# python/paddle/framework/framework.py:36).
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            f"set_default_dtype only supports floating dtypes, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


# paddle.dtype — the dtype TYPE itself (reference framework/dtype.py
# exposes `paddle.dtype` as the class of dtype objects).
dtype = jnp.dtype
