"""AOT compilation plane — kill cold-start with warmed executables.

BENCH_r03/r04 record 18-492 s first-step compiles: fatal for elastic
serving (a preempted replica re-compiles the world before its first
token) and for the guardian rollback path.  The fix has three parts,
mirroring what the alpa/levanter-style JAX stacks do:

1. **AOT compile without real buffers** — ``CountedJit.aot_compile``
   (analysis/audit.py) drives ``jit(fn).lower(*ShapeDtypeStruct)
   .compile()`` and installs the resulting executable in a per-program
   table keyed by the abstract call signature; a dispatch whose
   signature hits the table runs the executable directly, so a warmed
   program NEVER re-traces.
2. **A persistent compile cache** — :class:`CompileCache` serializes
   executables (``jax.experimental.serialize_executable``) under a
   manifest keyed like the autotune cache keys tiles: (program,
   abstract shapes/dtypes, backend, device kind, jax/jaxlib version).
   A second process deserializes instead of compiling — zero traces,
   seconds instead of minutes.  Corrupt or version-skewed entries are
   dropped and recompiled, never a crash.
3. **A formal shape-bucket ladder** — :class:`BucketLadder` (powers of
   two by default) makes the runtime shape set finite: chunked prefill
   decomposes a prompt into descending ladder rungs, the past-KV cover
   pads to a bucketed page count (garbage masked by ``past_len``, so
   numerics are exact), and the decode-family batch sizes enumerate
   ``1..max_seqs``.  ``PagedExecutor.aot_warmup`` pre-compiles every
   (program x rung) pair at engine build, and ``CheckpointManager``
   restore invokes the same warmup so rollback resumes in seconds.

Gating: ``PT_AOT={off,warm,strict}``.  ``off`` (default) is bit-exact
r17 — no ladder, no table, no signature hashing on the dispatch path.
``warm`` pre-compiles and falls back to normal jit tracing on a miss.
``strict`` seals every program after warmup: a post-warmup miss raises
:class:`AotMissError` — the serving-fleet contract (a replica that
would silently compile mid-traffic must fail loudly instead).

Cache layout: ``PT_CACHE_DIR`` (default ``~/.cache/paddle_tpu``) is
the shared cache root (the autotune cache lives beside it);
``PT_COMPILE_CACHE`` (default ``<root>/compile``) holds
``manifest.json`` + one pickled serialized executable per entry, and
the XLA-level ``jax_compilation_cache_dir`` is pointed at an ``xla/``
subdir so both layers persist together.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import time

MODES = ("off", "warm", "strict")

#: manifest/entry schema version — bump on any layout change so stale
#: caches are dropped (never mis-deserialized).
CACHE_VERSION = 1


class AotMissError(RuntimeError):
    """A sealed (PT_AOT=strict) program was dispatched at a shape the
    warmup never compiled — the post-warmup-miss contract violation."""


def mode() -> str:
    m = os.environ.get("PT_AOT", "off").strip().lower()
    if m not in MODES:
        raise ValueError(f"PT_AOT must be one of {MODES}, got {m!r}")
    return m


def cache_root() -> str:
    """Shared on-disk cache root (``PT_CACHE_DIR``): the compile cache
    and the autotune cache both live under it."""
    return os.environ.get(
        "PT_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))


def compile_cache_dir() -> str:
    return os.environ.get("PT_COMPILE_CACHE",
                          os.path.join(cache_root(), "compile"))


# -- abstract call signature --------------------------------------------------

def signature(args, kwargs=None) -> str:
    """Deterministic string for one call's abstract signature: the
    pytree structure plus every leaf's (shape, dtype) — or ``repr`` for
    static python leaves.  Concrete arrays and the ShapeDtypeStructs
    the warmup lowers with produce the SAME string, which is what lets
    a warmed executable claim the real dispatch."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(
        (tuple(args), dict(kwargs or {})))
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{tuple(shape)}/{dtype}")
        else:
            parts.append(f"py:{leaf!r}")
    return f"{treedef}|{';'.join(parts)}"


# -- the shape-bucket ladder --------------------------------------------------

class BucketLadder:
    """Sorted positive rungs a runtime quantity is quantized onto.

    ``floor(n)`` (largest rung <= n) drives chunked prefill: taking the
    floor rung of the remaining prompt each step decomposes any length
    into descending rungs (for powers of two, its binary expansion), so
    every chunk the executor ever sees is a rung.  ``ceil(n)`` (smallest
    rung >= n) drives padding-style bucketing (the past-KV page cover).
    """

    def __init__(self, rungs):
        rungs = sorted({int(r) for r in rungs})
        if not rungs or rungs[0] < 1:
            raise ValueError(f"BucketLadder needs positive rungs, "
                             f"got {rungs}")
        self.rungs = tuple(rungs)

    @classmethod
    def pow2(cls, cap, lo=1) -> "BucketLadder":
        """Powers of two from ``lo`` up to (at most) ``cap``."""
        cap, r = int(cap), int(lo)
        if cap < r:
            raise ValueError(f"pow2 ladder cap {cap} < lo {lo}")
        rungs = []
        while r <= cap:
            rungs.append(r)
            r *= 2
        return cls(rungs)

    def floor(self, n):
        """Largest rung <= n, or None when n is below the ladder."""
        n = int(n)
        best = None
        for r in self.rungs:
            if r > n:
                break
            best = r
        return best

    def ceil(self, n):
        """Smallest rung >= n, or None when n is above the ladder."""
        n = int(n)
        for r in self.rungs:
            if r >= n:
                return r
        return None

    def chunks(self, total):
        """Descending rung decomposition of ``total`` — exactly the
        chunk sequence the scheduler produces for a prompt."""
        out, left = [], int(total)
        while left > 0:
            r = self.floor(left)
            if r is None:
                raise ValueError(
                    f"{left} is below the smallest rung "
                    f"{self.rungs[0]}")
            out.append(r)
            left -= r
        return out

    def __contains__(self, n):
        return int(n) in self.rungs

    def __repr__(self):
        return f"BucketLadder{self.rungs}"


def page_buckets(max_pages) -> tuple:
    """Past-KV page-cover buckets: 0 (no past), powers of two, and the
    per-seq page budget itself as the cap."""
    out, r = [0], 1
    while r < int(max_pages):
        out.append(r)
        r *= 2
    out.append(int(max_pages))
    return tuple(sorted(set(out)))


def bucket_pages(n, buckets):
    """Smallest bucket >= n (capped at the top bucket)."""
    n = int(n)
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


# -- the persistent executable cache -----------------------------------------

class CompileCache:
    """On-disk cache of serialized AOT executables + manifest.

    Layout: ``<dir>/manifest.json`` mapping key -> {program, file,
    bytes, version}; one ``aot-<key>.pkl`` per entry holding the
    serialized executable payload and its in/out pytree defs.  Keys
    hash (program name, abstract signature, backend, device kind,
    jax/jaxlib versions, CACHE_VERSION) — the autotune-cache discipline
    applied to executables.

    Every read path is crash-proof: an unreadable manifest, a missing
    or truncated entry file, a bit-flipped pickle, or a version-skewed
    entry is dropped (``errors`` bumped) and the caller recompiles.
    The ``aot.cache`` fault point brackets one entry load so the
    serviceability tests can inject exactly those failures.
    """

    def __init__(self, path=None, wire_xla=True):
        self.path = str(path) if path is not None else compile_cache_dir()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.errors = 0
        self.bytes_written = 0
        if wire_xla:
            # the XLA-level persistent cache rides along under xla/:
            # even a program compiled through plain jit (PT_AOT=off, or
            # a warm-mode miss) persists its HLO->binary step
            from ..utils import enable_compile_cache

            enable_compile_cache(
                cache_dir=os.path.join(self.path, "xla"))

    # -- keys ---------------------------------------------------------------

    @staticmethod
    def _versions():
        import jax
        import jaxlib

        try:
            backend = jax.default_backend()
            kind = jax.devices()[0].device_kind
        except Exception:  # pragma: no cover - no backend at all
            backend, kind = "none", "unknown"
        return (backend, kind, jax.__version__, jaxlib.__version__)

    def key(self, program: str, sig: str) -> str:
        raw = "|".join((program, sig) + self._versions()
                       + (f"v{CACHE_VERSION}",))
        return hashlib.sha256(raw.encode()).hexdigest()[:32]

    # -- manifest -----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.path, "manifest.json")

    def manifest(self) -> dict:
        """Parsed manifest ({} on any read problem); a version-skewed
        manifest is dropped wholesale — its entry files are unreadable
        by THIS build anyway."""
        try:
            with open(self._manifest_path()) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {"version": CACHE_VERSION, "entries": {}}
        if (not isinstance(doc, dict)
                or doc.get("version") != CACHE_VERSION
                or not isinstance(doc.get("entries"), dict)):
            self.errors += 1
            return {"version": CACHE_VERSION, "entries": {}}
        return doc

    def _write_manifest(self, mutate) -> None:
        """Read-merge-write under atomic rename (the autotune-cache
        discipline); losing a race costs one recompile somewhere."""
        try:
            os.makedirs(self.path, exist_ok=True)
            doc = self.manifest()
            mutate(doc["entries"])
            tmp = f"{self._manifest_path()}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self._manifest_path())
        except OSError:  # pragma: no cover - read-only FS etc.
            pass

    def drop(self, key: str) -> None:
        """Remove one (corrupt/stale) entry: manifest row + file."""
        entry = self.manifest()["entries"].get(key)
        self._write_manifest(lambda e: e.pop(key, None))
        if entry and isinstance(entry, dict) and entry.get("file"):
            try:
                os.unlink(os.path.join(self.path, entry["file"]))
            except OSError:
                pass

    # -- load / store -------------------------------------------------------

    def load(self, key: str, program: str = "?"):
        """Deserialize-and-load the cached executable for ``key``, or
        None on a miss.  EVERY failure mode — injected fault, torn
        file, bit rot, version skew — degrades to a miss (entry
        dropped) so the caller compiles fresh."""
        from ..testing import faults

        entry = self.manifest()["entries"].get(key)
        fpath = (os.path.join(self.path, entry["file"])
                 if isinstance(entry, dict) and entry.get("file")
                 else None)
        try:
            faults.fire("aot.cache", "before", path=fpath)
            if fpath is None or not os.path.isfile(fpath):
                raise FileNotFoundError(key)
            with open(fpath, "rb") as f:
                blob = pickle.load(f)
            if (not isinstance(blob, dict)
                    or blob.get("versions") != list(self._versions())
                    or blob.get("cache_version") != CACHE_VERSION):
                raise ValueError("compile-cache entry version skew")
            from jax.experimental.serialize_executable import (
                deserialize_and_load)

            exe = deserialize_and_load(blob["payload"], blob["in_tree"],
                                       blob["out_tree"])
            faults.fire("aot.cache", "after", path=fpath)
        except FileNotFoundError:
            self._count(program, hit=False)
            return None
        except Exception:
            # corrupt / truncated / injected: drop and recompile —
            # never a crash
            self.errors += 1
            if entry is not None:
                self.drop(key)
            self._count(program, hit=False)
            return None
        self._count(program, hit=True)
        return exe

    def store(self, key: str, exe, program: str = "?",
              sig: str = "") -> bool:
        """Serialize ``exe`` under ``key``; best-effort (False on any
        failure — persistence is an optimization, never a requirement).
        """
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(exe)
            blob = {"cache_version": CACHE_VERSION,
                    "versions": list(self._versions()),
                    "program": program,
                    "payload": payload,
                    "in_tree": in_tree, "out_tree": out_tree}
            os.makedirs(self.path, exist_ok=True)
            fname = f"aot-{key}.pkl"
            tmp = os.path.join(self.path, f"{fname}.{os.getpid()}.tmp")
            with open(tmp, "wb") as f:
                pickle.dump(blob, f)
            nbytes = os.path.getsize(tmp)
            os.replace(tmp, os.path.join(self.path, fname))
            self._write_manifest(lambda e: e.__setitem__(key, {
                "program": program, "file": fname, "bytes": nbytes,
                "sig": sig[:200], "version": CACHE_VERSION}))
            self.stores += 1
            self.bytes_written += nbytes
            return True
        except Exception:
            self.errors += 1
            return False

    # -- accounting ---------------------------------------------------------

    def _count(self, program, hit: bool) -> None:
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        from .. import obs

        h = obs.handle()
        if h is not None:
            name = ("aot_cache_hits_total" if hit
                    else "aot_cache_misses_total")
            h.registry.counter(
                name, "Persistent compile-cache "
                + ("hits" if hit else "misses") + " per program",
                labels=("program",)).labels(program=program).inc()
            ents = self.manifest()["entries"]
            h.registry.gauge(
                "aot_cache_entries",
                "Entries in the persistent compile cache").set(len(ents))
            h.registry.gauge(
                "aot_cache_bytes",
                "Total bytes of serialized executables on disk").set(
                sum(int(e.get("bytes", 0)) for e in ents.values()
                    if isinstance(e, dict)))

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def statusz(self) -> dict:
        """/statusz compile-cache provider payload."""
        ents = self.manifest()["entries"]
        by_prog: dict = {}
        for e in ents.values():
            if isinstance(e, dict):
                p = e.get("program", "?")
                by_prog[p] = by_prog.get(p, 0) + 1
        return {
            "dir": self.path,
            "entries": len(ents),
            "bytes": sum(int(e.get("bytes", 0)) for e in ents.values()
                         if isinstance(e, dict)),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate, 4),
            "stores": self.stores,
            "errors": self.errors,
            "programs": by_prog,
        }
