"""Error enforcement.

Reference: ``paddle/common/enforce.h`` — ``PADDLE_ENFORCE_*`` macros raising
typed errors with rich messages; error taxonomy in
``paddle/common/errors.h`` (InvalidArgument, NotFound, OutOfRange, ...).
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    pass


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


def enforce(cond, msg="", err_cls=InvalidArgumentError):
    if not cond:
        raise err_cls(msg() if callable(msg) else msg)


def enforce_eq(a, b, msg="", err_cls=InvalidArgumentError):
    if a != b:
        raise err_cls(f"{msg} (expected {a!r} == {b!r})")


def enforce_shape_match(shape_a, shape_b, msg=""):
    if tuple(shape_a) != tuple(shape_b):
        raise InvalidArgumentError(
            f"{msg}: shape mismatch {tuple(shape_a)} vs {tuple(shape_b)}")
