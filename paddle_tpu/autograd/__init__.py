"""paddle.autograd analog.

Reference: ``python/paddle/autograd/`` — backward(), grad(), no_grad,
PyLayer (``py_layer.py:280``), saved-tensor hooks.
"""
from . import engine  # noqa: F401
from .engine import (  # noqa: F401
    backward, enable_grad, grad, is_grad_enabled, no_grad,
    saved_tensors_hooks, set_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import hessian, jacobian, jvp, vjp  # noqa: F401
