"""Functional higher-order autograd: jacobian / hessian / vjp / jvp.

Reference: ``python/paddle/incubate/autograd/functional.py`` (jacobian,
hessian, vjp, jvp) and the prim/composite higher-order machinery
(``paddle/fluid/prim``).  TPU-native: higher-order differentiation is what
jax's functional transforms are built for — the Layer/Tensor function is
lifted to a pure jax function and jax.jacobian/jax.hessian/jax.vjp/jax.jvp
do the rest, composing to any order.
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor


def _lift(func):
    """Wrap a Tensor-function as a pure jax function."""

    def pure(*arrays):
        from . import engine

        with engine.no_grad():
            out = func(*[Tensor(a) for a in arrays])
        return jax.tree.map(
            lambda o: o._data if isinstance(o, Tensor) else o, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    return pure


def _datas(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [x._data if isinstance(x, Tensor) else x for x in xs]


def _wrap(tree):
    return jax.tree.map(Tensor, tree)


def vjp(func, xs, v=None):
    """(outputs, vjp_result): reverse-mode products.  Reference
    incubate/autograd/functional.py vjp."""
    datas = _datas(xs)
    out, vjp_fn = jax.vjp(_lift(func), *datas)
    if v is None:
        v = jax.tree.map(lambda o: jax.numpy.ones_like(o), out)
    else:
        v = jax.tree.map(
            lambda t: t._data if isinstance(t, Tensor) else t, v,
            is_leaf=lambda x: isinstance(x, Tensor))
    grads = vjp_fn(v)
    grads = grads[0] if len(datas) == 1 else list(grads)
    return _wrap(out), _wrap(grads)


def jvp(func, xs, v=None):
    """(outputs, jvp_result): forward-mode products."""
    datas = _datas(xs)
    if v is None:
        tangents = [jax.numpy.ones_like(d) for d in datas]
    else:
        vs = v if isinstance(v, (list, tuple)) else [v]
        tangents = [t._data if isinstance(t, Tensor) else t for t in vs]
    out, tangent_out = jax.jvp(_lift(func), tuple(datas), tuple(tangents))
    return _wrap(out), _wrap(tangent_out)


def jacobian(func, xs, create_graph=False):
    """Full Jacobian (reverse-mode).  For func: R^n -> R^m over a single
    input, returns [*out_shape, *in_shape]; multiple inputs return a
    tuple."""
    datas = _datas(xs)
    jac = jax.jacrev(_lift(func), argnums=tuple(range(len(datas))))(*datas)
    if len(datas) == 1:
        jac = jac[0] if isinstance(jac, tuple) else jac
    return _wrap(jac)


def hessian(func, xs, create_graph=False):
    """Hessian of a scalar-output function (forward-over-reverse)."""
    datas = _datas(xs)
    hes = jax.hessian(_lift(func), argnums=tuple(range(len(datas))))(
        *datas)
    if len(datas) == 1:
        hes = hes[0][0] if isinstance(hes, tuple) else hes
    return _wrap(hes)
