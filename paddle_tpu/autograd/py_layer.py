"""PyLayer — user-defined forward/backward pairs.

Reference: ``python/paddle/autograd/py_layer.py:280`` (PyLayer with
``forward``/``backward`` staticmethods and a context for saved tensors) +
the C++ side ``paddle/fluid/eager/pylayer/``.  The custom node plugs into
the same GradNode graph as built-in ops.
"""
from __future__ import annotations

from ..autograd import engine


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    # paddle exposes it as a method too
    def saved_tensor_list(self):
        return list(self._saved)


class _PyLayerNode(engine.GradNode):
    __slots__ = ("layer_cls", "ctx")

    def __init__(self, layer_cls, ctx, inputs):
        super().__init__(None, None, inputs, {})
        self.layer_cls = layer_cls
        self.ctx = ctx
        self.name = f"PyLayer<{layer_cls.__name__}>"

    def run_backward(self, grads_out):
        from ..core.tensor import Tensor
        import jax.numpy as jnp

        gs = []
        for i, g in enumerate(grads_out):
            if g is None and self.out_meta[i] is not None:
                shape, dtype = self.out_meta[i]
                g = jnp.zeros(shape, dtype)
            gs.append(Tensor(g, stop_gradient=True) if g is not None else None)
        with engine.no_grad():
            result = self.layer_cls.backward(
                self.ctx, *(gs if len(gs) > 1 else [gs[0]]))
        if not isinstance(result, (tuple, list)):
            result = (result,)
        grads = []
        for r in result:
            if r is None:
                grads.append(None)
            elif isinstance(r, Tensor):
                grads.append(r._data)
            else:
                grads.append(jnp.asarray(r))
        return list(grads) + [None] * (len(self.inputs) - len(grads))

    def release(self):
        pass  # PyLayer contexts own their saved tensors


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor

        ctx = PyLayerContext()
        with engine.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need_grad = engine.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if need_grad:
            node = _PyLayerNode(cls, ctx, args)
            bindable = [o if isinstance(o, Tensor) else None for o in outs]
            for o in bindable:
                if o is not None:
                    o.stop_gradient = False
            node.bind_outputs(bindable)
        return outs[0] if single else tuple(outs)


def once_differentiable(fn):
    return fn
