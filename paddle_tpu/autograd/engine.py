"""Tape-free reverse-mode autograd engine.

TPU-native re-design of the reference eager autograd stack:
``GradNodeBase`` (``paddle/fluid/eager/grad_node_info.h:197``),
``AutogradMeta`` (``autograd_meta.h:61``), the topological backward engine
(``paddle/fluid/eager/backward.cc:439`` — in-degree map + ready queue), the
``GradTensorHolder`` accumulation, and ``GeneralGrad`` partial gradients
(``general_grad.h``).

Autograd metadata lives directly on ``Tensor`` (``_grad_node``/``_out_slot``)
instead of a separate AutogradMeta object; GradNodes hold either explicit
residuals for ops with hand-written backward kernels (the reference's
backward.yaml pairing) or a ``jax.vjp`` closure as the fallback.  All
gradient arithmetic is jax — a backward pass over the graph is a sequence of
XLA executable calls, and the engine also works under ``jax.jit`` tracing
(used by ``paddle_tpu.jit.to_static``).
"""
from __future__ import annotations

from collections import defaultdict, deque

import jax.numpy as jnp

# --------------------------------------------------------------------------
# Grad mode (reference: eager/api/utils/global_utils.h Controller;
# python/paddle/base/dygraph/base.py no_grad_)
# --------------------------------------------------------------------------

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


class no_grad:
    """Context manager + decorator disabling gradient recording."""

    _target = False

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = self._target
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with type(self)():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad(no_grad):
    _target = True


class set_grad_enabled(no_grad):
    def __init__(self, mode: bool):
        self._target = bool(mode)


# --------------------------------------------------------------------------
# Grad graph nodes
# --------------------------------------------------------------------------

class saved_tensors_hooks:
    """Pack/unpack hooks over residuals saved for backward (reference
    ``python/paddle/autograd/saved_tensors_hooks.py:20``): ``pack_hook``
    runs on every tensor a GradNode saves (offload to host/disk),
    ``unpack_hook`` reloads it when backward consumes the node.  Ops that
    fall back to a jax vjp closure keep their residuals inside the closure
    and are not intercepted (XLA owns that memory)."""

    _active = None  # (pack_hook, unpack_hook) | None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._prev = saved_tensors_hooks._active
        saved_tensors_hooks._active = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = self._prev
        return False


class _Packed:
    """Marker holding a pack_hook payload (distinguishes packed array
    leaves from pass-through non-tensor residuals at unpack time)."""

    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload


def _pack_saved(saved):
    """Apply the active pack hook to each array leaf of a residual tree;
    returns (packed_tree, unpack_hook) or (saved, None) when inactive."""
    hooks = saved_tensors_hooks._active
    if hooks is None or saved is None:
        return saved, None
    pack, unpack = hooks
    from ..core.tensor import Tensor

    def _pack_leaf(v):
        if isinstance(v, jnp.ndarray) or (hasattr(v, "dtype")
                                          and hasattr(v, "shape")):
            return _Packed(pack(Tensor(jnp.asarray(v))))
        return v

    import jax

    packed = jax.tree_util.tree_map(
        _pack_leaf, saved, is_leaf=lambda x: not isinstance(
            x, (list, tuple, dict)))
    return packed, unpack


def _unpack_saved(saved, unpack):
    if unpack is None:
        return saved
    from ..core.tensor import Tensor

    def _unpack_leaf(v):
        if not isinstance(v, _Packed):
            return v
        out = unpack(v.payload)
        return out._data if isinstance(out, Tensor) else jnp.asarray(out)

    import jax

    return jax.tree_util.tree_map(
        _unpack_leaf, saved,
        is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))


class GradNode:
    """One backward step; created per differentiable forward op call.

    Reference: GradNodeBase (grad_node_info.h:197).  ``saved`` is either the
    op's explicit residuals (hand-written bwd) or a jax vjp closure.
    """

    __slots__ = ("op", "saved", "inputs", "attrs", "vjp_fallback",
                 "diff_idx", "out_meta", "n_outs", "name", "released",
                 "out_hooks", "unpack_hook")

    def __init__(self, op, saved, inputs, attrs, vjp_fallback=False,
                 diff_idx=None):
        self.released = False
        self.op = op
        self.name = op.name if op is not None else "custom"
        self.unpack_hook = None
        if not vjp_fallback:
            saved, self.unpack_hook = _pack_saved(saved)
        self.saved = saved
        self.inputs = list(inputs)  # Tensor | raw array per forward slot
        self.attrs = attrs
        self.vjp_fallback = vjp_fallback
        self.diff_idx = diff_idx
        self.out_meta = None  # [(shape, dtype)] per output slot
        self.n_outs = 0
        self.out_hooks = None  # live per-slot hook lists (Tensor._hooks)

    def bind_outputs(self, outs):
        self.n_outs = len(outs)
        self.out_meta = [
            (tuple(o.shape), o.dtype) if o is not None else None for o in outs
        ]
        self.out_hooks = [o._hooks if o is not None else None for o in outs]
        for i, o in enumerate(outs):
            if o is not None:
                o._grad_node = self
                o._out_slot = i

    def parent_edges(self):
        """Yield ("node", i, parent_node, parent_slot) for inputs produced by
        another node, ("leaf", i, tensor, None) for grad-requiring leaves."""
        from ..core.tensor import Tensor

        for i, t in enumerate(self.inputs):
            if isinstance(t, Tensor) and not t.stop_gradient:
                if t._grad_node is not None:
                    yield ("node", i, t._grad_node, t._out_slot)
                else:
                    yield ("leaf", i, t, None)

    def run_backward(self, grads_out):
        """grads_out: list (len n_outs) of arrays/None -> grads per input."""
        if self.released:
            raise RuntimeError(
                f"Trying to backward through {self.name} a second time, but "
                "the saved intermediate results have already been freed. "
                "Specify retain_graph=True on the first backward.")
        filled = []
        for i, g in enumerate(grads_out):
            if g is None:
                shape, dtype = self.out_meta[i]
                g = jnp.zeros(shape, dtype)
            filled.append(g)

        if self.vjp_fallback:
            cotangent = filled[0] if self.n_outs == 1 else tuple(filled)
            diff_grads = self.saved(cotangent)
            grads = [None] * len(self.inputs)
            for idx, g in zip(self.diff_idx, diff_grads):
                grads[idx] = g
            return grads

        gout = filled[0] if self.n_outs == 1 else tuple(filled)
        saved = _unpack_saved(self.saved, self.unpack_hook)
        grads = self.op.jit_bwd(saved, gout, **self.attrs)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        return list(grads) + [None] * (len(self.inputs) - len(grads))

    def release(self):
        """Free residuals (retain_graph=False semantics)."""
        self.saved = None
        self.released = True

    def __repr__(self):
        return f"GradNode<{self.name}>"


# --------------------------------------------------------------------------
# Backward traversal (reference: eager/backward.cc:439 Backward())
# --------------------------------------------------------------------------

def _reachable_graph(root_nodes, needed=None):
    """BFS over parent edges; returns {id: node} and consumer in-degree map.
    When ``needed`` is given (GeneralGrad pruning), edges to nodes outside
    it are ignored.  Reference: getInDegreeMap (backward.cc:23)."""
    nodes = {id(n): n for n in root_nodes}
    indeg = defaultdict(int)
    queue = deque(root_nodes)
    while queue:
        node = queue.popleft()
        for kind, _i, parent, _slot in node.parent_edges():
            if kind != "node":
                continue
            if needed is not None and id(parent) not in needed:
                continue
            indeg[id(parent)] += 1
            if id(parent) not in nodes:
                nodes[id(parent)] = parent
                queue.append(parent)
    return nodes, indeg


def _mark_needed(root_nodes, slot_targets, leaf_target_ids):
    """Subset of nodes that can reach a target (GeneralGrad's pruned
    subgraph, eager/general_grad.h).  Iterative post-order DFS."""
    needed: dict[int, bool] = {}

    def compute(start):
        stack = [(start, False)]
        while stack:
            node, expanded = stack.pop()
            if id(node) in needed and not expanded:
                continue
            if not expanded:
                needed.setdefault(id(node), False)
                stack.append((node, True))
                for kind, _i, obj, _slot in node.parent_edges():
                    if kind == "node" and id(obj) not in needed:
                        stack.append((obj, False))
            else:
                res = any((id(node), s) in slot_targets
                          for s in range(node.n_outs))
                if not res:
                    for kind, _i, obj, _slot in node.parent_edges():
                        if kind == "leaf" and id(obj) in leaf_target_ids:
                            res = True
                            break
                        if kind == "node" and needed.get(id(obj), False):
                            res = True
                            break
                needed[id(node)] = res

    for n in root_nodes:
        compute(n)
    return {k for k, v in needed.items() if v}


def _node_backward_recorded(node, grads_out):
    """create_graph=True step: compute this node's input grads THROUGH
    the op registry (a recompute-based VJP grad-op, registry.grad_op),
    so the backward computation itself is recorded on the tape and
    supports another backward.  Reference: eager double grad
    (general_grad.h + backward.yaml *_double_grad pairs)."""
    from ..core.tensor import Tensor
    from ..ops import registry

    op = node.op
    if op is None or getattr(op, "fn", None) is None:
        raise NotImplementedError(
            f"create_graph=True cannot differentiate through "
            f"'{node.name}': the node has no re-traceable forward "
            "(PyLayer/compiled custom nodes); wrap that region in "
            "autograd.functional (jax.grad) instead")
    nondiff = getattr(op, "nondiff_argnums", frozenset())
    diff_idx = tuple(
        i for i, t in enumerate(node.inputs)
        if isinstance(t, Tensor)
        and not t.stop_gradient
        and i not in nondiff
        and jnp.issubdtype(t._data.dtype, jnp.inexact))
    if not diff_idx:
        return [None] * len(node.inputs)
    gop = registry.grad_op(op, node.attrs, node.n_outs, diff_idx,
                           len(node.inputs))
    outs = registry.apply(gop, *(list(grads_out) + list(node.inputs)))
    if not isinstance(outs, tuple):
        outs = (outs,)
    grads = [None] * len(node.inputs)
    for j, g in zip(diff_idx, outs):
        grads[j] = g
    return grads


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 targets=None, accumulate_into_grad=True,
                 create_graph=False):
    """Core engine used by Tensor.backward() and paddle.grad().

    Accumulates into leaf ``.grad`` (unless accumulate_into_grad=False);
    if ``targets`` given, additionally captures and returns grads flowing
    through those tensors (leaf or intermediate) as {id(tensor): array}.

    Hooks (Tensor.register_hook) fire once per backward on the fully
    accumulated gradient of the tensor — for intermediates when their
    producing node's cotangent is finalized, for leaves after all
    contributions are summed (reference: GradNodeBase gradient hooks).
    """
    from ..core.tensor import Tensor

    tensors = [t for t in tensors if isinstance(t, Tensor)]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    targets = targets or []
    # Map (node id, slot) -> target tensor ids, for intermediate capture.
    slot_targets = defaultdict(list)
    leaf_targets = {}
    for t in targets:
        if t._grad_node is not None:
            slot_targets[(id(t._grad_node), t._out_slot)].append(id(t))
        else:
            leaf_targets[id(t)] = t
    captured: dict[int, object] = {}

    root_nodes = []
    node_grads: dict[int, list] = {}
    leaf_buf: dict[int, list] = {}  # id(tensor) -> [tensor, grad]

    def leaf_acc(tensor, g):
        entry = leaf_buf.get(id(tensor))
        if entry is None:
            leaf_buf[id(tensor)] = [tensor, g]
        else:
            entry[1] = entry[1] + g

    def seed(node, slot, g):
        if id(node) not in node_grads:
            node_grads[id(node)] = [None] * node.n_outs
            root_nodes.append(node)
        slots = node_grads[id(node)]
        slots[slot] = g if slots[slot] is None else slots[slot] + g

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t.shape, t.dtype)
            if create_graph:
                g = Tensor(g, stop_gradient=True)
        elif create_graph:
            # Keep Tensor cotangents as-is — a graph-carrying seed makes
            # the returned grads differentiable w.r.t. it too.
            g = g if isinstance(g, Tensor) \
                else Tensor(jnp.asarray(g), stop_gradient=True)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if t._grad_node is None:
            leaf_acc(t, g)
        else:
            seed(t._grad_node, t._out_slot, g)

    # GeneralGrad pruning: for pure grad queries, restrict traversal to the
    # subgraph between outputs and targets.
    needed = None
    if targets and not accumulate_into_grad:
        needed = _mark_needed(root_nodes, slot_targets, set(leaf_targets))
        root_nodes = [n for n in root_nodes if id(n) in needed]

    nodes, indeg = _reachable_graph(root_nodes, needed)
    ready = deque(n for n in root_nodes if indeg[id(n)] == 0)
    processed = set()

    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        grads_out = node_grads.pop(id(node), [None] * node.n_outs)

        # Finalized cotangents for this node's outputs: apply output-tensor
        # hooks once, then capture intermediate targets.
        for slot in range(node.n_outs):
            g = grads_out[slot]
            if g is None:
                continue
            hooks = node.out_hooks[slot] if node.out_hooks else None
            if hooks:
                for hook in hooks:
                    out = hook(g if isinstance(g, Tensor)
                               else Tensor(g, stop_gradient=True))
                    if out is not None:
                        g = out if create_graph and isinstance(out, Tensor) \
                            else (out._data if isinstance(out, Tensor)
                                  else out)
                grads_out[slot] = g
            key = (id(node), slot)
            if key in slot_targets:
                for tid in slot_targets[key]:
                    captured[tid] = _acc(captured.get(tid), g)

        if create_graph:
            filled = []
            for slot in range(node.n_outs):
                g = grads_out[slot]
                if g is None:
                    shape, dtype = node.out_meta[slot]
                    g = Tensor(jnp.zeros(shape, dtype),
                               stop_gradient=True)
                filled.append(g)
            grads_in = _node_backward_recorded(node, filled)
        else:
            grads_in = node.run_backward(grads_out)

        for kind, i, obj, slot in node.parent_edges():
            g = grads_in[i]
            if kind == "leaf":
                if g is not None:
                    leaf_acc(obj, g)
            else:
                parent = obj
                if needed is not None and id(parent) not in needed:
                    continue  # pruned branch
                if g is not None:
                    if id(parent) not in node_grads:
                        node_grads[id(parent)] = [None] * parent.n_outs
                    slots = node_grads[id(parent)]
                    slots[slot] = g if slots[slot] is None \
                        else slots[slot] + g
                # The in-degree must drop even for None grads, or the
                # parent (and everything above it) never processes.
                indeg[id(parent)] -= 1
                if indeg[id(parent)] <= 0:
                    ready.append(parent)
        if not retain_graph:
            node.release()

    # Leaf finalization: hooks on the accumulated grad, then .grad write.
    for tid, (tensor, g) in leaf_buf.items():
        if tensor._hooks:
            for hook in tensor._hooks:
                out = hook(g if isinstance(g, Tensor)
                           else Tensor(g, stop_gradient=True))
                if out is not None:
                    g = out if create_graph and isinstance(out, Tensor) \
                        else (out._data if isinstance(out, Tensor) else out)
        if tid in leaf_targets:
            captured[tid] = _acc(captured.get(tid), g)
        if accumulate_into_grad:
            _leaf_write(tensor, g._data if isinstance(g, Tensor) else g)

    return captured


def _acc(old, g):
    return g if old is None else old + g


def _leaf_write(tensor, g):
    from ..core.tensor import Tensor

    new = g if tensor.grad is None else tensor.grad._data + g
    tensor.grad = Tensor(new, stop_gradient=True)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward — accumulate into .grad."""
    run_backward(tensors, grad_tensors, retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad (GeneralGrad analog, eager/general_grad.h).

    Returns grads of ``outputs`` w.r.t. ``inputs`` without touching .grad.
    """
    from ..core.tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        # paddle semantics: retain the graph when building a new one.
        retain_graph = bool(create_graph)

    captured = run_backward(outputs, grad_outputs,
                            retain_graph=retain_graph, targets=inputs,
                            accumulate_into_grad=False,
                            create_graph=create_graph)
    results = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True to return None for it")
            results.append(None)
        elif isinstance(g, Tensor):
            # create_graph path: the grad carries its own graph and can
            # be differentiated again (reference double-grad contract).
            results.append(g)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results
